"""Bit-exact incident replay (ISSUE 11 tentpole, part c).

``python -m paddle_tpu.observability.replay <journal>`` rebuilds the
recorded serve — engines, scheduler or fleet router, prefix caches,
fault injector, the full arrival trace — from the journal's header,
re-runs it with the RECORDED clock fed back through ``journal.now()``,
and diffs the replayed decision + token stream against the journal:
either certifying identity or reporting the first divergence as
``(seq, kind, field, recorded, replayed)``.

Why this is bit-exact rather than best-effort: every serving decision
is a pure function of (the seeded trace, engine/scheduler state, and
the decision-clock reads). The journal records all three — the trace
and state in the header, the clock reads as ``clock`` records — so the
replay is immune to replay-machine timing: XLA compiles, container
load and host jitter change nothing, because the replayed loop never
looks at the real clock. Divergence therefore means exactly one of

* the journal was tampered with / corrupted (the mutated-journal test),
* the code running the replay differs from the code that recorded
  (a real regression-localisation signal: the first diverging record
  names the first decision the new code makes differently), or
* non-recorded state leaked into a decision (a bug in the recorder —
  the replay-identity tests in tests/test_journal.py exist to keep the
  recorded-state set complete).

What replay does NOT need: the recording's wall-clock budget (a 60 s
incident replays in seconds — device work is the only real cost) or
its monitors (SLO/perf monitors are observers, not deciders; their
``slo_alert`` events are journaled but outside the diffed decision
set). What it DOES need: the same model params — pass them in-process
(``replay_serve(path, params=...)``), or record
``Journal.params_info = {"prng_seed": s}`` so the CLI can rebuild them.

Limits (documented, enforced with clear errors): mesh-sharded (mp)
engines need the recording topology's devices — the CLI refuses rather
than mis-replaying; a serve that started from pre-warmed caches or
live slots replays from the recorded header state only (the standard
lane/test flow — warm pass, reset, measured serve — is exactly that).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import journal as _journal
from . import metrics as _metrics
from .journal import (DECISION_KINDS, Journal, JournalError, read_journal,
                      sections)

__all__ = ["ReplayResult", "rebuild", "rebuild_params", "replay_serve",
           "diff_decisions", "main"]

# journal bookkeeping fields never compared: wall stamps and sequence
# counters differ by construction (the replay interleaves non-decision
# records — cold_start, recompiles — differently than the recording)
_IGNORED_FIELDS = frozenset({"t", "gseq", "seq", "v"})


@dataclasses.dataclass
class ReplayResult:
    identical: bool
    n_decisions: int               # recorded decision records diffed
    n_replayed: int
    divergence: Optional[dict]     # first (seq, kind, field) mismatch
    error: Optional[str] = None    # control-flow divergence (clock feed)
    driver: Optional[str] = None
    report: Optional[object] = None   # the replayed OnlineReport/FleetReport

    def as_dict(self) -> dict:
        return {"identical": self.identical,
                "n_decisions": self.n_decisions,
                "n_replayed": self.n_replayed,
                "divergence": self.divergence,
                "error": self.error,
                "driver": self.driver}


# --- header -> live objects ------------------------------------------------

def _cfg_from(d: dict):
    import jax.numpy as jnp

    from ..models import llama

    d = dict(d)
    d["dtype"] = getattr(jnp, d["dtype"])
    return llama.LlamaConfig(**d)


def rebuild_params(header: dict, cfg=None):
    """Model params from the header's ``params`` info (a PRNG seed) —
    the CLI path. In-process callers usually pass params directly."""
    info = header.get("params") or {}
    if "prng_seed" not in info:
        raise JournalError(
            "journal header carries no params provenance — set "
            "Journal.params_info = {'prng_seed': s} when recording, or "
            "replay in-process with replay_serve(..., params=params)")
    import jax

    from ..models import llama

    cfg = cfg if cfg is not None else _cfg_from(header["llama"])
    return llama.init_params(cfg, jax.random.PRNGKey(
        int(info["prng_seed"])))


def _engine_from(d: dict, cfg, params):
    from ..inference.serving import ServingEngine

    if d.get("mesh"):
        raise JournalError(
            f"recorded engine is mesh-sharded over {d['mesh']} — replay "
            f"needs the recording topology's devices; rebuild the mesh "
            f"and engines yourself, then drive rebuild() manually")
    kw: Dict[str, Any] = dict(
        slots=d["slots"], max_len=d["max_len"], chunk=d["chunk"],
        prompt_buckets=tuple(d["prompt_buckets"]),
        eos_token_id=d["eos_token_id"], paged=d["paged"],
        chunked_prefill=d["chunked_prefill"],
        prefill_chunks=tuple(d["prefill_chunks"]),
        speculative=d["speculative"], sampling=d["sampling"],
        sample_seed=d["sample_seed"],
        quality_digest=d.get("quality_digest", False),
        digest_top_k=d.get("digest_top_k", 4),
        # r21: the engine re-quantizes the fp params in __init__, so a
        # recorded quantized serve rebuilds from the SAME fp tree
        quant=d.get("quant"),
        # r23: long-context geometry (absent in pre-r23 journals)
        seq_parallel=d.get("seq_parallel", 0),
        long_buckets=tuple(d.get("long_buckets") or ()))
    if d["paged"]:
        kw["page_size"] = d["page_size"]
        kw["num_pages"] = d["num_pages"]
    eng = ServingEngine(cfg, params, **kw)
    # mutable state the serve started from: rid offsets feed sampling
    # seeds and class-order keys; the acceptance EWMA feeds shed math
    eng._next_rid = int(d["next_rid"])
    eng.spec_accept_ewma = float(d["spec_accept_ewma"])
    return eng


def _prefix_cache_from(d: Optional[dict], engine):
    if d is None:
        return None
    from ..inference.prefix_cache import PagedPrefixCache, PrefixCache

    if d["kind"] == "paged":
        host_tier = None
        if d.get("host_tier_pages"):
            # r19: the spill tier decides restores/spills — rebuild it
            # at the recorded capacity so tier_transfer records replay
            from ..inference.kv_tiers import HostTier

            host_tier = HostTier(engine.pager,
                                 capacity_pages=d["host_tier_pages"])
        return PagedPrefixCache(engine.pager,
                                capacity_pages=d["capacity_pages"],
                                host_tier=host_tier)
    return PrefixCache(block=d["block"],
                       capacity_tokens=d["capacity_tokens"])


def _injector_from(d: Optional[dict]):
    if d is None:
        return None
    from ..inference.fleet import FaultInjector

    inj = FaultInjector(
        crash={int(k): int(v) for k, v in (d.get("crash") or {}).items()},
        hang={int(k): tuple(v) for k, v in (d.get("hang") or {}).items()},
        recover_after=d.get("recover_after", 1),
        seed=d.get("seed", 0), crash_p=d.get("crash_p", 0.0))
    for _ in range(int(d.get("draws", 0))):
        inj._rng.rand()            # fast-forward the consumed draws
    return inj


def _trace_from(header: dict):
    from ..inference.scheduler import Arrival

    return [Arrival(a["at"], np.asarray(a["prompt"], np.int32),
                    a["gen"], priority=a.get("priority", 0),
                    deadline_s=a.get("deadline_s"))
            for a in header["trace"]]


def rebuild(header: dict, params):
    """(driver, trace): the serve topology the header describes, built
    fresh — an ``OnlineScheduler``/``SLOScheduler`` over one engine, or
    a ``FleetRouter`` over N replicas with per-replica caches and the
    fault injector's recorded schedule."""
    from ..inference.fleet import FleetRouter
    from ..inference.scheduler import OnlineScheduler, SLOScheduler

    cfg = _cfg_from(header["llama"])
    trace = _trace_from(header)
    driver = header["driver"]
    engines = [_engine_from(d, cfg, params) for d in header["engines"]]
    if driver in ("fleet", "disagg"):
        fk = header["fleet"]
        pcs = [_prefix_cache_from(d, e)
               for d, e in zip(header["prefix_caches"], engines)]
        # r17: a canary is a routing DECIDER — rebuild it from its
        # recorded config (assign() is a pure seeded draw and the
        # latency verdicts re-derive from the fed clock, so holds
        # replay bit-exactly). A quality-linked canary's holds depend
        # on shadow-diff state replay does not rebuild: refuse loudly.
        canary = None
        ck = header.get("canary")
        if ck is not None:
            if ck.get("quality_linked"):
                raise JournalError(
                    "recorded canary was linked to a live quality "
                    "monitor — its hold decisions depend on shadow-"
                    "diff state the replay does not rebuild; replay "
                    "latency-only canaries, or drive rebuild() "
                    "yourself with the shadow re-attached")
            from .quality import CanaryController

            canary = CanaryController(
                ck["replica"], weight=ck["weight"], seed=ck["seed"],
                latency_ratio_max=ck["latency_ratio_max"],
                min_outcomes=ck["min_outcomes"],
                verdict_every=ck["verdict_every"])
        kw = dict(
            max_queue=fk["max_queue"], seg_steps=fk["seg_steps"],
            affinity_block=fk["affinity_block"],
            segment_timeout_s=fk["segment_timeout_s"],
            max_finish_retries=fk["max_finish_retries"],
            max_requeues=fk["max_requeues"],
            fault_injector=_injector_from(header.get("fault")),
            probe_after_s=fk["probe_after_s"],
            directory=bool(fk.get("directory", False)))
        # r25 (ISSUE 20): the autoscaler is a DECIDER — rebuild the
        # policies AND their input monitors from the recorded configs
        # so the elastic control loop re-derives every scale decision
        # from the fed clock + event stream (absent section: pre-r25
        # journal, nothing to rebuild)
        ak = header.get("autoscaler")
        if ak is not None:
            from ..inference.autoscaler import Autoscaler

            kw["autoscaler"] = [Autoscaler.from_description(p)
                                for p in ak["policies"]]
            if ak.get("slo") is not None:
                from .slo import SLOMonitor

                kw["slo_monitor"] = SLOMonitor.from_description(
                    ak["slo"])
            if ak.get("capacity") is not None:
                from .capacity import CapacityMonitor

                kw["capacity_monitor"] = CapacityMonitor \
                    .from_description(ak["capacity"])
        if driver == "disagg":
            # r22: the disaggregated fleet rebuilds from the header
            # alone — pool role per replica (index order is
            # prefill-first, the DisaggRouter construction order) plus
            # each pool's segment budget; the per-pool envelopes
            # re-derive from the rebuilt engines' geometry
            from ..inference.disagg import DisaggRouter

            pools = header["pools"]
            dk = header["disagg"]
            pre = [i for i, p in enumerate(pools) if p == "prefill"]
            dec = [i for i, p in enumerate(pools) if p == "decode"]
            if pre + dec != list(range(len(pools))):
                raise JournalError(
                    f"disagg header pools not prefill-first: {pools}")
            router = DisaggRouter(
                [engines[i] for i in pre], [engines[i] for i in dec],
                prefill_caches=[pcs[i] for i in pre],
                decode_caches=[pcs[i] for i in dec],
                prefill_seg_steps=dk["prefill_seg_steps"],
                decode_seg_steps=dk["decode_seg_steps"], **kw)
        else:
            router = FleetRouter(
                engines,
                prefix_caches=(pcs if any(p is not None for p in pcs)
                               else None),
                canary=canary, **kw)
        router._next_rid = int(fk.get("next_rid", 0))
        return router, trace
    sk = header["scheduler"]
    cls = SLOScheduler if driver == "slo" else OnlineScheduler
    kw: Dict[str, Any] = dict(max_queue=sk["max_queue"],
                              seg_steps=sk["seg_steps"])
    if driver == "slo":
        kw["preempt"] = sk["preempt"]
        kw["shed_deadlines"] = sk["shed_deadlines"]
    sched = cls(engines[0],
                prefix_cache=_prefix_cache_from(
                    header.get("prefix_cache"), engines[0]), **kw)
    # measured-state carry-over: the service-rate EWMAs a warm pass (or
    # earlier traffic) left behind are shed-decision inputs
    sched._per_tick_s = float(sk.get("per_tick_s", 0.0))
    if driver == "slo":
        sched._per_token_s = float(sk.get("per_token_s", 0.0))
    return sched, trace


# --- the diff --------------------------------------------------------------

def _decision_stream(records: Sequence[dict]) -> List[dict]:
    # r17: shadow-marked records (mirrored segments, quality compares,
    # shadow drain clock reads) are journaled losslessly but sit OFF
    # the decision stream — the shadow is an observer, and a serve must
    # replay identically whether or not one was attached (the replay
    # does not rebuild the shadow; see fleet.Shadow)
    return [r for r in records
            if r["kind"] in DECISION_KINDS and not r.get("shadow")]


def diff_decisions(recorded: Sequence[dict],
                   replayed: Sequence[dict]) -> Optional[dict]:
    """First divergence between two decision streams, or None when they
    are identical. Compared field-by-field (everything but wall stamps
    and sequence counters), so the report names the exact decision and
    the exact field that first went a different way."""
    n = min(len(recorded), len(replayed))
    for i in range(n):
        a, b = recorded[i], replayed[i]
        fields = (["kind"] if a["kind"] != b["kind"]
                  else sorted((set(a) | set(b)) - _IGNORED_FIELDS))
        for k in fields:
            if a.get(k) != b.get(k):
                return {"index": i, "seq": a.get("seq"),
                        "rank": a.get("rank"), "kind": a["kind"],
                        "field": k, "recorded": a.get(k),
                        "replayed": b.get(k)}
    if len(recorded) != len(replayed):
        tail = recorded[n] if len(recorded) > n else replayed[n]
        return {"index": n, "seq": tail.get("seq"),
                "rank": tail.get("rank"), "kind": tail.get("kind"),
                "field": "stream_length", "recorded": len(recorded),
                "replayed": len(replayed)}
    return None


# --- the replay ------------------------------------------------------------

def replay_serve(source, params=None, section: int = -1) -> ReplayResult:
    """Replay one recorded serve and diff it against the journal.

    ``source``: a journal directory/file path, a ``read_journal``
    result, or a raw record list. ``section`` picks which serve when
    the journal holds several (a ``warm=True`` pass records its own);
    the default ``-1`` is the LAST — the measured pass. ``params``:
    the model weights (rebuilt from the header's ``prng_seed`` when
    omitted).

    The replay runs inside a scratch metrics registry (its counters
    must not pollute the live process) with an in-memory scratch
    journal attached and the recorded clock fed back; the returned
    ``ReplayResult`` certifies identity or carries the first
    divergence."""
    if isinstance(source, str):
        records = read_journal(source)["records"]
    elif isinstance(source, dict):
        records = source["records"]
    else:
        records = list(source)
    secs = [s for s in sections(records) if s["header"] is not None]
    if not secs:
        raise JournalError("journal holds no serve header — nothing to "
                           "replay")
    sec = secs[section]
    header, sec_records = sec["header"], sec["records"]
    if params is None:
        params = rebuild_params(header)
    driver, trace = rebuild(header, params)
    clock = [r["c"] for r in sec_records if r["kind"] == "clock"]
    scratch = Journal()                      # in-memory
    error = None
    report = None
    prev_enabled = _metrics.set_enabled(
        bool(header.get("telemetry_enabled", True)))
    try:
        with _metrics.scoped_registry(_metrics.Registry()):
            with _journal.attach(scratch):
                try:
                    with _journal.feed_clock(clock):
                        report = driver.serve(trace)
                except JournalError as e:
                    error = str(e)           # control flow diverged
                except AssertionError as e:
                    error = f"replay invariant failed: {e}"
    finally:
        _metrics.set_enabled(prev_enabled)
    rec_dec = _decision_stream(sec_records)
    rep_dec = _decision_stream(scratch.records())
    div = diff_decisions(rec_dec, rep_dec)
    return ReplayResult(identical=div is None and error is None,
                        n_decisions=len(rec_dec),
                        n_replayed=len(rep_dec), divergence=div,
                        error=error, driver=header["driver"],
                        report=report)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.observability.replay",
        description="Re-execute a recorded serve and certify the "
                    "decision + token stream bit-identical (or report "
                    "the first divergence).")
    ap.add_argument("journal", help="journal directory (or one rank file)")
    ap.add_argument("--section", type=int, default=-1,
                    help="which recorded serve (default: last)")
    ap.add_argument("--params-seed", type=int, default=None,
                    help="override the header's params PRNG seed")
    ap.add_argument("--json", default=None, help="write the result JSON")
    ap.add_argument("--journey", type=int, default=None, metavar="RID",
                    help="also print request RID's journey")
    args = ap.parse_args(argv)

    merged = read_journal(args.journal)
    if merged.get("skipped_files"):
        print(f"warning: skipped corrupt rank files: "
              f"{merged['skipped_files']}")
    params = None
    if args.params_seed is not None:
        secs = [s for s in sections(merged["records"])
                if s["header"] is not None]
        hdr = dict(secs[args.section]["header"])
        hdr["params"] = {"prng_seed": args.params_seed}
        params = rebuild_params(hdr)
    res = replay_serve(merged, params=params, section=args.section)
    if args.journey is not None:
        j = _journal.request_journey(merged["records"], args.journey)
        print(f"journey rid={args.journey}: kinds={j['kinds']} "
              f"replicas={j['replicas']} tokens={j['n_tokens']}")
    if res.identical:
        print(f"REPLAY IDENTICAL: {res.n_decisions} decision records "
              f"(driver={res.driver}) reproduced bit-exactly")
    else:
        print("REPLAY DIVERGED:")
        if res.error:
            print(f"  control flow: {res.error}")
        if res.divergence:
            d = res.divergence
            print(f"  first divergence at decision #{d['index']} "
                  f"(rank {d['rank']} seq {d['seq']}): kind={d['kind']} "
                  f"field={d['field']}\n"
                  f"    recorded: {d['recorded']}\n"
                  f"    replayed: {d['replayed']}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res.as_dict(), f, indent=1, default=str)
    return 0 if res.identical else 1


if __name__ == "__main__":
    sys.exit(main())

"""paddle_tpu.parallel — the TPU-native SPMD substrate.

This is the functional core that the ``paddle.distributed`` compatibility
surface (fleet, meta_parallel, sharding) is built on. Reference counterpart:
the C++ distributed core (``paddle/fluid/distributed/collective/``,
``paddle/phi/core/distributed/auto_parallel/``; SURVEY.md §2.2) — but
designed mesh-first: process groups are mesh axes, collectives are XLA HLO
ops scheduled by the compiler over ICI, and parallelism strategies are
sharding rules over one ``jax.sharding.Mesh``.
"""

from .mesh import (
    HYBRID_AXES,
    create_hybrid_mesh,
    get_mesh,
    mesh_axis_size,
    host_to_global,
    named_sharding,
    set_mesh,
    shard_map_compat,
    with_sharding_constraint,
)

__all__ = [
    "HYBRID_AXES",
    "create_hybrid_mesh",
    "get_mesh",
    "set_mesh",
    "shard_map_compat",
    "mesh_axis_size",
    "named_sharding",
    "host_to_global",
    "with_sharding_constraint",
]

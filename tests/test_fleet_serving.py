"""Fleet serving subsystem (r12 tentpole): router determinism, prefix
affinity (a hot prefix must route BACK to the replica whose cache holds
it), least-loaded fallback under skew, fleet backpressure accounting
(fleet counter == sum of replica counters), the one-sync-per-segment
audit over the whole fleet serve loop, rank-merged telemetry, and mp=2
tensor-parallel segment token parity vs the single-device reference
(dense AND paged) — the multi-chip serving acceptance tests, runnable
on the virtual-CPU multi-device platform and on chip."""

import numpy as np
import pytest

import jax

from paddle_tpu.inference.fleet import FleetRouter, build_fleet
from paddle_tpu.inference.scheduler import (Arrival, OnlineScheduler,
                                            poisson_arrivals)
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import llama
from paddle_tpu.parallel import set_mesh


def _dense_reference(cfg, params, prompt, n):
    out = llama.generate(params, np.asarray(prompt, np.int32)[None], cfg,
                         max_new_tokens=n, max_len=96)
    return [int(t) for t in np.asarray(out)[0]]


def _burst(reqs):
    """Every arrival due at t=0: routing then depends only on the event
    stream, never the wall clock — the determinism contract's regime."""
    return [Arrival(0.0, p, n) for p, n in reqs]


def _mixed_reqs(seed, n, cfg, lens=(5, 12, 8, 20, 3, 15, 7, 9),
                gens=(9, 6, 12, 4, 8, 5, 10, 7)):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, cfg.vocab_size, (lens[i % len(lens)],)
                         ).astype(np.int32), gens[i % len(gens)])
            for i in range(n)]


class TestFleetRouter:
    def test_determinism_and_token_identity(self, tiny_llama):
        """Same burst trace + same fleet -> identical per-replica
        assignment and identical tokens across serves; every request's
        tokens == dense generate() (greedy is placement-independent)."""
        set_mesh(None)
        cfg, params = tiny_llama
        reqs = _mixed_reqs(21, 6, cfg)
        refs = [_dense_reference(cfg, params, p, n) for p, n in reqs]
        router = FleetRouter(build_fleet(cfg, params, 2, slots=2,
                                         max_len=96,
                                         prompt_buckets=(8, 16, 32)),
                             max_queue=8, seg_steps=8)
        rep1 = router.serve(_burst(reqs))
        out1, asg1 = router.results(), router.assignment()
        router.reset()
        rep2 = router.serve(_burst(reqs))
        out2, asg2 = router.results(), router.assignment()
        assert asg1 == asg2 and out1 == out2
        assert sorted(len(a) for a in asg1) != [0, 6], \
            "router sent everything to one replica on a spread workload"
        for rid, ref in zip(sorted(out1), refs):
            assert out1[rid] == ref, (rid, out1[rid], ref)
        assert rep1.n_requests == rep2.n_requests == 6
        assert rep1.segments > 0 and rep1.ticks > 0

    def test_least_loaded_fallback_under_skew(self, tiny_llama):
        """A deliberately skewed trace — every prompt carries the SAME
        affinity prefix — must spill past the preferred replica's full
        queue to the least-loaded one instead of stalling."""
        set_mesh(None)
        cfg, params = tiny_llama
        rng = np.random.RandomState(31)
        shared = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        reqs = [(np.concatenate([shared, rng.randint(
            0, cfg.vocab_size, (4,)).astype(np.int32)]), 5)
            for _ in range(6)]
        refs = [_dense_reference(cfg, params, p, n) for p, n in reqs]
        # caches on: affinity is cache-gated (without them the router
        # is least-loaded only — a prompt-hash pin would be pure load
        # imbalance); block 16 so the 16-token shared head is the key
        router = FleetRouter(build_fleet(cfg, params, 2, slots=2,
                                         max_len=96,
                                         prompt_buckets=(8, 16, 32)),
                             max_queue=2, seg_steps=8,
                             prefix_caches="auto", affinity_block=16)
        rep = router.serve(_burst(reqs))
        out = router.results()
        for rid, ref in zip(sorted(out), refs):
            assert out[rid] == ref
        assert rep.dispatches_affinity > 0
        assert rep.dispatches_least_loaded > 0, \
            "skewed trace never spilled to the least-loaded replica"
        assert all(len(a) > 0 for a in router.assignment()), \
            "fallback left a replica idle under a full preferred queue"

    def test_fleet_backpressure_counter_is_sum_of_replicas(self,
                                                           tiny_llama):
        """When every replica's queue is full the arrival stays client-
        side and the refusal is billed to exactly one replica — the
        fleet counter is definitionally the replica sum, and everything
        still serves once queues drain."""
        set_mesh(None)
        cfg, params = tiny_llama
        reqs = _mixed_reqs(33, 6, cfg, lens=(6,), gens=(6,))
        router = FleetRouter(build_fleet(cfg, params, 2, slots=1,
                                         max_len=96, prompt_buckets=(8,)),
                             max_queue=1, seg_steps=8)
        rep = router.serve(_burst(reqs))
        assert rep.backpressure_events > 0
        assert rep.backpressure_events == sum(
            r["backpressure_events"] for r in rep.per_replica)
        assert rep.n_requests == 6 and len(router.results()) == 6

    def test_affinity_prefix_never_misses_across_replicas(self,
                                                          tiny_llama):
        """THE affinity contract: once a prefix is hot in replica A's
        cache, every later request sharing it routes back to A and HITS
        — round-robin to B would silently re-prefill (the r12 fleet-
        isolation bug class this router exists to prevent)."""
        set_mesh(None)
        cfg, params = tiny_llama
        rng = np.random.RandomState(41)
        groups = [rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
                  for _ in range(2)]

        def wave(seed, per_group):
            r = np.random.RandomState(seed)
            return [(np.concatenate([g, r.randint(
                0, cfg.vocab_size, (6,)).astype(np.int32)]), 5)
                for g in groups for _ in range(per_group)]

        router = FleetRouter(build_fleet(cfg, params, 2, slots=2,
                                         max_len=96,
                                         prompt_buckets=(8, 16, 64)),
                             max_queue=8, seg_steps=16,
                             prefix_caches="auto")
        wave1, wave2 = wave(43, 1), wave(44, 3)
        router.serve(_burst(wave1))     # cold: populates per-replica caches
        router.serve(_burst(wave2))     # hot: must route back + hit
        out = router.results()
        for rid, (p, n) in zip(sorted(out), wave1 + wave2):
            assert out[rid] == _dense_reference(cfg, params, p, n)
        wave2_reqs = [router._reqs[rid][1]
                      for rid in sorted(router._reqs)[len(wave1):]]
        assert all(r.prefix_hit_len >= 32 for r in wave2_reqs), \
            [r.prefix_hit_len for r in wave2_reqs]
        hits = sum(rr.prefix_cache.hits for rr in router._replicas)
        assert hits >= len(wave2)
        assert sum(rr.dispatches["affinity"]
                   for rr in router._replicas) == len(wave1) + len(wave2)

    def test_one_sync_per_segment_over_fleet_loop(self, tiny_llama):
        """The r7/r9/r11 audit contract survives the fleet: the whole
        N-replica serve loop performs exactly ONE allowed device->host
        sync per segment (each replica's event fetch), zero flagged —
        routing, stamping and per-replica telemetry are host arithmetic."""
        from paddle_tpu.analysis import syncs

        set_mesh(None)
        cfg, params = tiny_llama
        reqs = _mixed_reqs(51, 6, cfg)
        router = FleetRouter(build_fleet(cfg, params, 2, slots=2,
                                         max_len=96,
                                         prompt_buckets=(8, 16, 32)),
                             max_queue=8, seg_steps=8)
        router.serve(_burst(reqs))          # warm: compiles + first fetch
        router.reset()
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            rep = router.serve(_burst(reqs))
        flagged = sa.flagged("replay")
        assert flagged == [], [f"{e.kind}@{e.site}" for e in flagged]
        allowed = sa.allowed("replay")
        assert set(allowed) == {"serving.segment_event_fetch"}
        assert allowed["serving.segment_event_fetch"] == rep.segments

    def test_paged_fleet_leak_report_aggregates(self, tiny_llama):
        """Per-replica paged pools audit through ONE fleet-level
        leak_report (replica-tagged); a page pinned outside its own
        engine's accounting is named with its replica."""
        set_mesh(None)
        cfg, params = tiny_llama
        reqs = _mixed_reqs(61, 4, cfg)
        router = FleetRouter(build_fleet(cfg, params, 2, slots=2,
                                         max_len=96,
                                         prompt_buckets=(8, 16, 32),
                                         paged=True, page_size=16),
                             max_queue=8, seg_steps=8,
                             prefix_caches="auto")
        router.serve(_burst(reqs))
        out = router.results()
        for rid, (p, n) in zip(sorted(out), reqs):
            assert out[rid] == _dense_reference(cfg, params, p, n)
        assert router.leak_report() == []
        # inject a stray ref on replica 1's pool: the aggregate must
        # name the replica
        pgr = router._replicas[1].engine.pager
        page = pgr.allocator.alloc(1)
        bad = router.leak_report()
        assert bad and all(b.startswith("replica 1:") for b in bad), bad
        pgr.allocator.release(page)
        assert router.leak_report() == []

    def test_merged_telemetry_ranks(self, tiny_llama, tmp_path):
        """Replica registries merge through the EXISTING rank machinery:
        one telemetry_rank<i>.json per replica, counters summed, gauges
        kept by rank."""
        set_mesh(None)
        cfg, params = tiny_llama
        reqs = _mixed_reqs(71, 4, cfg)
        router = FleetRouter(build_fleet(cfg, params, 2, slots=2,
                                         max_len=96,
                                         prompt_buckets=(8, 16, 32)),
                             max_queue=8, seg_steps=8)
        rep = router.serve(_burst(reqs))
        merged = router.merged_telemetry(str(tmp_path))
        assert merged["ranks"] == [0, 1]
        assert merged["counters"]["serving.segments"]["value"] == \
            rep.segments
        assert merged["counters"]["serving.tokens_generated"]["value"] == \
            rep.total_tokens
        by_rank = merged["gauges"]["fleet.replica_queue_depth"]["by_rank"]
        assert set(by_rank) == {"0", "1"}

    def test_prefix_cache_engine_keying_enforced(self, tiny_llama):
        """Fleet isolation: a paged replica handed a cache wrapping a
        DIFFERENT engine's pager fails loudly at construction (sharing
        would retain pages of the wrong pool)."""
        from paddle_tpu.inference.prefix_cache import make_prefix_cache

        set_mesh(None)
        cfg, params = tiny_llama
        engines = build_fleet(cfg, params, 2, slots=2, max_len=96,
                              prompt_buckets=(8, 16, 32), paged=True,
                              page_size=16)
        wrong = [make_prefix_cache(engines[1]), make_prefix_cache(
            engines[0])]
        with pytest.raises(ValueError, match="ITS OWN"):
            FleetRouter(engines, prefix_caches=wrong)


class TestTensorParallelServing:
    """Acceptance: mp=2 serving segments (dense and paged) are token-
    identical to the single-device reference on the r7-style Poisson
    workload, under the forced multi-device CPU platform (conftest's
    8-device recipe; skips on a single real chip)."""

    @pytest.fixture()
    def mp2(self, tiny_llama):
        if len(jax.devices()) < 2:
            pytest.skip("tensor-parallel parity needs >= 2 devices")
        from paddle_tpu.parallel.mesh import create_hybrid_mesh

        set_mesh(None)
        mesh = create_hybrid_mesh(mp=2, devices=jax.devices()[:2],
                                  set_as_global=False)
        set_mesh(None)
        return mesh

    def test_mp2_dense_scheduler_token_parity(self, tiny_llama, mp2):
        """The online scheduler over an mp=2 engine serves the seeded
        Poisson trace token-identical to dense generate() — and the
        engine restores the global mesh (no leak into other tests)."""
        from paddle_tpu.parallel.mesh import get_mesh

        cfg, params = tiny_llama
        arr = poisson_arrivals(31, 6, 1e4, cfg.vocab_size,
                               prompt_lens=(5, 11, 23),
                               gen_lens=(3, 7, 11))
        eng = ServingEngine(cfg, params, slots=2, max_len=96,
                            prompt_buckets=(8, 16, 32), mesh=mp2)
        sch = OnlineScheduler(eng, seg_steps=8)
        rep = sch.serve(arr)
        out = sch.results()
        assert rep.n_requests == len(arr) == len(out)
        for a, rid in zip(sorted(arr, key=lambda x: x.t), sorted(out)):
            ref = _dense_reference(cfg, params, a.prompt, a.max_new_tokens)
            assert out[rid] == ref, (rid, out[rid], ref)
        assert get_mesh() is None

    def test_mp2_paged_segment_token_parity(self, tiny_llama, mp2):
        """Paged mp=2: page tables stay replicated host data while the
        pool shards on heads — token parity + zero page leaks."""
        cfg, params = tiny_llama
        reqs = _mixed_reqs(23, 5, cfg)
        eng = ServingEngine(cfg, params, slots=2, max_len=96,
                            prompt_buckets=(8, 16, 32), mesh=mp2,
                            paged=True, page_size=16)
        rids = [eng.add_request(p, n) for p, n in reqs]
        while eng._queue or eng.free_slot_count() < eng.slots:
            eng.run_segment(8)
        out = eng.collect_finished()
        for rid, (p, n) in zip(rids, reqs):
            assert out[rid] == _dense_reference(cfg, params, p, n)
        assert eng.pager.leak_report() == []

    def test_mp2_fleet_of_sharded_replicas(self, tiny_llama, mp2):
        """Composability: the DP router over TP-sharded replicas (the
        engines x chips product) — both layers at once, token parity
        preserved."""
        cfg, params = tiny_llama
        reqs = _mixed_reqs(29, 4, cfg)
        engines = [ServingEngine(cfg, params, slots=2, max_len=96,
                                 prompt_buckets=(8, 16, 32), mesh=mp2)
                   for _ in range(2)]
        router = FleetRouter(engines, max_queue=8, seg_steps=8)
        router.serve(_burst(reqs))
        out = router.results()
        for rid, (p, n) in zip(sorted(out), reqs):
            assert out[rid] == _dense_reference(cfg, params, p, n)

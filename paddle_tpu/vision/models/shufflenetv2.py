"""ShuffleNetV2 (reference: ``python/paddle/vision/models/shufflenetv2.py``)."""

from ... import nn
from ...ops import manipulation as M

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0"]


def _channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = M.reshape(x, [b, groups, c // groups, h, w])
    x = M.transpose(x, [0, 2, 1, 3, 4])
    return M.reshape(x, [b, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride, 1, groups=inp,
                          bias_attr=False), nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), nn.ReLU())
            in2 = inp
        else:
            self.branch1 = None
            in2 = inp // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride, 1, groups=branch,
                      bias_attr=False), nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = M.split(x, [c, c], axis=1)
            out = M.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = M.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_CFG = {
    0.25: [24, 24, 48, 96, 512], 0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024], 1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        ch = _CFG[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, ch[0], 3, 2, 1, bias_attr=False),
            nn.BatchNorm2D(ch[0]), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        stages = []
        inp = ch[0]
        for i, reps in enumerate([4, 8, 4]):
            oup = ch[i + 1]
            units = [_ShuffleUnit(inp, oup, 2)]
            units += [_ShuffleUnit(oup, oup, 1) for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(inp, ch[4], 1, bias_attr=False),
            nn.BatchNorm2D(ch[4]), nn.ReLU())
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(ch[4], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        return self.fc(self.pool(x).flatten(1))


def _make(scale):
    def f(pretrained=False, **kwargs):
        return ShuffleNetV2(scale=scale, **kwargs)
    return f


shufflenet_v2_x0_25 = _make(0.25)
shufflenet_v2_x0_5 = _make(0.5)
shufflenet_v2_x1_0 = _make(1.0)
shufflenet_v2_x1_5 = _make(1.5)
shufflenet_v2_x2_0 = _make(2.0)

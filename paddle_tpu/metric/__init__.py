"""``paddle.metric`` (reference: ``python/paddle/metric/metrics.py``)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional preprocessing run on device outputs before ``update``."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = topk_idx == label_np[..., None]
        return correct

    def compute_traced(self, pred, label, *args):
        """Traceable form of ``compute`` (paddle ops on device tensors):
        hapi fuses this INTO the compiled train step, so per batch only
        the tiny [N, maxk] correctness matrix crosses to the host instead
        of the whole logits tensor (SURVEY §3.2's hot loop; the transfer
        dominates on dispatch-latency-bound transports)."""
        from ..ops import logic, manipulation

        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = manipulation.squeeze(label, -1)
        idx = manipulation.argsort(pred, axis=-1, descending=True)
        idx = idx[..., : self.maxk]
        return logic.equal(idx, manipulation.unsqueeze(label, -1))

    def update(self, correct, *args):
        correct = _np(correct)
        flat = correct.reshape(-1, correct.shape[-1])
        n = flat.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += flat[:, :k].any(-1).sum()
            self.count[i] += n
        num = self.total[0] / max(self.count[0], 1)
        return float(num)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(int).reshape(-1)
        labels = _np(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(int).reshape(-1)
        labels = _np(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        bins = np.minimum((preds * self.num_thresholds).astype(int), self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoidal over thresholds high->low
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..core.tensor import to_tensor

    pred = _np(input)
    lab = _np(label).reshape(-1)
    topk_idx = np.argsort(-pred, axis=-1)[:, :k]
    correct_mask = (topk_idx == lab[:, None]).any(-1)
    return to_tensor(np.asarray(correct_mask.mean(), dtype="float32"))

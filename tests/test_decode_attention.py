"""Ragged decode attention + fused tick epilogue (r6 tentpole).

CPU-backend parity: the Pallas kernels run through the pallas
interpreter (FORCE_INTERPRET) so the exact kernel code paths — block
clamp, tail-block masking, online-softmax scratch carry, the fused
rms/rope/residual chains — are exercised where tier-1 runs, against the
dense XLA formulation that remains the fallback path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.ops.pallas.decode_attention as da
import paddle_tpu.ops.pallas.tick_fusion as tf
from paddle_tpu.models import llama
from paddle_tpu.parallel import set_mesh


@pytest.fixture
def forced(monkeypatch):
    """Force both kernel families through the interpreter on CPU; clear
    the compiled-program caches so dispatch decisions re-trace."""
    set_mesh(None)
    monkeypatch.setattr(da, "FORCE_INTERPRET", True)
    monkeypatch.setattr(tf, "FORCE_INTERPRET", True)
    llama._prefill_program.cache_clear()
    llama._decode_program.cache_clear()
    yield
    llama._prefill_program.cache_clear()
    llama._decode_program.cache_clear()


@pytest.fixture(scope="module")
def kcfg():
    """Smallest config on which BOTH kernels activate: hidden % 128 == 0
    and num_kv_heads * head_dim % 128 == 0 (GQA: 4 q heads over 2 kv).
    Module scope (r11): params are seeded and read-only here."""
    set_mesh(None)
    cfg = llama.LlamaConfig(
        vocab_size=128, hidden_size=256, intermediate_size=512,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
        dtype=jnp.float32, remat=False, scan_layers=False)
    return cfg, llama.init_params(cfg, jax.random.PRNGKey(0))


def _dense_cache_attention(cfg, q, kc, vc, pos_b):
    """The XLA formulation, bypassing dispatch (the parity referee)."""
    qg = q  # [B, 1, nH, D]
    B = q.shape[0]
    visible = jnp.arange(kc.shape[1]) <= pos_b[:, None, None]
    rep = cfg.num_heads // cfg.num_kv_heads
    s = jnp.einsum("bthrd,bshd->bhrts",
                   qg.reshape(B, 1, cfg.num_kv_heads, rep, cfg.head_dim),
                   kc, preferred_element_type=jnp.float32)
    s = s / np.sqrt(cfg.head_dim)
    s = jnp.where(visible[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhrts,bshd->bthrd", p.astype(q.dtype), vc,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    return o.reshape(B, 1, cfg.num_heads, cfg.head_dim)


class TestRaggedKernel:
    @pytest.mark.parametrize("nH,Hkv,D", [(8, 8, 64), (8, 4, 64),
                                          (2, 2, 128)])
    def test_parity_mixed_positions(self, nH, Hkv, D):
        """Kernel vs dense at mixed per-slot positions: pos=0 (one visible
        key), pos=max_len-1 (full window), block-unaligned interior
        positions (tail-block masking)."""
        rng = np.random.RandomState(0)
        B, Smax = 4, 256
        q = jnp.asarray(rng.randn(B, nH, D), jnp.float32)
        kc = jnp.asarray(rng.randn(B, Smax, Hkv, D), jnp.float32)
        vc = jnp.asarray(rng.randn(B, Smax, Hkv, D), jnp.float32)
        cfg = llama.LlamaConfig.tiny(num_heads=nH, num_kv_heads=Hkv,
                                     hidden_size=nH * D)
        for pos_vals in ([0, 1, 129, 255], [37, 64, 128, 200],
                         [255, 0, 63, 191]):
            pos = jnp.asarray(pos_vals, jnp.int32)
            out = da.ragged_decode_attention(q, kc, vc, pos, interpret=True)
            ref = _dense_cache_attention(cfg, q[:, None], kc, vc, pos)[:, 0]
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)

    def test_small_block_fallback_shapes(self):
        """max_len that only a 64-row block tiles (e.g. 192, the
        llama_decode bench cache) still runs on the kernel."""
        rng = np.random.RandomState(1)
        B, Smax, nH, D = 2, 192, 4, 64
        q = jnp.asarray(rng.randn(B, nH, D), jnp.float32)
        kc = jnp.asarray(rng.randn(B, Smax, nH, D), jnp.float32)
        vc = jnp.asarray(rng.randn(B, Smax, nH, D), jnp.float32)
        assert da.pick_kv_block(Smax) == 64
        pos = jnp.asarray([0, 191], jnp.int32)
        out = da.ragged_decode_attention(q, kc, vc, pos, interpret=True)
        cfg = llama.LlamaConfig.tiny(num_heads=nH, num_kv_heads=nH,
                                     hidden_size=nH * D)
        ref = _dense_cache_attention(cfg, q[:, None], kc, vc, pos)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_dispatch_gates(self, monkeypatch):
        """CPU without the force stays dense; indivisible shapes and
        disabled flags stay dense even when forced."""
        import paddle_tpu

        assert not da.decode_attention_active(256, 4, 2, 64)  # CPU
        monkeypatch.setattr(da, "FORCE_INTERPRET", True)
        assert da.decode_attention_active(256, 4, 2, 64)
        assert not da.decode_attention_active(250, 4, 2, 64)  # no block
        assert not da.decode_attention_active(256, 4, 2, 32)  # lanes < 128
        assert not da.decode_attention_active(256, 3, 2, 64)  # GQA ragged
        paddle_tpu.set_flags({"use_ragged_decode": False})
        try:
            assert not da.decode_attention_active(256, 4, 2, 64)
        finally:
            paddle_tpu.set_flags({"use_ragged_decode": True})

    def test_bytes_scale_with_pos(self):
        """The analytic blocks-read contract the BlockSpec clamp
        enforces: fetched rows track pos, not max_len."""
        blk = da.pick_kv_block(512)
        assert blk == 128
        assert da.kv_blocks_read(0, blk) == 1
        assert da.kv_blocks_read(127, blk) == 1
        assert da.kv_blocks_read(128, blk) == 2
        assert da.kv_blocks_read(511, blk) == 4


class TestTickFusionKernels:
    def test_rms_and_add_rms_match_inline(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(8, 256), jnp.float32)
        y = jnp.asarray(rng.randn(8, 256), jnp.float32)
        w = jnp.asarray(rng.randn(256), jnp.float32)
        eps = 1e-6
        tf_prev = tf.FORCE_INTERPRET
        tf.FORCE_INTERPRET = True
        try:
            o = tf.fused_rms_norm(x, w, eps)
            s, o2 = tf.fused_add_rms_norm(x, y, w, eps)
        finally:
            tf.FORCE_INTERPRET = tf_prev
        ref = llama._rms_norm(x, w, eps)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(s), np.asarray(x + y),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(o2), np.asarray(llama._rms_norm(x + y, w, eps)),
            rtol=1e-6, atol=1e-6)

    def test_rope_matches_inline_ragged_positions(self):
        rng = np.random.RandomState(3)
        B, nH, Hkv, D = 4, 4, 2, 64
        zq = jnp.asarray(rng.randn(B, nH * D), jnp.float32)
        zk = jnp.asarray(rng.randn(B, Hkv * D), jnp.float32)
        pos = jnp.asarray([0, 7, 100, 255], jnp.int32)
        tf_prev = tf.FORCE_INTERPRET
        tf.FORCE_INTERPRET = True
        try:
            oq, ok = tf.fused_rope_qk(zq, zk, pos, D, 10000.0)
        finally:
            tf.FORCE_INTERPRET = tf_prev
        rq = llama._rope_at(zq.reshape(B, 1, nH, D), 10000.0,
                            pos[:, None]).reshape(B, nH * D)
        rk = llama._rope_at(zk.reshape(B, 1, Hkv, D), 10000.0,
                            pos[:, None]).reshape(B, Hkv * D)
        np.testing.assert_allclose(np.asarray(oq), np.asarray(rq),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(ok), np.asarray(rk),
                                   rtol=1e-5, atol=1e-5)


class TestFusedDecodePath:
    def test_tick_matches_dense_ragged_positions(self, forced, kcfg):
        """One ragged decode tick, kernels forced vs everything dense —
        mixed positions including 0 and max_len-1."""
        cfg, params = kcfg
        cfg_off = dataclasses.replace(cfg, fused_tick_epilogue=False)
        cache = llama.init_kv_cache(cfg, 4, 256)
        nxt = jnp.array([[3], [5], [7], [11]], jnp.int32)
        posv = jnp.array([0, 17, 130, 255], jnp.int32)
        out, c1 = llama.forward_with_cache(params, nxt, cfg, cache, posv)
        tf_da = (da.FORCE_INTERPRET, tf.FORCE_INTERPRET)
        da.FORCE_INTERPRET = tf.FORCE_INTERPRET = False
        try:
            ref, c2 = llama.forward_with_cache(params, nxt, cfg_off,
                                               cache, posv)
        finally:
            da.FORCE_INTERPRET, tf.FORCE_INTERPRET = tf_da
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=1e-5)
        for kk in ("k", "v"):
            np.testing.assert_allclose(np.asarray(c1[kk]),
                                       np.asarray(c2[kk]),
                                       rtol=2e-5, atol=2e-5)

    def test_generate_matches_dense(self, forced, kcfg):
        cfg, params = kcfg
        rng = np.random.RandomState(4)
        prompt = jnp.array(rng.randint(0, cfg.vocab_size, (2, 10)),
                           jnp.int32)
        da.reset_selection_count()
        out = np.asarray(llama.generate(params, prompt, cfg,
                                        max_new_tokens=6, max_len=256))
        assert da.selection_count() >= 1, \
            "generate()'s decode program did not select the ragged kernel"
        da.FORCE_INTERPRET = tf.FORCE_INTERPRET = False
        llama._prefill_program.cache_clear()
        llama._decode_program.cache_clear()
        ref = np.asarray(llama.generate(params, prompt, cfg,
                                        max_new_tokens=6, max_len=256))
        np.testing.assert_array_equal(out, ref)

    def test_unrolled_vs_scan_cache_parity_with_kernels(self, forced, kcfg):
        """VERDICT item 6 subset: the unrolled static-index KV path and
        the layer-scan path must agree WITH the ragged kernel + fused
        epilogue active (both branches route through the same kernels) —
        prefill, then a ragged per-slot tick, comparing logits AND the
        cache contents (the layer-scan stacking is where r4's
        4-copies-per-tick bug class lived)."""
        cfg_u, params = kcfg
        cfg_s = dataclasses.replace(cfg_u, scan_layers=True)
        rng = np.random.RandomState(5)
        prompt = jnp.array(rng.randint(0, cfg_u.vocab_size, (2, 9)),
                           jnp.int32)
        caches = [llama.init_kv_cache(c, 2, 256) for c in (cfg_u, cfg_s)]
        outs = []
        for cfg, cache in zip((cfg_u, cfg_s), caches):
            _, cache = llama.forward_with_cache(params, prompt, cfg,
                                                cache, jnp.int32(0))
            posv = jnp.array([9, 137], jnp.int32)  # ragged, cross-block
            lg, cache = llama.forward_with_cache(
                params, jnp.array([[3], [5]], jnp.int32), cfg, cache, posv)
            outs.append((np.asarray(lg), np.asarray(cache["k"])))
        for a, b in zip(*outs):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)

    def test_cpu_defaults_stay_dense(self, kcfg):
        """Without the force, CPU dispatch must not select any kernel —
        tier-1 numerics are byte-identical to the pre-kernel tree."""
        cfg, params = kcfg
        assert not llama._tick_fused_active(cfg)
        da.reset_selection_count()
        cache = llama.init_kv_cache(cfg, 2, 256)
        llama.forward_with_cache(params, jnp.array([[1], [2]], jnp.int32),
                                 cfg, cache, jnp.array([4, 9], jnp.int32))
        assert da.selection_count() == 0

"""Parameter-server stack tests (reference test strategy: local brpc
server+client, SURVEY.md §4 "PS tests" — CPU-only, loopback)."""

import threading

import numpy as np
import pytest

from paddle_tpu.distributed.ps import PsClient, PsServer


@pytest.fixture()
def ps():
    server = PsServer()
    client = PsClient(server.host, server.port)
    yield server, client
    client.close()
    server.stop()


def test_dense_pull_push(ps):
    server, client = ps
    client.create_dense_table(0, shape=(4,), lr=0.1,
                              init=np.ones(4, np.float32))
    np.testing.assert_allclose(client.pull_dense(0), np.ones(4))
    client.push_dense_grad(0, np.full(4, 2.0, np.float32))
    np.testing.assert_allclose(client.pull_dense(0), np.full(4, 0.8),
                               rtol=1e-6)


def test_sparse_embedding_flow(ps):
    """Typical recommendation step: pull rows by id, push row grads back."""
    server, client = ps
    client.create_sparse_table(1, dim=8, lr=0.5)
    ids = np.array([3, 99, 3], np.int64)
    rows = client.pull_sparse(1, ids)
    assert rows.shape == (3, 8)
    np.testing.assert_allclose(rows[0], rows[2])  # same id, same row
    grads = np.zeros((3, 8), np.float32)
    grads[1] = 1.0
    client.push_sparse_grad(1, ids, grads)
    rows2 = client.pull_sparse(1, np.array([99], np.int64))
    np.testing.assert_allclose(rows2[0], rows[1] - 0.5, rtol=1e-5)
    assert client.table_stats()["sparse"][1] == 2


def test_multi_trainer_async_updates(ps):
    """Two trainer clients pushing concurrently — async-SGD semantics: all
    updates land (order-free sum for constant grads)."""
    server, client = ps
    client.create_dense_table(2, shape=(2,), lr=1.0,
                              init=np.zeros(2, np.float32))
    c2 = PsClient(server.host, server.port)

    def trainer(c, n):
        for _ in range(n):
            c.push_dense_grad(2, np.array([1.0, -1.0], np.float32))

    ts = [threading.Thread(target=trainer, args=(c, 50))
          for c in (client, c2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    np.testing.assert_allclose(client.pull_dense(2), [-100.0, 100.0])
    c2.close()


def test_trainer_local_train_converges(ps):
    """End-to-end: linear regression where the trainer computes grads locally
    and the PS owns the weights (sync pull → grad → push loop)."""
    server, client = ps
    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    w_true = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    y = X @ w_true
    client.create_dense_table(3, shape=(4,), lr=0.1,
                              init=np.zeros(4, np.float32))
    for _ in range(100):
        w = client.pull_dense(3)
        grad = 2 * X.T @ (X @ w - y) / len(X)
        client.push_dense_grad(3, grad)
    np.testing.assert_allclose(client.pull_dense(3), w_true, atol=1e-2)

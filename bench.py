"""Headline benchmark: transformer pretraining throughput on one TPU chip.

Workload = BASELINE config 2 (ERNIE/BERT-base-budget pretraining with
flash-attention + AdamW): a ~110M-parameter decoder
(``paddle_tpu.models.llama.LlamaConfig.bert_base_equiv``), bf16 compute with
fp32 master weights, full train step (fwd + bwd + global-norm clip + AdamW)
as ONE jitted XLA program with donated buffers.

Baseline: BASELINE.md gives no reference measurement (the reference repo
publishes none); the north star is "match A100". Public ballpark for an A100
on a 110M-param causal LM at ~50% MFU is ≈190k tokens/s (312 TF/s fp16 × 0.5
÷ ~0.8 GFLOPs/token fwd+bwd). ``vs_baseline`` = measured tokens/s ÷ 190_000.

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import sys
import time

A100_BALLPARK_TOKENS_PER_S = 190_000.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run(batch: int, seq: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.models import llama
    from paddle_tpu.parallel import create_hybrid_mesh, set_mesh

    cfg = llama.LlamaConfig.bert_base_equiv(max_seq_len=seq)
    dev = jax.devices()
    log(f"devices: {dev}")
    mesh = create_hybrid_mesh(devices=dev[:1])  # single chip
    params = llama.init_params(cfg)
    opt_state = llama.init_opt_state(params)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    rng = np.random.RandomState(0)
    tokens = jnp.array(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    step = llama.make_sharded_train_step(cfg, mesh, lr=1e-4)

    # warmup / compile. NOTE: completion is forced via float(loss) — a real
    # device->host value transfer — because block_until_ready does not
    # reliably block through tunneled PJRT transports.
    params, opt_state, loss = step(params, opt_state, tokens, tokens)
    float(loss)
    params, opt_state, loss = step(params, opt_state, tokens, tokens)
    log(f"warmup loss {float(loss):.4f}; params {n_params/1e6:.1f}M")

    # 40-step chains: each timing block ends in ONE blocking scalar fetch
    # whose ~30-60 ms tunnel round trip rides inside the measurement —
    # at 20 iters that contaminated the per-step number by 1.5-3 ms
    # (r5: 148.3k -> 151.6k tok/s from amortizing it alone). best-of-4
    # also gives the varying per-block dispatch overhead a shot at a
    # quiet window.
    iters = 40
    best_dt = None
    for _ in range(4):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, tokens, tokens)
        float(loss)
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    set_mesh(None)

    tokens_per_s = iters * batch * seq / best_dt
    flops_per_token = 6.0 * n_params  # fwd+bwd matmul FLOPs estimate
    mfu = tokens_per_s * flops_per_token / 197e12  # v5e bf16 peak ≈197 TF/s
    # r10: headline utilisation reports THROUGH the metrics layer — the
    # same gauges an operator scrapes, so the bench and the telemetry
    # surface cannot drift apart
    from paddle_tpu import observability as obs

    obs.gauge("train.mfu").set(mfu)
    obs.gauge("train.tokens_per_s").set(tokens_per_s)
    obs.histogram("train.step_time_s").observe(best_dt / iters)
    log(f"b{batch}: {tokens_per_s:,.0f} tokens/s, step {best_dt/iters*1e3:.1f} ms, "
        f"MFU≈{mfu:.1%} (v5e)")
    return tokens_per_s


def main():
    best = 0.0
    # 44 is the measured sweet spot on v5e after the r3 CE/logits-slice
    # work (b48 -0.7%, b42/b46 -0.3/-1.2%, b64 compiles but -4%); 48/32/16
    # are fallback brackets, 8/4 OOM-only
    for batch in (44, 48, 32, 16, 8, 4):
        if best and batch <= 48:
            break
        # the tunneled compile service occasionally drops a request
        # (INTERNAL: remote_compile ... response body closed) — retry each
        # batch once on that signature; anything else (e.g. OOM) falls
        # through to the next batch immediately
        for attempt in (1, 2):
            try:
                best = max(best, run(batch, 512))
                break
            except Exception as e:
                log(f"batch {batch} attempt {attempt} failed: "
                    f"{type(e).__name__}: {e}")
                if "remote_compile" not in str(e):
                    break
    tokens_per_s = best
    if not best:
        print(json.dumps({
            "metric": "bert_base_equiv_pretrain_throughput", "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": "all batch sizes failed",
        }))
        return
    from paddle_tpu import observability as obs

    print(json.dumps({
        "metric": "bert_base_equiv_pretrain_throughput",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tokens_per_s / A100_BALLPARK_TOKENS_PER_S, 4),
        # read back from the gauge, not a local: the artifact publishes
        # what the telemetry layer holds
        "mfu": round(obs.gauge("train.mfu").value, 4),
        "step_time_p50_s": round(
            obs.histogram("train.step_time_s").quantile(0.5), 4),
    }))


if __name__ == "__main__":
    main()

"""Program auditor: run every static + dynamic pass over one program.

The auditor consumes either a raw jit-compiled callable (``audit_fn``)
or a registered canonical program (``programs.build``), runs the six
passes, and returns an ``AuditReport`` of findings + metrics that
``budgets.check`` judges:

1. host-sync detector    (dynamic; ``syncs.SyncAudit`` over a warm replay)
2. recompile-hazard lint (dynamic; ``recompile.CompileWatch`` + cache keys)
3. relayout accounting   (static;  ``hlo.relayout_inventory``)
4. donation/aliasing     (static;  ``hlo.donation_report``)
5. collective/mesh audit (static;  ``hlo.collective_check``)
6. HBM liveness          (static;  ``memory.peak_live`` — r24)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import hlo as hlo_passes
from . import memory as memory_pass
from . import recompile as recompile_pass
from . import syncs as sync_pass

__all__ = ["Finding", "AuditReport", "audit_static", "audit_fn",
           "audit_replay"]


@dataclass
class Finding:
    pass_name: str        # 'host_sync' | 'recompile' | 'relayout' | ...
    severity: str         # 'hazard' | 'info'
    message: str
    data: Any = None

    def __str__(self):
        return f"[{self.pass_name}:{self.severity}] {self.message}"


@dataclass
class AuditReport:
    program: str
    findings: List[Finding] = field(default_factory=list)
    metrics: Dict[str, Any] = field(default_factory=dict)

    @property
    def hazards(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "hazard"]

    def add(self, pass_name: str, severity: str, message: str,
            data: Any = None) -> None:
        self.findings.append(Finding(pass_name, severity, message, data))

    def merge(self, other: "AuditReport") -> "AuditReport":
        self.findings.extend(other.findings)
        self.metrics.update(other.metrics)
        return self

    def format(self) -> str:
        lines = [f"== audit: {self.program} =="]
        for k in sorted(self.metrics):
            lines.append(f"  {k}: {self.metrics[k]}")
        for f in self.findings:
            lines.append(f"  {f}")
        if not self.findings:
            lines.append("  (no findings)")
        return "\n".join(lines)


def audit_static(program: str, hlo_text: str, mesh=None,
                 donation_threshold: int = 1 << 20,
                 expected_undonated: Sequence[str] = (),
                 allowed_axes: Optional[Sequence[str]] = None,
                 memory: bool = True) -> AuditReport:
    """Passes 3-6 over one program's optimized HLO text.

    ``memory=False`` skips the liveness pass (the ``--memory off``
    contract: no ``peak_bytes`` metric is emitted, so ``budgets.check``
    skips the peak ceiling — every other budget is bit-identical)."""
    rep = AuditReport(program=program)

    inv = hlo_passes.relayout_inventory(hlo_text)
    relayout = sum(e.bytes for e in inv if e.klass == "relayout")
    pack = sum(e.bytes for e in inv if e.klass == "pack")
    rep.metrics["relayout_bytes"] = relayout
    rep.metrics["pack_bytes"] = pack
    rep.metrics["relayout_ops"] = sum(1 for e in inv
                                      if e.klass == "relayout")
    biggest = sorted((e for e in inv if e.klass == "relayout"),
                     key=lambda e: -e.bytes)[:5]
    for e in biggest:
        rep.add("relayout", "info",
                f"{e.op} {e.bytes / 2**20:.2f} MiB {e.shape}"
                + (f" [{e.metadata}]" if e.metadata else ""), e)

    don = hlo_passes.donation_report(hlo_text, threshold=donation_threshold,
                                     expected_undonated=expected_undonated)
    rep.metrics["undonated_bytes"] = don.undonated_bytes
    rep.metrics["donated_bytes"] = don.donated_bytes
    for p in don.large_undonated:
        rep.add("donation", "hazard",
                f"large non-donated parameter #{p.number} {p.name} "
                f"({p.bytes / 2**20:.2f} MiB {p.shape}) — HBM peak pays "
                f"for input and output copies", p)

    chk = hlo_passes.collective_check(hlo_text, mesh,
                                      allowed_axes=allowed_axes)
    rep.metrics["collective_bytes"] = chk.total_bytes
    rep.metrics["collectives"] = len(chk.inventory)
    for e in chk.unattributed:
        rep.add("collective", "hazard",
                f"{e['op']} ({e['bytes'] / 2**20:.2f} MiB) matches no "
                f"declared mesh-axis subset", e)
    for e in chk.partial_ring:
        rep.add("collective", "hazard",
                f"{e['op']} rides a partial ring {e['axes']} — relayout "
                f"fragment billed as axis traffic", e)
    for e in chk.disallowed_axes:
        rep.add("collective", "hazard",
                f"{e['op']} rides axes {e['axes']} outside the program's "
                f"declared set {sorted(allowed_axes)}", e)

    if memory:
        mem = memory_pass.peak_live(hlo_text, program=program)
        rep.metrics["peak_bytes"] = mem.peak_bytes
        rep.metrics["peak_transient_bytes"] = mem.transient_bytes
        rep.add("memory", "info",
                f"peak {mem.peak_bytes / 2**20:.2f} MiB at "
                f"#{mem.peak_index}/{mem.schedule_len} "
                f"{mem.peak_instruction} (params "
                f"{mem.param_bytes / 2**20:.2f} MiB + transient "
                f"{mem.transient_bytes / 2**20:.2f} MiB)", mem)
        for b in mem.live_at_peak[:3]:
            if not b.bytes:
                continue
            tag = "param" if b.param else "live"
            rep.add("memory", "info",
                    f"at peak [{tag}] {b.bytes / 2**20:.2f} MiB "
                    f"{b.name} {b.op} {b.shape}"
                    + (f" [{b.metadata}]" if b.metadata else ""), b)
        for b in memory_pass.hot_transients(mem):
            rep.add("memory", "info",
                    f"liveness hotspot: {b.name} {b.op} "
                    f"({b.bytes / 2**20:.2f} MiB {b.shape}) live "
                    f"[{b.start}, {b.end}] of {mem.schedule_len} — a "
                    f"whole-schedule transient dominating the peak "
                    f"(the stacked-across-steps class)", b)
    return rep


def audit_replay(program: str, replay: Callable[[], Any],
                 warmups: int = 2, replays: int = 2) -> AuditReport:
    # warmups=2: some programs restructure after their FIRST execution
    # (FusedTrainStep switches to a fixed RNG key once the trace proves
    # the model consumes no randomness — that switch compiles the key
    # constant); the steady state begins at call 2.
    """Passes 1-2 (dynamic): run ``replay()`` ``warmups`` times to let
    every shape compile, then ``replays`` more times under the sync
    audit and compile watch. A warm workload must neither sync outside
    ``allowed_sync`` regions nor compile anything new."""
    rep = AuditReport(program=program)
    with recompile_pass.CompileWatch() as cw, sync_pass.SyncAudit() as sa:
        sa.phase = "warm"
        for _ in range(warmups):
            replay()
        cw.mark()
        sa.phase = "replay"
        for _ in range(replays):
            replay()
    flagged = sa.flagged("replay")
    allowed = sa.allowed("replay")
    rep.metrics["host_syncs_flagged"] = len(flagged)
    rep.metrics["host_syncs_allowed"] = dict(allowed)
    rep.metrics["warm_compiles"] = cw.since_mark
    rep.metrics["replays"] = replays
    seen = set()
    for e in flagged:
        key = (e.kind, e.site)
        if key in seen:
            continue
        seen.add(key)
        n = sum(1 for x in flagged if (x.kind, x.site) == key)
        rep.add("host_sync", "hazard",
                f"{e.kind} at {e.site} ({n}x over {replays} replays) — "
                f"device→host sync in a warm loop", e)
    if cw.since_mark:
        rep.add("recompile", "hazard",
                f"{cw.since_mark} XLA compilations during warm replay — "
                f"the workload is re-specialising on an unpinned shape "
                f"or flag", cw.since_mark)
    return rep


def audit_fn(fn: Callable, *args, program: Optional[str] = None,
             mesh=None, donation_threshold: int = 1 << 20,
             expected_undonated: Sequence[str] = (),
             allowed_axes: Optional[Sequence[str]] = None,
             replays: int = 2, **kwargs) -> AuditReport:
    """Audit any jit-compiled callable on example arguments.

    Static passes run over ``fn.lower(*args).compile()`` when ``fn`` is
    a ``jax.jit`` wrapper (or anything exposing ``lower``); dynamic
    passes replay ``fn(*args)``. Programs that donate buffers should be
    audited via a replay closure that rebuilds inputs instead
    (``audit_replay``) — donation consumes the example args."""
    name = program or getattr(fn, "__name__", "program")
    rep = AuditReport(program=name)
    lowered = getattr(fn, "lower", None)
    if lowered is not None:
        text = lowered(*args, **kwargs).compile().as_text()
        rep.merge(audit_static(name, text, mesh=mesh,
                               donation_threshold=donation_threshold,
                               expected_undonated=expected_undonated,
                               allowed_axes=allowed_axes))
    rep.merge(audit_replay(name, lambda: fn(*args, **kwargs),
                           replays=replays))
    return rep

"""Chip certification for the INFERENCE surface — REAL TPU ONLY
(VERDICT r5 item 6 / weak #6: training was chip-certified, but
``generate()``'s scan program, the fused drain, and the unrolled-KV path
were only exercised on-chip via benchmarks, never as parity-asserted
tests). Runs in the TPU lane (``benchmarks/tpu_test_lane.py``); the CPU
suite skips it like the other ``*_tpu.py`` files.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="inference chip certification runs on TPU only")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny(**kw):
    from paddle_tpu.models import llama

    return llama.LlamaConfig.tiny(max_seq_len=96, **kw)


def _dense(cfg, params, prompt, n):
    from paddle_tpu.models import llama

    out = llama.generate(params, np.asarray(prompt, np.int32)[None], cfg,
                         max_new_tokens=n, max_len=96)
    return [int(t) for t in np.asarray(out)[0]]


def test_generate_greedy_parity_chip_vs_cpu():
    """Greedy prefill + scan-decode on the chip must emit the same tokens
    as the CPU backend (fp32 tiny config: same argmax stream)."""
    from paddle_tpu.models import llama
    from paddle_tpu.parallel import set_mesh

    set_mesh(None)
    cfg = _tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 12)).astype(np.int32)
    chip = np.asarray(llama.generate(params, jnp.asarray(prompt), cfg,
                                     max_new_tokens=10, max_len=96))
    # CPU reference in a subprocess (the in-process backend is pinned to
    # the chip; re-exec with JAX_PLATFORMS=cpu mirrors conftest)
    code = (
        "import numpy as np, jax, sys;"
        "sys.path.insert(0, {root!r});"
        "from paddle_tpu.models import llama;"
        "from paddle_tpu.parallel import set_mesh;"
        "set_mesh(None);"
        "cfg = llama.LlamaConfig.tiny(max_seq_len=96);"
        "params = llama.init_params(cfg, jax.random.PRNGKey(0));"
        "prompt = np.random.RandomState(0).randint("
        "0, cfg.vocab_size, (2, 12)).astype(np.int32);"
        "out = llama.generate(params, prompt, cfg, max_new_tokens=10,"
        " max_len=96);"
        "print('TOKS', np.asarray(out).tolist())"
    ).format(root=ROOT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=ROOT,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(l for l in proc.stdout.splitlines() if l.startswith("TOKS"))
    cpu = np.asarray(eval(line[5:]))
    np.testing.assert_array_equal(chip, cpu)


def test_fused_drain_mixed_lengths_eos_matches_dense():
    """The single-program drain on the chip: mixed prompt/generation
    lengths + EOS freeze, token-identical to dense generate()."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama
    from paddle_tpu.parallel import set_mesh

    set_mesh(None)
    cfg = _tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    reqs = [(rng.randint(0, cfg.vocab_size, (l,)).astype(np.int32), n)
            for l, n in [(5, 7), (12, 3), (30, 9), (3, 12), (17, 5)]]
    refs = [_dense(cfg, params, p, n) for p, n in reqs]
    eos = refs[0][1]  # freezes request 0 early
    eng = ServingEngine(cfg, params, slots=2, max_len=96, chunk=4,
                        prompt_buckets=(8, 16, 32), eos_token_id=eos)
    rids = [eng.add_request(p, n) for p, n in reqs]
    out = eng.run()
    for rid, ref in zip(rids, refs):
        want = ref[:ref.index(eos) + 1] if eos in ref else ref
        assert out[rid] == want, (rid, out[rid], want)


def test_online_segments_match_dense():
    """The r7 re-entrant segment path on the chip: requests arriving
    between segments (slots mid-flight) still match dense generate()."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama
    from paddle_tpu.parallel import set_mesh

    set_mesh(None)
    cfg = _tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(2))
    rng = np.random.RandomState(2)
    wave1 = [(rng.randint(0, cfg.vocab_size, (l,)).astype(np.int32), n)
             for l, n in [(5, 9), (12, 6)]]
    wave2 = [(rng.randint(0, cfg.vocab_size, (l,)).astype(np.int32), n)
             for l, n in [(20, 4), (7, 10)]]
    eng = ServingEngine(cfg, params, slots=2, max_len=96,
                        prompt_buckets=(8, 16, 32))
    rids1 = [eng.add_request(p, n) for p, n in wave1]
    eng.run_segment(4)
    rids2 = [eng.add_request(p, n) for p, n in wave2]
    while eng._queue or eng.free_slot_count() < eng.slots:
        eng.run_segment(8)
    out = eng.collect_finished()
    for rid, (p, n) in zip(rids1 + rids2, wave1 + wave2):
        assert out[rid] == _dense(cfg, params, p, n)


def test_unrolled_kv_matches_scan_layers_on_chip():
    """scan_layers=False (static-index row-DUS cache writes, the decode
    fast path) vs the layer-scan branch: generate parity AND ragged
    per-slot decode parity, on the chip's numerics."""
    import dataclasses

    from paddle_tpu.models import llama
    from paddle_tpu.parallel import set_mesh

    set_mesh(None)
    cfg_s = _tiny()
    cfg_u = dataclasses.replace(cfg_s, scan_layers=False)
    params = llama.init_params(cfg_s, jax.random.PRNGKey(3))
    rng = np.random.RandomState(3)
    prompt = jnp.array(rng.randint(0, cfg_s.vocab_size, (2, 10)), jnp.int32)
    o_s = np.asarray(llama.generate(params, prompt, cfg_s,
                                    max_new_tokens=8, max_len=32))
    o_u = np.asarray(llama.generate(params, prompt, cfg_u,
                                    max_new_tokens=8, max_len=32))
    np.testing.assert_array_equal(o_s, o_u)

    outs = []
    for cfg in (cfg_s, cfg_u):
        cache = llama.init_kv_cache(cfg, 2, 32)
        lg, cache = llama.forward_with_cache(params, prompt, cfg, cache,
                                             jnp.int32(0))
        posv = jnp.array([10, 10], jnp.int32)
        l2, cache = llama.forward_with_cache(
            params, jnp.array([[3], [5]], jnp.int32), cfg, cache, posv)
        outs.append((np.asarray(lg), np.asarray(l2),
                     np.asarray(cache["k"])))
    for a, b in zip(*outs):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-4)


def test_prefix_cache_hit_matches_cold_on_chip():
    """Shared-prefix admission (suffix-only prefill from reused KV rows)
    must be token-identical to cold admission on the chip."""
    from paddle_tpu.inference.prefix_cache import PrefixCache
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama
    from paddle_tpu.parallel import set_mesh

    set_mesh(None)
    cfg = _tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(4))
    rng = np.random.RandomState(4)
    prefix = rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
    prompts = [np.concatenate(
        [prefix, rng.randint(0, cfg.vocab_size, (6,))]).astype(np.int32)
        for _ in range(3)]

    def serve(pc):
        eng = ServingEngine(cfg, params, slots=2, max_len=96,
                            prompt_buckets=(8, 16, 64))
        rids = [eng.add_request(p, 6) for p in prompts]
        while eng._queue or eng.free_slot_count() < eng.slots:
            eng.run_segment(16, prefix_cache=pc)
        done = eng.collect_finished()
        return [done[r] for r in rids]

    cold = serve(None)
    pc = PrefixCache(block=16, capacity_tokens=2048)
    hot = serve(pc)
    assert cold == hot
    assert pc.hits >= 2

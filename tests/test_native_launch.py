"""Native runtime tests: C++ TCPStore, blob queue, launcher (reference test
strategy SURVEY.md §4: all distributed plumbing exercisable on one host —
loopback store, local process pods)."""

import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.store import TCPStore, load_native


class TestTCPStore:
    def test_set_get_roundtrip(self):
        s = TCPStore(is_master=True, world_size=1)
        s.set("k", b"value-bytes")
        assert s.get("k") == b"value-bytes"
        s.close()

    def test_add_counter(self):
        s = TCPStore(is_master=True, world_size=1)
        assert s.add("c", 5) == 5
        assert s.add("c", 7) == 12
        s.close()

    def test_get_blocks_until_set(self):
        s = TCPStore(is_master=True, world_size=1)
        got = []

        def waiter():
            c = TCPStore(port=s.port, world_size=1)
            got.append(c.get("late", timeout_ms=5000))
            c.close()

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.3)
        s.set("late", b"arrived")
        t.join(timeout=10)
        assert got == [b"arrived"]
        s.close()

    def test_wait_timeout(self):
        s = TCPStore(is_master=True, world_size=1)
        with pytest.raises(TimeoutError):
            s.wait("never", timeout_ms=200)
        s.close()

    def test_barrier_three_ranks(self):
        s = TCPStore(is_master=True, world_size=3)
        passed = []

        def rank(i):
            c = TCPStore(port=s.port, world_size=3)
            c.barrier("b", timeout_ms=5000)
            passed.append(i)
            c.close()

        ts = [threading.Thread(target=rank, args=(i,)) for i in (1, 2)]
        [t.start() for t in ts]
        s.barrier("b", timeout_ms=5000)
        [t.join(timeout=10) for t in ts]
        assert sorted(passed) == [1, 2]
        s.close()

    def test_delete_and_num_keys(self):
        s = TCPStore(is_master=True, world_size=1)
        s.set("a", b"1")
        s.set("b", b"2")
        assert s.num_keys() == 2
        assert s.delete_key("a")
        assert s.num_keys() == 1
        s.close()

    def test_large_value(self):
        s = TCPStore(is_master=True, world_size=1)
        blob = os.urandom(1 << 20)  # 1 MiB > initial 64 KiB client buffer
        s.set("big", blob)
        assert s.get("big") == blob
        s.close()


class TestBlobQueue:
    def test_push_pop_fifo(self):
        import ctypes

        lib = load_native()
        q = lib.dl_queue_create(4)
        for i in range(3):
            data = f"batch{i}".encode()
            assert lib.dl_queue_push(q, data, len(data), 1000) == 0
        assert lib.dl_queue_size(q) == 3
        for i in range(3):
            buf = ctypes.create_string_buffer(64)
            n = lib.dl_queue_pop(q, buf, 64, 1000)
            assert buf.raw[:n] == f"batch{i}".encode()
        lib.dl_queue_close(q)
        lib.dl_queue_destroy(q)

    def test_pop_timeout(self):
        lib = load_native()
        import ctypes

        q = lib.dl_queue_create(2)
        buf = ctypes.create_string_buffer(8)
        assert lib.dl_queue_pop(q, buf, 8, 100) == -1  # timeout
        lib.dl_queue_close(q)
        assert lib.dl_queue_pop(q, buf, 8, 100) == -2  # closed+drained
        lib.dl_queue_destroy(q)

    def test_bounded_capacity_blocks_producer(self):
        lib = load_native()
        q = lib.dl_queue_create(1)
        assert lib.dl_queue_push(q, b"x", 1, 100) == 0
        assert lib.dl_queue_push(q, b"y", 1, 100) == -1  # full → timeout
        lib.dl_queue_close(q)
        lib.dl_queue_destroy(q)


class TestLauncher:
    def test_single_proc_launch_env_contract(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent("""
            import os
            print("RANK", os.environ["PADDLE_TRAINER_ID"],
                  "WORLD", os.environ["PADDLE_TRAINERS_NUM"],
                  "EP", os.environ["PADDLE_CURRENT_ENDPOINT"])
        """))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        rc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--log_dir", str(tmp_path / "log"), str(script)],
            cwd="/root/repo", env=env, timeout=60)
        assert rc.returncode == 0
        log = (tmp_path / "log" / "workerlog.0").read_text()
        assert "RANK 0 WORLD 1" in log

    def test_elastic_restart_on_failure(self, tmp_path):
        marker = tmp_path / "tries"
        script = tmp_path / "flaky.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            p = {str(marker)!r}
            n = int(open(p).read()) if os.path.exists(p) else 0
            open(p, "w").write(str(n + 1))
            sys.exit(1 if n == 0 else 0)  # fail first run, succeed second
        """))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        rc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic_level", "1", "--max_restart", "2",
             "--log_dir", str(tmp_path / "log"), str(script)],
            cwd="/root/repo", env=env, timeout=60)
        assert rc.returncode == 0
        assert marker.read_text() == "2"

    def test_two_process_rendezvous_through_store(self, tmp_path):
        """A REAL 2-process pod: the launcher spawns both ranks, each
        connects to the master's C++ TCPStore from the env contract,
        crosses a barrier, publishes its rank key, and rank 0 verifies
        both arrived — the reference's loopback fake-multi-node recipe
        (SURVEY §4) end to end."""
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent("""
            import os
            from paddle_tpu.distributed.store import TCPStore

            rank = int(os.environ["PADDLE_TRAINER_ID"])
            world = int(os.environ["PADDLE_TRAINERS_NUM"])
            master = os.environ["PADDLE_MASTER"]
            host, port = master.rsplit(":", 1)
            store = TCPStore(host=host, port=int(port),
                             is_master=(rank == 0), world_size=world)
            store.set(f"hello_{rank}", str(rank).encode())
            store.barrier("rdv", timeout_ms=30000)
            if rank == 0:
                got = sorted(int(store.get(f"hello_{r}", timeout_ms=10000))
                             for r in range(world))
                assert got == list(range(world)), got
                # the master must shut down LAST: wait for every other
                # rank's done-mark before closing the store server
                for r in range(1, world):
                    store.get(f"done_{r}", timeout_ms=10000)
                print("RENDEZVOUS-OK", got)
            else:
                store.set(f"done_{rank}", b"1")
            store.close()
        """))
        import socket

        with socket.socket() as s:  # unique master port: no cross-test
            s.bind(("127.0.0.1", 0))  # TIME_WAIT collisions on the default
            free_port = s.getsockname()[1]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        rc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2",
             "--master", f"127.0.0.1:{free_port}",
             "--log_dir", str(tmp_path / "log"), str(script)],
            cwd="/root/repo", env=env, timeout=120)
        assert rc.returncode == 0
        log = (tmp_path / "log" / "workerlog.0").read_text()
        assert "RENDEZVOUS-OK [0, 1]" in log


_SPMD_WORKER = """
import os
import numpy as np
import jax
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()   # -> jax.distributed.initialize
rank = env.rank
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

# --- eager cross-process collectives (multi-controller runtime) ---
t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
dist.all_reduce(t)
np.testing.assert_allclose(t.numpy(), 3.0)

lst = []
dist.all_gather(lst, paddle.to_tensor(np.full((2,), float(rank), np.float32)))
assert len(lst) == 2, len(lst)
np.testing.assert_allclose(lst[0].numpy(), 0.0)
np.testing.assert_allclose(lst[1].numpy(), 1.0)

b = paddle.to_tensor(np.full((3,), float(rank * 7 + 1), np.float32))
dist.broadcast(b, src=1)
np.testing.assert_allclose(b.numpy(), 8.0)

objs = []
dist.all_gather_object(objs, {"rank": rank, "tag": "x" * (rank + 1)})
assert objs == [{"rank": 0, "tag": "x"}, {"rank": 1, "tag": "xx"}], objs

# all_gather must NOT overwrite its input buffer
src_buf = paddle.to_tensor(np.full((2,), float(rank), np.float32))
dist.all_gather([], src_buf)
assert tuple(src_buf.shape) == (2,), src_buf.shape

# scatter: reference convention — only src passes tensor_list
out_buf = paddle.to_tensor(np.zeros((2,), np.float32))
if rank == 0:
    got = dist.scatter(out_buf, tensor_list=[
        paddle.to_tensor(np.array([1., 2.], np.float32)),
        paddle.to_tensor(np.array([3., 4.], np.float32))], src=0)
else:
    got = dist.scatter(out_buf, src=0)
np.testing.assert_allclose(got.numpy(), [1., 2.] if rank == 0 else [3., 4.])

# reduce_scatter honors the reduce op
rs_in = paddle.to_tensor(np.arange(1, 5, dtype=np.float32) + rank)
got = dist.reduce_scatter(rs_in, op=dist.ReduceOp.MAX)
np.testing.assert_allclose(got.numpy(), [2., 3.] if rank == 0 else [4., 5.])

# DataParallel bucketed grad sync across the two processes: each rank
# backwards its batch shard; the synced grad must equal the full-batch
# gradient (reference Reducer semantics)
paddle.seed(5)
net = paddle.nn.Linear(8, 8)
dpm = paddle.DataParallel(net)
xfull = np.random.RandomState(7).randn(4, 8).astype(np.float32)
shard = paddle.to_tensor(xfull[rank * 2:(rank + 1) * 2])
paddle.mean(dpm(shard) ** 2).backward()
paddle.seed(5)
ref = paddle.nn.Linear(8, 8)
paddle.mean(ref(paddle.to_tensor(xfull)) ** 2).backward()
np.testing.assert_allclose(net.weight.grad.numpy(),
                           ref.weight.grad.numpy(), rtol=1e-5, atol=1e-6)

# --- one sharded llama train step over the global 2-process mesh ---
from jax.sharding import PartitionSpec as P

from paddle_tpu.models import llama
from paddle_tpu.parallel import create_hybrid_mesh, host_to_global

mesh = create_hybrid_mesh(dp=2, mp=4)  # dp axis spans the two processes
cfg = llama.LlamaConfig.tiny()
params = llama.init_params(cfg)
opt = llama.init_opt_state(params)
ps = llama.param_specs(cfg)
os_ = llama.opt_state_specs(cfg)
gparams = {k: host_to_global(np.asarray(v), ps[k], mesh)
           for k, v in params.items()}
gopt = {
    "step": host_to_global(np.asarray(opt["step"]), P(), mesh),
    "m": {k: host_to_global(np.asarray(v), os_[k], mesh)
          for k, v in opt["m"].items()},
    "v": {k: host_to_global(np.asarray(v), os_[k], mesh)
          for k, v in opt["v"].items()},
}
tokens = np.random.RandomState(0).randint(
    0, cfg.vocab_size, (4, 64)).astype(np.int32)
gtok = host_to_global(tokens, P(("dp", "sharding"), None), mesh)
step = llama.make_sharded_train_step(cfg, mesh, lr=1e-3)
_, _, loss = step(gparams, gopt, gtok, gtok)
loss = float(np.asarray(loss.addressable_data(0)))
if rank == 0:
    print("SPMD-LLAMA-LOSS", repr(loss))
print("SPMD-WORKER-OK", rank)
"""


class TestMultiProcessSPMD:
    def test_launch_two_process_collectives_and_train_step(self, tmp_path):
        """The launcher->runtime->collective chain end to end (VERDICT r1
        item 3): the launcher spawns 2 workers; each joins the
        jax.distributed coordinator via init_parallel_env (4 virtual CPU
        devices per process -> 8 global), runs eager cross-process
        all_reduce/all_gather/broadcast/all_gather_object, then ONE sharded
        llama train step over a global dp=2 x mp=4 mesh. Rank 0's loss must
        match the same step computed single-process on this pytest
        process's own 8 local devices."""
        script = tmp_path / "spmd_worker.py"
        script.write_text(_SPMD_WORKER)
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            free_port = s.getsockname()[1]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        rc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2",
             "--master", f"127.0.0.1:{free_port}",
             "--log_dir", str(tmp_path / "log"), str(script)],
            cwd="/root/repo", env=env, timeout=600,
            capture_output=True, text=True)
        log0 = (tmp_path / "log" / "workerlog.0")
        log1 = (tmp_path / "log" / "workerlog.1")
        detail = "\n".join(
            p.read_text()[-3000:] for p in (log0, log1) if p.exists())
        assert rc.returncode == 0, f"launch failed:\n{detail}"
        text0 = log0.read_text()
        assert "SPMD-WORKER-OK 0" in text0, text0[-3000:]
        assert "SPMD-WORKER-OK 1" in log1.read_text()

        # single-process reference on this process's 8 local devices
        import re

        m = re.search(r"SPMD-LLAMA-LOSS (\S+)", text0)
        assert m, text0[-3000:]
        loss_mp = float(m.group(1))

        from spmd_util import single_process_llama_loss

        loss_sp = single_process_llama_loss(dp=2, mp=4)
        np.testing.assert_allclose(loss_mp, loss_sp, rtol=2e-5)


def test_native_tsan_stress():
    """ThreadSanitizer lane for the C++ runtime (SURVEY.md §5.2 race
    detection; VERDICT r2 partial row): builds the store + prefetch queue
    with -fsanitize=thread and hammers them from 12 threads. Any data
    race makes TSAN print a report and exit non-zero."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++ in this environment")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(["make", "-C", "native", "tsan"], cwd=root,
                          capture_output=True, text=True, timeout=300)
    out = proc.stdout + proc.stderr
    if proc.returncode != 0 and ("libtsan" in out or "cannot find -ltsan"
                                 in out or "fsanitize=thread" in out
                                 and "unrecognized" in out):
        pytest.skip("toolchain lacks ThreadSanitizer support")
    assert proc.returncode == 0, out[-2000:]
    assert "ThreadSanitizer" not in out, out[-2000:]
    assert "tsan_stress OK" in out

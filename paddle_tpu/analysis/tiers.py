"""Tier-transfer budget pass (r19, ISSUE 14 tentpole part c).

A memory tier is only a win while it moves LESS than it saves: a
restore that uploads more bytes than the request's own KV footprint, or
an import that copies a prefix bigger than the prefill it replaced,
would be a regression wearing a cache's clothes. This pass makes that
arithmetic enforceable, the budgets.py way:

* **per-request budget** — every request's billed tier traffic
  (``Request.tier_pages`` / ``tier_bytes``: restores + cross-replica
  imports stamped at admission) must satisfy ``tier_bytes <=
  pages_reserved x page_bytes`` (the request's own KV size — the §3n
  cost-model ceiling). ``tier_transfer_audit`` returns one violation
  string per offender.
* **conservation identities** — the tier's byte counters must agree
  with its page counters at exactly ``page_bytes`` per page (a drifted
  counter means a transfer went unmetered), and restores can never
  outnumber spills + imports (you cannot promote an entry that never
  left HBM; an entry stages once but may spill/restore many times).

The zero-extra-sync half of the tiered contract is enforced where sync
contracts live: ``SyncAudit`` over the tiered serve loop (the staging
D2H rides the per-segment event fetch, restores are dispatches), pinned
in tests/test_kv_tiers.py with allowed == segment fetches exactly.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["tier_transfer_audit", "tier_conservation_audit",
           "tiered_serve_audit"]


def tier_transfer_audit(requests, page_bytes: int) -> List[str]:
    """Per-request tier-budget check: bytes migrated for a request must
    not exceed the KV bytes the request itself spans. Empty list =
    within budget."""
    v: List[str] = []
    if page_bytes <= 0:
        return [f"page_bytes must be positive, got {page_bytes}"]
    for r in requests:
        kv_bytes = r.pages_reserved * page_bytes
        if r.tier_bytes > kv_bytes:
            v.append(f"request {r.rid}: tier bytes {r.tier_bytes} > "
                     f"KV size {kv_bytes} "
                     f"({r.pages_reserved} pages x {page_bytes} B)")
        if r.tier_pages > r.pages_reserved:
            v.append(f"request {r.rid}: {r.tier_pages} tier pages > "
                     f"{r.pages_reserved} reserved")
    return v


def tier_conservation_audit(tier_stats: dict) -> List[str]:
    """Counter-consistency check over a ``HostTier.stats()`` snapshot:
    bytes and pages must agree at page_bytes per page, and the host
    store can never hold more than its bound."""
    v: List[str] = []
    pb = tier_stats.get("page_bytes", 0)
    if pb <= 0:
        return ["tier stats carry no page_bytes"]
    for bkey, ckey in (("bytes_to_host", "stages"),
                       ("bytes_to_hbm", "restores"),
                       ("bytes_imported", "imports")):
        if tier_stats[bkey] % pb:
            v.append(f"{bkey} {tier_stats[bkey]} is not a multiple of "
                     f"page_bytes {pb} — an unmetered partial transfer")
    if tier_stats["pages_host"] > tier_stats["capacity_pages"]:
        v.append(f"host store holds {tier_stats['pages_host']} pages > "
                 f"capacity {tier_stats['capacity_pages']}")
    # an entry stages ONCE and may spill/restore many times, but every
    # restore promotes an entry a spill (or import) previously demoted
    if tier_stats["restores"] > (tier_stats["spills"]
                                 + tier_stats["imports"]):
        v.append(f"{tier_stats['restores']} restores > "
                 f"{tier_stats['spills']} spills + "
                 f"{tier_stats['imports']} imports — a promotion of an "
                 f"entry that never left HBM")
    return v


def tiered_serve_audit(requests, host_tier,
                       page_bytes: Optional[int] = None) -> List[str]:
    """The combined pass a lane/test runs after a tiered serve: the
    per-request budget + the tier's conservation identities."""
    pb = page_bytes if page_bytes is not None else host_tier.page_bytes()
    return (tier_transfer_audit(requests, pb)
            + tier_conservation_audit(host_tier.stats()))

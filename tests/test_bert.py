"""BERT/ERNIE encoder family tests (models/bert.py).

Mirrors the reference's PaddleNLP BERT pretraining tests: forward shape,
MLM loss decreases under the sharded train step, padding mask correctness,
and the hybrid-mesh (dp×mp) sharded step on the virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import bert
from paddle_tpu.parallel import create_hybrid_mesh, set_mesh


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    set_mesh(None)


def test_forward_shape():
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg)
    tokens = jnp.array(np.random.RandomState(0).randint(0, cfg.vocab_size,
                                                        (2, 16)), jnp.int32)
    logits = bert.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits, np.float32)))


def test_pad_mask_blocks_attention():
    """Padding keys must not influence real positions' encodings."""
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg)
    rng = np.random.RandomState(1)
    toks = rng.randint(0, cfg.vocab_size, (1, 16))
    t1 = jnp.array(toks, jnp.int32)
    t2 = jnp.array(np.concatenate([toks[:, :8], rng.randint(
        0, cfg.vocab_size, (1, 8))], axis=1), jnp.int32)  # differ in padding
    pad = jnp.array([[True] * 8 + [False] * 8])
    e1 = bert.encode(params, t1, cfg, pad_mask=pad)
    e2 = bert.encode(params, t2, cfg, pad_mask=pad)
    np.testing.assert_allclose(np.asarray(e1[:, :8]), np.asarray(e2[:, :8]),
                               rtol=1e-5, atol=1e-5)


def test_mlm_loss_ignores_unmasked():
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg)
    tokens = jnp.array(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)), jnp.int32)
    all_ignore = jnp.full((2, 16), bert.IGNORE_INDEX, jnp.int32)
    labels = all_ignore.at[:, 3].set(tokens[:, 3])
    loss = bert.loss_fn(params, tokens, labels, cfg)
    # only position 3 scored — must equal per-position CE there
    logits = bert.forward(params, tokens, cfg).astype(jnp.float32)
    logz = jax.nn.logsumexp(logits[:, 3], axis=-1)
    gold = jnp.take_along_axis(logits[:, 3], tokens[:, 3][:, None],
                               axis=-1)[:, 0]
    np.testing.assert_allclose(float(loss), float(jnp.mean(logz - gold)),
                               rtol=1e-5)


def test_train_step_learns():
    cfg = bert.BertConfig.tiny()
    mesh = create_hybrid_mesh(devices=jax.devices()[:1])
    params = bert.init_params(cfg)
    opt = bert.init_opt_state(params)
    tokens, labels = bert.random_mlm_batch(cfg, batch=4, seq=32, seed=0)
    step = bert.make_sharded_train_step(cfg, mesh, lr=5e-3)
    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_hybrid_mesh_train_step():
    """dp×mp sharded step on the virtual 8-CPU mesh (TP + ZeRO-3)."""
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs >=4 devices")
    cfg = bert.BertConfig.tiny(sharding_stage=3)
    mesh = create_hybrid_mesh(dp=2, mp=2, devices=jax.devices()[:4])
    params = bert.init_params(cfg)
    opt = bert.init_opt_state(params)
    tokens, labels = bert.random_mlm_batch(cfg, batch=4, seq=32, seed=0)
    step = bert.make_sharded_train_step(cfg, mesh, lr=1e-3)
    params, opt, loss = step(params, opt, tokens, labels)
    assert np.isfinite(float(loss))

    # parity with single-device execution
    set_mesh(None)
    cfg1 = bert.BertConfig.tiny()
    mesh1 = create_hybrid_mesh(devices=jax.devices()[:1])
    p1 = bert.init_params(cfg1)
    o1 = bert.init_opt_state(p1)
    step1 = bert.make_sharded_train_step(cfg1, mesh1, lr=1e-3)
    _, _, loss1 = step1(p1, o1, tokens, labels)
    np.testing.assert_allclose(float(loss), float(loss1), rtol=2e-4)

"""LLaMA-family decoder — the flagship pretraining workload, TPU-first.

Reference counterpart: PaddleNLP's LLaMA with Fleet hybrid parallel
(BASELINE config 4: "LLaMA-7B with Fleet sharding stage2/3 + tensor-parallel
(c_allgather/reduce_scatter)"), built on the reference's
``ColumnParallelLinear``/``RowParallelLinear``/``VocabParallelEmbedding``
(``python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py``,
SURVEY.md §2.2) and flash-attention fused kernels (§2.1).

TPU-native design decisions (NOT a port):

* **One pure function** for the whole train step, jitted over a hybrid
  ``Mesh`` — XLA GSPMD inserts the all-gathers/reduce-scatters the reference
  codes by hand as ``c_*`` ops.
* **Scan over layers**: per-layer weights are stacked on a leading ``L`` axis
  and the decoder is a ``jax.lax.scan`` — O(1) compile time in depth, and the
  leading axis doubles as the pipeline-stage axis for PP.
* **Sharding rules, not collectives**: Megatron TP is expressed as
  PartitionSpecs (column-parallel = shard output dim on ``mp``, row-parallel
  = shard input dim on ``mp``, vocab-parallel embedding = shard vocab) plus
  activation constraints; ZeRO (sharding stage 1/2/3) is PartitionSpecs on
  optimizer state / params over ``('dp','sharding')``.
* **bf16 compute, fp32 master weights** — AMP-O2 with master weights
  (reference: ``paddle.amp`` O2 + ``GradScaler``; bf16 needs no loss scale).
* **Remat** (``jax.checkpoint``) per layer = the reference's
  ``fleet.recompute`` activation checkpointing.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.pallas.flash_attention import (
    dot_product_attention,
    flash_path_active as _flash_path_active,
)
from ..parallel.mesh import with_sharding_constraint as wsc


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    # ZeRO level for optimizer/param sharding over the ('dp','sharding') axes:
    # 1 = shard opt states, 2 = (+grads, implicit in jit), 3 = shard params too
    sharding_stage: int = 1
    remat: bool = True
    # scan_layers=True: decoder as lax.scan over stacked weights — O(1)
    # compile depth, the right shape for deep models and the pp axis.
    # scan_layers=False: python-unrolled layers — XLA saves residuals as
    # plain buffers with NO scan dynamic-update-slice stacking machinery;
    # measured ~20% faster on the bert-base-budget single-chip workload
    # (usually paired with remat=False when activations fit HBM).
    scan_layers: bool = True
    # sequence parallel: shard activations' seq dim over 'sep' outside matmuls
    sequence_parallel: bool = False
    # which SP attention formulation carries the sep axis (r7, mirroring
    # the reference's two SP implementations): "ring" = K/V blocks
    # ppermute around the sep ring with online-softmax merging; "ulysses"
    # = two all-to-alls reshard seq-parallel activations head-parallel,
    # exact attention per rank (cheaper when 2*|q| < (n-1)*|kv| — MHA at
    # moderate sep; GQA favours the ring). Both fall back dense when the
    # axis is absent or shapes don't divide.
    sp_impl: str = "ring"
    # single-chip chunked cross-entropy: head+CE recomputed per batch-chunk
    # so [B,S,V] logits never materialise (0 = off; see loss_fn)
    ce_chunks: int = 0
    # perf experiment knob: comma-joined set of backward-cotangent barrier
    # sites ('mlp', 'qkv', 'logits') — forces the named cotangents to
    # MATERIALISE once instead of letting XLA re-fuse their elementwise
    # chains into both consumer dots (dW and dx). See _barrier_grad.
    bwd_barriers: str = ""
    # store wq/wk/wv as ONE stacked [H, H+2*Hkv] matrix and w_gate/w_up
    # as [H, 2F]: one projection dot with a wider N instead of three/two
    # (fewer MXU ramp-ups, one dW instead of three in the bwd). The split
    # into q/k/v (gate/up) is a free minor-dim slice of the dot output.
    # r3's measured LOSS on this idea concatenated the weights PER STEP;
    # storing them fused removes that cost from the step entirely.
    fused_weights: bool = False
    # AMP-O2 gradient dtype: differentiate w.r.t. the bf16 param VIEW so
    # grads stay bf16 end-to-end (half the HBM traffic in the dW writes,
    # global-norm pass, and AdamW reads); the fp32 master weights are only
    # touched by the optimizer. Matches the reference's O2 GradScaler
    # contract (fp16/bf16 grads + fp32 master params).
    bf16_grads: bool = False
    # decode-tick fusion: on the KV-cache single-token path, collapse the
    # between-matmul small-op chains (rms, rope, residual+norm) into one
    # Pallas op each and run attention as the RAGGED kernel that reads
    # only KV rows [0, pos] per slot instead of the full max_len window
    # (ops/pallas/decode_attention.py, tick_fusion.py). Dispatch falls
    # back to the inline jnp chains off-TPU / under a mesh / on
    # non-tileable shapes — identical math either way.
    fused_tick_epilogue: bool = True
    # custom-VJP head+CE tail (single-chip, non-chunked path only): the
    # backward picks each dot's MXU orientation independently — dx runs
    # as (W @ dlogits^T)^T, the wide-N transpose formulation a bare-dot
    # microbench clocks at ~96% of peak vs ~60% for autodiff's
    # dlogits @ W^T (benchmarks/dot_variants.py); the softmax recompute
    # stays fused inside both bwd dots (no [M,V] cotangent materialises),
    # and dx's one-hot term becomes a cheap GATHER of W columns at the
    # target ids instead of a mask pass.
    ce_tail_custom: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw):
        """Tiny config for tests / compile-checks."""
        d = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                 num_layers=2, num_heads=4, num_kv_heads=4, max_seq_len=64,
                 dtype=jnp.float32, remat=False)
        d.update(kw)
        return cls(**d)

    @classmethod
    def bert_base_equiv(cls, **kw):
        """~110M decoder matching BERT/ERNIE-base budget (BASELINE config 2).

        Unrolled + no remat: at this depth/width the activations fit HBM
        alongside the optimizer, and skipping both the recompute FLOPs and
        the scan residual-stacking copies is worth ~25% step time."""
        d = dict(vocab_size=32000, hidden_size=768, intermediate_size=3072,
                 num_layers=12, num_heads=12, num_kv_heads=12, max_seq_len=512,
                 remat=False, scan_layers=False, ce_tail_custom=True)
        d.update(kw)
        return cls(**d)

    @classmethod
    def cpu_small(cls, **kw):
        """~3M-param decoder: the serving benchmarks' CPU-tractable shape
        (the chip lane runs bert_base_equiv; off-chip artifact runs record
        this model so scheduling behaviour — not matmul speed — is what
        the numbers exercise). Unrolled+fp32 like bert_base_equiv so the
        same decode code paths run."""
        d = dict(vocab_size=2048, hidden_size=128, intermediate_size=512,
                 num_layers=4, num_heads=8, num_kv_heads=8, max_seq_len=512,
                 dtype=jnp.float32, remat=False, scan_layers=False)
        d.update(kw)
        return cls(**d)

    @classmethod
    def llama7b(cls, **kw):
        return cls(**kw)  # defaults above are 7B


# ---------------------------------------------------------------------------
# Sharding rules (Megatron TP + ZeRO over the hybrid mesh axes)
# ---------------------------------------------------------------------------

def param_specs(cfg: LlamaConfig) -> Dict[str, P]:
    """PartitionSpec per parameter. Leading axis of ``layers/*`` is the
    stacked layer axis (scanned; sharded over 'pp' when pipelining).

    TP mapping (reference mp_layers.py → specs):
      VocabParallelEmbedding → embed sharded on vocab over mp
      ColumnParallelLinear (wq/wk/wv/w1/w3) → output-dim over mp
      RowParallelLinear   (wo/w2)           → input-dim over mp
    ZeRO stage 3 additionally shards the non-mp dim over ('dp','sharding').
    """
    zdim = ("dp", "sharding") if cfg.sharding_stage >= 3 else None
    specs = {
        "embed": P("mp", zdim),                    # [V, H]
        "wq": P(None, zdim, "mp"),                 # [L, H, H]
        "wk": P(None, zdim, "mp"),                 # [L, H, Hkv]
        "wv": P(None, zdim, "mp"),                 # [L, H, Hkv]
        "wo": P(None, "mp", zdim),                 # [L, H, H]
        "w_gate": P(None, zdim, "mp"),             # [L, H, F]
        "w_up": P(None, zdim, "mp"),               # [L, H, F]
        "w_down": P(None, "mp", zdim),             # [L, F, H]
        "ln_attn": P(None, None),                  # [L, H]
        "ln_mlp": P(None, None),                   # [L, H]
        "ln_f": P(None),                           # [H]
        "lm_head": P(zdim, "mp"),                  # [H, V]
    }
    return _fuse_keys(cfg, specs)


def _fuse_keys(cfg: "LlamaConfig", d: Dict[str, Any]) -> Dict[str, Any]:
    """Rewrite a per-key dict to the fused_weights param tree: wq/wk/wv →
    wqkv, w_gate/w_up → w_gate_up (the fused matrices share wq's spec —
    the stacked minor dim stays the 'column' TP dim)."""
    if not cfg.fused_weights:
        return d
    out = {k: v for k, v in d.items()
           if k not in ("wq", "wk", "wv", "w_gate", "w_up")}
    out["wqkv"] = d["wq"]
    out["w_gate_up"] = d["w_gate"]
    return out


def opt_state_specs(cfg: LlamaConfig) -> Dict[str, P]:
    """ZeRO stage>=1: Adam moments sharded over ('dp','sharding') on the
    first shardable dim (reference: DygraphShardingOptimizer /
    GroupShardedOptimizerStage2 shard optimizer states)."""
    if cfg.sharding_stage < 1:
        return param_specs(cfg)
    z = ("dp", "sharding")
    return _fuse_keys(cfg, {
        "embed": P("mp", z),
        "wq": P(None, z, "mp"),
        "wk": P(None, z, "mp"),
        "wv": P(None, z, "mp"),
        "wo": P(None, "mp", z),
        "w_gate": P(None, z, "mp"),
        "w_up": P(None, z, "mp"),
        "w_down": P(None, "mp", z),
        "ln_attn": P(None, z),
        "ln_mlp": P(None, z),
        "ln_f": P(z),
        "lm_head": P(z, "mp"),
    })


def init_params(cfg: LlamaConfig, key: Optional[jax.Array] = None,
                dtype: Any = None) -> Dict[str, jax.Array]:
    """Initialise the parameter pytree (fp32 master weights)."""
    if key is None:
        key = jax.random.PRNGKey(0)
    dtype = dtype or jnp.float32
    H, F, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_layers)
    Hkv = cfg.num_kv_heads * cfg.head_dim
    ks = jax.random.split(key, 12)
    s = lambda fan_in: 1.0 / np.sqrt(fan_in)
    n = jax.random.normal
    out = {
        "embed": (n(ks[0], (V, H)) * 0.02).astype(dtype),
        "wq": (n(ks[1], (L, H, H)) * s(H)).astype(dtype),
        "wk": (n(ks[2], (L, H, Hkv)) * s(H)).astype(dtype),
        "wv": (n(ks[3], (L, H, Hkv)) * s(H)).astype(dtype),
        "wo": (n(ks[4], (L, H, H)) * s(H)).astype(dtype),
        "w_gate": (n(ks[5], (L, H, F)) * s(H)).astype(dtype),
        "w_up": (n(ks[6], (L, H, F)) * s(H)).astype(dtype),
        "w_down": (n(ks[7], (L, F, H)) * s(F)).astype(dtype),
        "ln_attn": jnp.ones((L, H), dtype),
        "ln_mlp": jnp.ones((L, H), dtype),
        "ln_f": jnp.ones((H,), dtype),
        "lm_head": (n(ks[8], (H, V)) * s(H)).astype(dtype),
    }
    if cfg.fused_weights:
        out["wqkv"] = jnp.concatenate(
            [out.pop("wq"), out.pop("wk"), out.pop("wv")], axis=-1)
        out["w_gate_up"] = jnp.concatenate(
            [out.pop("w_gate"), out.pop("w_up")], axis=-1)
    return out


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rms_norm(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


@jax.custom_vjp
def _barrier_grad(x):
    """Identity whose COTANGENT is fenced with an optimization_barrier.

    XLA fuses an elementwise backward chain (silu', rope shuffles, softmax
    recompute) into EVERY consumer dot's operand window, re-running it per
    dot; fencing the cotangent forces one materialisation that both the dW
    and dx dots then read. Whether that trade wins is shape-dependent —
    gate it with LlamaConfig.bwd_barriers and measure (benchmarks/perf_lab)."""
    return x


_barrier_grad.defvjp(lambda x: (x, None),
                     lambda _, g: (jax.lax.optimization_barrier(g),))


def _rope_at(x, theta, positions):
    # x: [B, S, H, D] at absolute ``positions`` — [S] (shared across the
    # batch) or [B, S] (ragged decode: every slot at its own position).
    # LLaMA rotate-half convention: the head dim splits into two contiguous
    # halves (lane-aligned slices on TPU — the strided ::2 interleave costs
    # extra vector shuffles every layer and again in every remat replay)
    b, s, h, d = x.shape
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # [..., S, D/2]
    if ang.ndim == 2:  # shared positions -> add the batch dim
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _rope(x, theta):
    return _rope_at(x, theta, jnp.arange(x.shape[1]))


def _act_spec(cfg: LlamaConfig) -> P:
    # activations: batch over (dp, sharding-as-extra-dp), seq over sep when SP
    seq = "sep" if cfg.sequence_parallel else None
    return P(("dp", "sharding"), seq, None)


def _w(p, name, dt):
    """Weight ``name`` from a param/layer dict at compute dtype ``dt``.

    Quantized serving trees (quantization/serving.py) store matmul
    weights narrow (int8/fp8) with a companion per-output-channel
    ``<name>_scale`` fp32 plane; the dense dequantize here sits
    adjacent to the consuming dot so XLA fuses convert+scale into the
    operand read — the CPU/mesh fallback of the in-kernel-dequant
    Pallas path (see ``_mm``). fp trees pass straight through."""
    sc = p.get(name + "_scale")
    if sc is None:
        return p[name].astype(dt)
    return (p[name].astype(jnp.float32)
            * sc.astype(jnp.float32)[..., None, :]).astype(dt)


def _mm(h, p, name, dt):
    """``h @ weight[name]`` — the one projection-matmul site shared by
    fp and quantized param trees. On the 2D decode tick with a narrow
    weight, dispatch to the Pallas quant matmul (HBM streams the
    narrow dtype; dequant and fp32 accumulation happen in VMEM —
    ops/pallas/tick_fusion.py); everywhere else the dense
    dequantize-then-dot is the same math."""
    sc = p.get(name + "_scale")
    if sc is None:
        return h @ p[name].astype(dt)
    w = p[name]
    if h.ndim == 2 and w.ndim == 2:
        from ..ops.pallas.tick_fusion import (quant_matmul,
                                              quant_matmul_active)

        if quant_matmul_active(w.shape[0], w.shape[1]):
            return quant_matmul(h, w, sc).astype(dt)
    return h @ _w(p, name, dt)


def layer_params(params, cfg: "LlamaConfig"):
    """Per-layer stacked weights for the forward paths: ``layer_keys``
    plus any companion quantization ``_scale`` planes (stacked on the
    same leading [L] axis, so they scan/slice identically)."""
    out = {}
    for kk in layer_keys(cfg):
        out[kk] = params[kk]
        if kk + "_scale" in params:
            out[kk + "_scale"] = params[kk + "_scale"]
    return out


def _qkv_proj(cfg: LlamaConfig, x, lp, positions=None):
    """rms → q/k/v projections → rope at ``positions`` (default 0..S-1).
    Returns q [B,S,nH,D] and UNREPEATED k/v [B,S,Hkv,D] — the single
    source of the attention input convention for both training and the
    KV-cache decode path."""
    B, S, H = x.shape
    dt = x.dtype
    if positions is None:
        positions = jnp.arange(S)
    h = _rms_norm(x, lp["ln_attn"], cfg.rms_eps)
    Hq = cfg.num_heads * cfg.head_dim
    Hkv = cfg.num_kv_heads * cfg.head_dim
    if cfg.fused_weights:
        z = _mm(h, lp, "wqkv", dt)
        zq, zk, zv = (z[..., :Hq], z[..., Hq:Hq + Hkv], z[..., Hq + Hkv:])
    else:
        zq = _mm(h, lp, "wq", dt)
        zk = _mm(h, lp, "wk", dt)
        zv = _mm(h, lp, "wv", dt)
    if "qkv" in cfg.bwd_barriers:
        zq, zk, zv = map(_barrier_grad, (zq, zk, zv))
    q = zq.reshape(B, S, cfg.num_heads, cfg.head_dim)
    k = zk.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    v = zv.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    q = _rope_at(q, cfg.rope_theta, positions)
    k = _rope_at(k, cfg.rope_theta, positions)
    return q, k, v


def _layer_qkv(cfg: LlamaConfig, x, lp):
    """Pre-attention half of a block: rms → qkv projections → rope → GQA."""
    q, k, v = _qkv_proj(cfg, x, lp)
    if cfg.num_kv_heads != cfg.num_heads:  # GQA: repeat kv heads
        rep = cfg.num_heads // cfg.num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # heads are mp-sharded (follows from wq's output sharding); under SP
    # the seq dim STAYS sep-sharded — pinning it replicated here would
    # all-gather the sequence right before the ring attention
    seq_ax = "sep" if cfg.sequence_parallel else None
    q = wsc(q, P(("dp", "sharding"), seq_ax, "mp", None))
    return q, k, v


def _layer_post(cfg: LlamaConfig, x, attn, lp):
    """Post-attention half: output projection, residual, mlp."""
    B, S, H = x.shape
    dt = x.dtype
    attn = attn.reshape(B, S, H)
    x = x + wsc(_mm(attn, lp, "wo", dt), _act_spec(cfg))
    h = _rms_norm(x, lp["ln_mlp"], cfg.rms_eps)
    if cfg.fused_weights:
        F_ = cfg.intermediate_size
        zz = _mm(h, lp, "w_gate_up", dt)
        zg, up = zz[..., :F_], zz[..., F_:]
    else:
        zg = _mm(h, lp, "w_gate", dt)
        up = _mm(h, lp, "w_up", dt)
    if "mlp" in cfg.bwd_barriers:
        zg = _barrier_grad(zg)
        up = _barrier_grad(up)
    gate = jax.nn.silu(zg)
    x = x + wsc(_mm(gate * up, lp, "w_down", dt), _act_spec(cfg))
    return x


def _attention(cfg: LlamaConfig, q, k, v):
    """Training attention dispatch: under sequence parallelism with a >1
    'sep' axis the seq dim is SHARDED, so attention must be the RING
    (context-parallel) formulation — K/V blocks ppermute around the sep
    ring with online-softmax merging — instead of letting GSPMD all-gather
    the whole sequence onto every device. The axis/divisibility fallback
    lives in context_parallel_attention itself (one guard, not two)."""
    if cfg.sequence_parallel:
        from ..ops.pallas.ring_attention import (
            context_parallel_attention, ulysses_parallel_attention)

        sp_fn = {"ring": context_parallel_attention,
                 "ulysses": ulysses_parallel_attention}[cfg.sp_impl]
        return sp_fn(
            q, k, v, axis_name="sep", is_causal=True,
            batch_axes=("dp", "sharding"), head_axes="mp",
            fallback=lambda: dot_product_attention(q, k, v, is_causal=True))
    return dot_product_attention(q, k, v, is_causal=True)


def _decoder_layer(cfg: LlamaConfig, x, lp):
    """One transformer block. x: [B, S, H]; lp: this layer's weight slice."""
    q, k, v = _layer_qkv(cfg, x, lp)
    attn = _attention(cfg, q, k, v)
    return _layer_post(cfg, x, attn, lp)


def forward_hidden(params: Dict[str, jax.Array], tokens: jax.Array,
                   cfg: LlamaConfig) -> jax.Array:
    """Final hidden states (post ln_f). tokens: [B, S] int32 → [B, S, H]."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    x = wsc(x, _act_spec(cfg))

    layer_weights = {k: params[k] for k in layer_keys(cfg)}

    if cfg.remat and _flash_path_active():
        # Flash-path remat structure: checkpoint the two matmul halves but
        # keep attention OUTSIDE the remat region, so the flash custom-VJP's
        # O(S) residuals (q/k/v/out/logsumexp) are saved rather than the
        # forward kernel re-running inside the backward scan. The halves
        # still fully remat: saving their matmul outputs measures neutral
        # (the save/reload HBM traffic ≈ the recompute cost at this scale)
        # while costing ~2.4 GB — recompute is the better trade.
        qkv_part = jax.checkpoint(functools.partial(_layer_qkv, cfg))
        post_part = jax.checkpoint(functools.partial(_layer_post, cfg))

        def body(x, lp):
            q, k, v = qkv_part(x, lp)
            attn = _attention(cfg, q, k, v)
            return post_part(x, attn, lp), None
    else:
        def body(x, lp):
            return _decoder_layer(cfg, x, lp), None

        if cfg.remat:
            body = jax.checkpoint(body)  # fleet.recompute analog

    if cfg.scan_layers:
        x, _ = jax.lax.scan(body, x, layer_weights)
    else:
        # python-unrolled: static per-layer slices, no scan stacking copies
        for i in range(cfg.num_layers):
            x, _ = body(x, {k: w[i] for k, w in layer_weights.items()})

    return _rms_norm(x, params["ln_f"], cfg.rms_eps)


def forward(params: Dict[str, jax.Array], tokens: jax.Array,
            cfg: LlamaConfig) -> jax.Array:
    """Logits for next-token prediction. tokens: [B, S] int32 → [B, S, V]."""
    x = forward_hidden(params, tokens, cfg)
    logits = x @ params["lm_head"].astype(cfg.dtype)
    return wsc(logits, P(("dp", "sharding"), None, "mp"))


def _nll_sum(logits, targets, weights) -> jax.Array:
    """Weighted token-nll sum over one logits block.

    The reduction upcasts to fp32 INSIDE the fused pass over the bf16
    logits: casting the whole [.., V] tensor first would materialise fp32
    holding bf16-precision values — pure HBM traffic for zero accuracy
    (the matmul already rounded to bf16)."""
    # stop_gradient on the max: lse's gradient (softmax) is exact for any
    # constant shift, and differentiating through jnp.max would cost an
    # extra [.., V] equality-mask pass plus an add_any combine in the bwd
    # (measured ~4.5 ms/step at the bench shape). Hand-written VJPs LOSE
    # here: an iota-onehot custom backward is +1.6 ms (the mask pass
    # outweighs the saved cotangent combine), scatter-based backwards are
    # +21..+50 ms (TPU scatters serialize). Autodiff of this exact form is
    # the measured optimum.
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1).astype(jnp.float32))
    sumexp = jnp.sum(
        jnp.exp(logits.astype(jnp.float32) - m[..., None]), axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return jnp.sum((m + jnp.log(sumexp) - gold) * weights)


@jax.custom_vjp
def _head_ce_tail(h2, W, targets, wgt):
    """lm_head matmul + weighted token-nll SUM with a hand-picked backward.

    Forward math is bit-identical to ``_nll_sum(h2 @ W, targets, wgt)``;
    ``wgt`` [T] row-weights let the caller score ALL S positions with a
    zero on the last (no next-token label) — keeping the token dim a
    multiple of the pallas block so the kernel sees no ragged edge (a
    non-divisible M makes pallas materialise a PADDED copy of the 1.4 GB
    logits, measured 6.7 ms/step). The backward differs from autodiff
    only in SCHEDULING (same algebra):

    - the dx softmax term is a hand-written pallas kernel
      (ops/pallas/head_dx.py): softmax computed in-kernel from natural-
      layout logits tiles, tile-dots against a pre-transposed W with an
      fp32 VMEM accumulator (in-step 6.0 ms vs autodiff's 7.3 ms at the
      bench shape). Its one-hot term is a GATHER of W columns at the
      target ids (34 MB) — scatter-free.
    - dW keeps autodiff's wide-N orientation; its one-hot term is an
      in-tile iota mask fused into the dot's operand read.
    - the softmax recompute never materialises an [M, V] cotangent
      (saving one would cost ~1.8 ms of HBM at the bench shape).
    """
    return _nll_sum(h2 @ W.astype(h2.dtype), targets, wgt[None, :])


def _head_ce_tail_fwd(h2, W, targets, wgt):
    logits = h2 @ W.astype(h2.dtype)
    m = jax.lax.stop_gradient(
        jnp.max(logits, axis=-1).astype(jnp.float32))
    se = jnp.sum(
        jnp.exp(logits.astype(jnp.float32) - m[..., None]), axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    out = jnp.sum((m + jnp.log(se) - gold) * wgt[None, :])
    return out, (h2, W, logits, m, se, targets, wgt)


def _head_ce_tail_bwd(res, gs):
    h2, W, logits, m, se, targets, wgt = res
    B, T, H = h2.shape
    V = logits.shape[-1]
    dt = h2.dtype
    M = B * T
    lf = logits.reshape(M, V)
    mf, sef, tf = m.reshape(M), se.reshape(M), targets.reshape(M)
    gsf = jnp.asarray(gs, jnp.float32)
    # per-row cotangent scale: gs * row-weight / sumexp feeds the softmax
    # terms; gs * row-weight scales the one-hot terms
    wf = jnp.broadcast_to(wgt[None, :], (B, T)).reshape(M)
    gw = gsf * wf
    Wd = W.astype(dt)

    # dx softmax term. On TPU this is the hand-written pallas kernel
    # (ops/pallas/head_dx.py): softmax computed in-kernel from natural-
    # layout logits tiles, tile-dots against a pre-transposed W with an
    # fp32 VMEM accumulator. XLA-level alternatives all lose (r5 ledger):
    # autodiff's orientation runs the dot at ~60-77% of peak, and every
    # transpose-orientation rewrite forces a >=1.4 GB materialisation
    # (the algebraic simplifier folds dot^T back, and a transposing
    # consumer cannot fuse the convert chain) that outweighs the win.
    from .. import flags
    from ..ops.pallas.flash_attention import _on_tpu
    from ..ops.pallas.head_dx import head_dx_softmax

    use_kernel = (
        _on_tpu()
        and flags.get_flags("use_pallas_kernels")["use_pallas_kernels"])
    if use_kernel:
        dh_soft = head_dx_softmax(lf, mf, gw / sef, Wd.T)
    else:
        p = (jnp.exp(lf.astype(jnp.float32) - mf[:, None])
             * (gw / sef)[:, None]).astype(dt)
        dh_soft = p @ Wd.T
    gold_rows = (jnp.take(Wd, tf, axis=1).T.astype(jnp.float32)
                 * gw[:, None]).astype(dt)                # [M, H]
    dh = (dh_soft - gold_rows).reshape(B, T, H)

    # dW: autodiff's wide-N orientation; one-hot as an in-tile iota mask
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (M, V), 1)
              == tf[:, None])
    dlog = ((jnp.exp(lf.astype(jnp.float32) - mf[:, None]) / sef[:, None]
             - onehot.astype(jnp.float32)) * gw[:, None]).astype(dt)
    dW = jax.lax.dot_general(h2.reshape(M, H), dlog,
                             (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32
                             ).astype(W.dtype)            # [H, V]
    return dh, dW, None, None


_head_ce_tail.defvjp(_head_ce_tail_fwd, _head_ce_tail_bwd)


def loss_fn(params, tokens, labels, cfg: LlamaConfig) -> jax.Array:
    """Next-token cross entropy (the reference's ``ParallelCrossEntropy`` /
    ``c_softmax_with_cross_entropy`` — here the vocab-sharded logsumexp
    reduction is a GSPMD-inserted collective).

    Single-chip, the head+CE is chunked over the batch dim with the chunk
    body ``jax.checkpoint``-ed: the [B,S,V] logits tensor (1.5 GB at the
    bench shape) is never materialised and never saved for the backward —
    each chunk's logits are recomputed from the (small) hidden states in
    the bwd, trading ~1.2 TF of recompute for ~5 passes of HBM traffic
    (measured worth ~4 ms/step at bert-base batch 48). Multi-device meshes
    keep the unchunked form: GSPMD owns the vocab-parallel layout there.

    ``labels`` is the same [B, S] token stream; the shift happens HERE:
    position i's logits are scored against labels[i+1]."""
    h = forward_hidden(params, tokens, cfg)
    dt = cfg.dtype
    B, S, _ = h.shape
    nc = cfg.ce_chunks
    from ..parallel.mesh import get_mesh

    mesh = get_mesh()
    multi = mesh is not None and mesh.size > 1
    if nc and not multi and B % nc == 0:
        W = params["lm_head"].astype(dt)
        # pad the shifted targets so every position has a label; the pad
        # column carries weight 0 (exactly the reference's shift+mean)
        targets = jnp.concatenate(
            [labels[:, 1:], jnp.zeros((B, 1), labels.dtype)], axis=1)
        wgt = jnp.concatenate(
            [jnp.ones((S - 1,), jnp.float32), jnp.zeros((1,), jnp.float32)])
        hc = h.reshape(nc, B // nc, S, h.shape[-1])
        tc = targets.reshape(nc, B // nc, S)
        logit_bar = ("logits" in cfg.bwd_barriers)
        body = jax.checkpoint(
            lambda hcb, tcb: _nll_sum(
                _barrier_grad(hcb @ W) if logit_bar else hcb @ W,
                tcb, wgt[None, :]))
        total = jnp.float32(0.0)
        for i in range(nc):
            total = total + body(hc[i], tc[i])
        return total / (B * (S - 1))
    if cfg.ce_tail_custom and not multi:
        # custom-VJP tail: same forward math, hand-scheduled backward
        # (see _head_ce_tail) — single-chip only (the mesh path needs
        # the wsc sharding constraint + GSPMD's vocab-sharded CE). ALL S
        # positions are scored with weight 0 on the last: B*S is a
        # multiple of the pallas dx block, so the kernel sees no ragged
        # edge (a padded-copy of the logits costs 6.7 ms — r5 ledger).
        targets = jnp.concatenate(
            [labels[:, 1:], jnp.zeros((B, 1), labels.dtype)], axis=1)
        wgt = jnp.ones((S,), jnp.float32).at[-1].set(0.0)
        total = _head_ce_tail(h, params["lm_head"], targets, wgt)
        return total / (B * (S - 1))
    # slice h BEFORE the head matmul: slicing the [B,S,V] product instead
    # would materialise a second ~1.5 GB logits copy (the last position
    # has no next-token label and needn't be scored at all)
    logits = wsc(h[:, :-1] @ params["lm_head"].astype(dt),
                 P(("dp", "sharding"), None, "mp"))
    if "logits" in cfg.bwd_barriers:
        logits = _barrier_grad(logits)
    targets = labels[:, 1:]
    return _nll_sum(logits, targets, jnp.float32(1.0)) / (B * (S - 1))


# ---------------------------------------------------------------------------
# Training step (AdamW, fp32 master weights, ZeRO via sharding specs)
# ---------------------------------------------------------------------------

def init_opt_state(params):
    zeros = lambda p: jnp.zeros_like(p)
    return {
        "step": jnp.zeros((), jnp.int32),
        "m": jax.tree.map(zeros, params),
        "v": jax.tree.map(zeros, params),
    }


NO_DECAY_KEYS = ("ln_attn", "ln_mlp", "ln_f", "embed")

# per-layer stacked weights (leading [L] axis) — the one list both the
# training forward and the KV-cache decode path slice from
_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
               "ln_attn", "ln_mlp")
_LAYER_KEYS_FUSED = ("wqkv", "wo", "w_gate_up", "w_down",
                     "ln_attn", "ln_mlp")


def layer_keys(cfg: LlamaConfig):
    return _LAYER_KEYS_FUSED if cfg.fused_weights else _LAYER_KEYS


def adamw_update(params, grads, opt_state, lr=3e-4, beta1=0.9, beta2=0.95,
                 eps=1e-8, weight_decay=0.1, no_decay_keys=None):
    """Fused-AdamW analog: one jitted tree-wide update (the reference's
    multi-tensor fused_adamw kernel; XLA fuses the per-leaf lambdas).
    Norm gains and the embedding are excluded from decay (the reference's
    ``apply_decay_param_fun`` convention); callers with different naming
    (e.g. models/bert.py) pass their own ``no_decay_keys``."""
    step = opt_state["step"] + 1
    t = step.astype(jnp.float32)
    c1 = 1.0 - jnp.power(beta1, t)
    c2 = 1.0 - jnp.power(beta2, t)

    def upd(wd, p, g, m, v):
        g = g.astype(jnp.float32)
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * (g * g)
        update = (m / c1) / (jnp.sqrt(v / c2) + eps) + wd * p
        return p - lr * update, m, v

    nd = NO_DECAY_KEYS if no_decay_keys is None else no_decay_keys
    wds = {k: 0.0 if k in nd else weight_decay for k in params}
    out = jax.tree.map(upd, wds, params, grads, opt_state["m"],
                       opt_state["v"])
    new_p = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
    new_m = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
    new_v = jax.tree.map(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
    return new_p, {"step": step, "m": new_m, "v": new_v}


def train_step(params, opt_state, tokens, labels, cfg: LlamaConfig,
               lr=3e-4):
    """One full step: fwd, bwd, global-norm clip, AdamW. Pure → jit it."""
    if cfg.bf16_grads:
        # differentiate w.r.t. the bf16 view: the fwd is numerically
        # IDENTICAL (every use site casts to cfg.dtype anyway) but the
        # cotangents stay bf16 — no [params]-sized fp32 convert pass
        diff = jax.tree.map(lambda p: p.astype(cfg.dtype)
                            if p.dtype == jnp.float32 else p, params)
        loss, grads = jax.value_and_grad(loss_fn)(diff, tokens, labels, cfg)
    else:
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels, cfg)
    # HybridParallelClipGrad analog: global norm across ALL parallel axes
    # (GSPMD reduces over every mesh axis for free)
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree.leaves(grads)))
    clip = jnp.minimum(1.0, 1.0 / (gnorm + 1e-6))
    # keep each leaf's dtype: a strong fp32 scalar would PROMOTE bf16
    # grads to fp32 (defeating bf16_grads' traffic contract)
    grads = jax.tree.map(lambda g: g * clip.astype(g.dtype), grads)
    params, opt_state = adamw_update(params, grads, opt_state, lr=lr)
    return params, opt_state, loss


def shard_state(cfg: LlamaConfig, mesh, params, opt_state=None):
    """device_put params (and opt state) to their canonical hybrid shardings
    (the reference's `shard_tensor`/placement step). Needed whenever arrays
    are already committed to devices with a different layout."""
    from jax.sharding import NamedSharding

    ps = {k: NamedSharding(mesh, v) for k, v in param_specs(cfg).items()}
    params = jax.device_put(params, ps)
    if opt_state is None:
        return params
    os_ = {k: NamedSharding(mesh, v) for k, v in opt_state_specs(cfg).items()}
    opt_state = {
        "step": jax.device_put(opt_state["step"], NamedSharding(mesh, P())),
        "m": jax.device_put(opt_state["m"], os_),
        "v": jax.device_put(opt_state["v"], os_),
    }
    return params, opt_state


def make_sharded_train_step(cfg: LlamaConfig, mesh, lr=3e-4):
    """jit the train step over ``mesh`` with the full hybrid shardings and
    donated param/opt buffers (in-place update semantics, TPU-style)."""
    from jax.sharding import NamedSharding

    ps = {k: NamedSharding(mesh, v) for k, v in param_specs(cfg).items()}
    os_spec = {k: NamedSharding(mesh, v) for k, v in opt_state_specs(cfg).items()}
    opt_sh = {"step": NamedSharding(mesh, P()), "m": os_spec, "v": os_spec}
    data_sh = NamedSharding(mesh, P(("dp", "sharding"), None))

    step = functools.partial(train_step, cfg=cfg, lr=lr)
    return jax.jit(
        step,
        in_shardings=(ps, opt_sh, data_sh, data_sh),
        out_shardings=(ps, opt_sh, NamedSharding(mesh, P())),
        donate_argnums=(0, 1),
    )


# ---------------------------------------------------------------------------
# KV-cache autoregressive decoding (inference). Reference: PaddleNLP's
# generation loop over the fused decode-attention kernels (SURVEY.md §2.4);
# here prefill and per-token decode are each ONE jitted program with the
# cache donated between steps, and the decode attention masks the padded
# cache tail instead of re-running the whole prefix.
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int,
                  dtype=None) -> Dict[str, jax.Array]:
    """Per-layer stacked K/V cache: [L, B, max_len, Hkv, D]."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def kv_cache_spec() -> P:
    """PartitionSpec of the serving KV cache [L, B, Smax, Hkv, D] under
    tensor-parallel serving (r12): the kv-head dim follows wk/wv's
    column-parallel output sharding over 'mp', so the decode tick's new
    K/V rows scatter into LOCAL shards and cache attention contracts
    per-shard — GSPMD inserts exactly one all-reduce per layer (after
    the row-parallel wo), none for the cache itself."""
    return P(None, None, None, "mp", None)


def paged_pool_spec() -> P:
    """PartitionSpec of the paged KV pool [L, pages, page, Hkv, D]:
    same rule as ``kv_cache_spec`` — pages replicate, heads shard, so
    the host-side page tables (pure int32 indices) stay replicated and
    page bookkeeping is unchanged under 'mp'."""
    return P(None, None, None, "mp", None)


def _cache_attention(cfg: LlamaConfig, q, kc, vc, positions):
    """q [B,T,nH,D] against the UNREPEATED cache kc/vc [B,Smax,Hkv,D].
    GQA contracts via a grouped einsum (q reshaped [B,T,Hkv,rep,D]) —
    the repeated cache is never materialised. Keys j > token position are
    masked (covers both causality and the unwritten cache tail).
    ``positions``: [T] shared, or [B, T] ragged (per-slot decode).

    Single-token decode (T=1) dispatches to the RAGGED Pallas kernel when
    shapes tile: each slot reads only ceil((pos+1)/block) KV blocks from
    HBM instead of the full static max_len window — the dense einsum
    below streams max_len rows per slot regardless of position, which at
    serving shapes is most of the tick's non-weight HBM traffic."""
    B, T, nH, D = q.shape
    if T == 1:
        from ..ops.pallas.decode_attention import (
            decode_attention_active, ragged_decode_attention)

        if decode_attention_active(kc.shape[1], cfg.num_heads,
                                   cfg.num_kv_heads, cfg.head_dim):
            pos_b = jnp.broadcast_to(
                jnp.reshape(jnp.asarray(positions)[..., 0], (-1,)),
                (B,)).astype(jnp.int32)
            return ragged_decode_attention(q[:, 0], kc, vc, pos_b)[:, None]
    return _dense_cache_attention(cfg, q, kc, vc, positions)


def _dense_cache_attention(cfg: LlamaConfig, q, kc, vc, positions):
    """The dense XLA formulation of cache attention (the dispatch
    fallback, shared by the contiguous and paged-gather paths)."""
    B, T, nH, D = q.shape
    Smax = kc.shape[1]
    rep = cfg.num_heads // cfg.num_kv_heads
    dt = q.dtype
    scale = 1.0 / np.sqrt(cfg.head_dim)
    qg = q.reshape(B, T, cfg.num_kv_heads, rep, D)
    s = jnp.einsum("bthrd,bshd->bhrts", qg, kc,
                   preferred_element_type=jnp.float32) * scale
    visible = jnp.arange(Smax) <= positions[..., None]  # [(B,) T, Smax]
    if visible.ndim == 2:
        visible = visible[None]
    s = jnp.where(visible[:, None, None], s, -jnp.inf)
    probs = jax.nn.softmax(s, axis=-1)
    attn = jnp.einsum("bhrts,bshd->bthrd", probs.astype(dt), vc,
                      preferred_element_type=jnp.float32).astype(dt)
    return attn.reshape(B, T, nH, D)


def _tick_fused_active(cfg: LlamaConfig) -> bool:
    """Does this decode tick use the fused Pallas epilogue kernels?"""
    if not cfg.fused_tick_epilogue:
        return False
    from ..ops.pallas.tick_fusion import tick_fusion_active

    return (tick_fusion_active(cfg.hidden_size)
            and cfg.head_dim % 8 == 0 and cfg.head_dim % 2 == 0)


def _decode_qkv(cfg: LlamaConfig, x, lp, pos_b):
    """T=1 fused-tick variant of ``_qkv_proj``: the rmsnorm chain is one
    Pallas op and the q/k rope chains (cos/sin/slice/concat per head,
    twice) collapse into one shared-cos/sin kernel. Same math — the
    projections themselves stay XLA dots (they carry the weight stream
    the tick is roofline-bound on)."""
    from ..ops.pallas.tick_fusion import fused_rms_norm, fused_rope_qk

    B = x.shape[0]
    dt = x.dtype
    h = fused_rms_norm(x[:, 0], lp["ln_attn"], cfg.rms_eps)
    Hq = cfg.num_heads * cfg.head_dim
    Hkv = cfg.num_kv_heads * cfg.head_dim
    if cfg.fused_weights:
        z = _mm(h, lp, "wqkv", dt)
        zq, zk, zv = (z[..., :Hq], z[..., Hq:Hq + Hkv], z[..., Hq + Hkv:])
    else:
        zq = _mm(h, lp, "wq", dt)
        zk = _mm(h, lp, "wk", dt)
        zv = _mm(h, lp, "wv", dt)
    zq, zk = fused_rope_qk(zq, zk, pos_b, cfg.head_dim, cfg.rope_theta)
    q = zq.reshape(B, 1, cfg.num_heads, cfg.head_dim)
    k = zk.reshape(B, 1, cfg.num_kv_heads, cfg.head_dim)
    v = zv.reshape(B, 1, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def _decode_post(cfg: LlamaConfig, x, attn, lp):
    """T=1 fused-tick variant of ``_layer_post``: the attention-residual
    add and the mlp pre-norm are ONE kernel emitting both the new
    residual stream and the normed value (single-device path — no wsc)."""
    from ..ops.pallas.tick_fusion import fused_add_rms_norm

    B, _, H = x.shape
    dt = x.dtype
    o = _mm(attn.reshape(B, H), lp, "wo", dt)
    x2, h = fused_add_rms_norm(x[:, 0], o, lp["ln_mlp"], cfg.rms_eps)
    if cfg.fused_weights:
        F_ = cfg.intermediate_size
        zz = _mm(h, lp, "w_gate_up", dt)
        zg, up = zz[..., :F_], zz[..., F_:]
    else:
        zg = _mm(h, lp, "w_gate", dt)
        up = _mm(h, lp, "w_up", dt)
    x3 = x2 + _mm(jax.nn.silu(zg) * up, lp, "w_down", dt)
    return x3[:, None]


def forward_with_cache(params, tokens, cfg: LlamaConfig, cache, pos,
                       logit_pos=None):
    """Run ``tokens`` [B, T] at absolute positions pos..pos+T-1 against the
    cache. Returns (logits [B, V], updated cache). T is the prompt length
    for prefill and 1 for decode; ``pos`` may be a traced scalar, or a
    traced [B] vector (ragged decode, T==1: every slot writes and attends
    at its OWN position — the continuous-batching engine's path). Logits
    come from the last position, or from ``logit_pos`` (traced scalar —
    bucket-padded prompts read the true last token). Layers run under
    lax.scan over the stacked [L, ...] weights and cache — O(1) compile
    depth, matching the training path's scan_layers design."""
    dt = cfg.dtype
    B, T = tokens.shape
    x = params["embed"].astype(dt)[tokens]
    ragged = getattr(pos, "ndim", 0) == 1
    if ragged and T != 1:
        raise ValueError("per-slot pos requires single-token decode (T=1)")
    positions = pos[:, None] if ragged else pos + jnp.arange(T)
    layer_weights = layer_params(params, cfg)

    # fused tick epilogue: single-token decode collapses each
    # between-matmul small-op chain into one Pallas op (dispatch-gated;
    # prefill T>1 and CPU keep the inline jnp chains — same math)
    fused_tick = T == 1 and _tick_fused_active(cfg)
    if fused_tick:
        pos_b = jnp.broadcast_to(
            jnp.reshape(jnp.asarray(positions)[..., 0], (-1,)),
            (B,)).astype(jnp.int32)

    def _qkv(x, lp):
        return (_decode_qkv(cfg, x, lp, pos_b) if fused_tick
                else _qkv_proj(cfg, x, lp, positions))

    def _post(x, attn, lp):
        return (_decode_post(cfg, x, attn, lp) if fused_tick
                else _layer_post(cfg, x, attn, lp))

    def body(x, per_layer):
        lp, kc, vc = per_layer
        q, k_new, v_new = _qkv(x, lp)
        if ragged:
            # scatter each slot's new row at its own position
            rows = jnp.arange(B)
            kc = kc.at[rows, pos].set(k_new[:, 0].astype(kc.dtype))
            vc = vc.at[rows, pos].set(v_new[:, 0].astype(vc.dtype))
        else:
            kc = jax.lax.dynamic_update_slice(
                kc, k_new.astype(kc.dtype), (0, pos, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v_new.astype(vc.dtype), (0, pos, 0, 0))
        attn = _cache_attention(cfg, q, kc, vc, positions)
        return _post(x, attn, lp), (kc, vc)

    if cfg.scan_layers:
        x, (kcs, vcs) = jax.lax.scan(body, x,
                                     (layer_weights, cache["k"], cache["v"]))
    else:
        # Unrolled layers (scan_layers=False): write the new K/V rows at a
        # STATIC layer index directly into the stacked cache buffers. The
        # layer-scan path must slice layer l's [B,S,Hkv,D] cache out of
        # the stacked xs and re-stack the updated copy into ys EVERY
        # layer — on the decode tick that is 4 full cache copies per
        # layer (~360 us/tick at the serving bench shape, measured in
        # benchmarks/decode_profile.py, vs ~0 for the in-place row DUS
        # here). Prefill/decode programs donate the cache, so these
        # updates happen in place.
        kcs, vcs = cache["k"], cache["v"]
        for i in range(cfg.num_layers):
            lp = {kk: layer_weights[kk][i] for kk in layer_weights}
            q, k_new, v_new = _qkv(x, lp)
            if ragged:
                rows = jnp.arange(B)
                kcs = kcs.at[i, rows, pos].set(k_new[:, 0].astype(kcs.dtype))
                vcs = vcs.at[i, rows, pos].set(v_new[:, 0].astype(vcs.dtype))
            else:
                kcs = jax.lax.dynamic_update_slice(
                    kcs, k_new[None].astype(kcs.dtype), (i, 0, pos, 0, 0))
                vcs = jax.lax.dynamic_update_slice(
                    vcs, v_new[None].astype(vcs.dtype), (i, 0, pos, 0, 0))
            attn = _cache_attention(cfg, q, kcs[i], vcs[i], positions)
            x = _post(x, attn, lp)
    if fused_tick:
        from ..ops.pallas.tick_fusion import fused_rms_norm

        x = fused_rms_norm(x[:, 0], params["ln_f"], cfg.rms_eps)[:, None]
    else:
        x = _rms_norm(x, params["ln_f"], cfg.rms_eps)
    if logit_pos is None:
        last = x[:, -1]
    elif getattr(logit_pos, "ndim", 0) == 1:
        last = x[jnp.arange(B), logit_pos]  # per-row (batched prefill)
    else:
        last = jax.lax.dynamic_index_in_dim(x, logit_pos, axis=1,
                                            keepdims=False)
    logits = _mm(last, params, "lm_head", dt)  # [B, V]
    return logits.astype(jnp.float32), {"k": kcs, "v": vcs}


def _paged_attention(cfg: LlamaConfig, q, kc, vc, page_table, positions,
                     ks=None, vs=None):
    """Attention over a paged KV pool. q [B,T,nH,D]; kc/vc
    [P, page_size, Hkv, D] (the flat pool); page_table [B, max_pages];
    ``positions`` [B, T] absolute query positions (row t of slot b at
    ``positions[b, t]``, keys [0, positions[b, t]] visible). Dispatches
    to the unified page-indirect Pallas kernel when the shape tiles
    (per-slot KV reads scale with position); the fallback gathers the
    slot's pages into a contiguous window and reuses the dense
    formulation — identical math, CPU/tier-1's path.

    ``ks``/``vs`` ([P, page_size] fp32, optional): a QUANTIZED pool's
    per-page scale planes — the gather fetches the scale rows with
    their pages and dequantizes the [B, W] window before the dense
    contraction (so HBM→gather traffic carried the narrow dtype; the
    slot-contiguous kernel analog dequantizes in VMEM —
    ops/pallas/decode_attention.py)."""
    from ..ops.pallas.paged_attention import (paged_attention_active,
                                              ragged_paged_attention)

    B, T = q.shape[:2]
    psz = kc.shape[1]
    if ks is None and paged_attention_active(psz, cfg.num_heads,
                                             cfg.num_kv_heads, cfg.head_dim):
        return ragged_paged_attention(q, kc, vc, page_table,
                                      positions[:, 0])
    dt = q.dtype
    W = page_table.shape[1] * psz
    gk = kc[page_table]
    gv = vc[page_table]
    if ks is not None:
        gk = gk.astype(dt) * ks[page_table][..., None, None].astype(dt)
        gv = gv.astype(dt) * vs[page_table][..., None, None].astype(dt)
    gk = gk.reshape(B, W, kc.shape[2], kc.shape[3])
    gv = gv.reshape(B, W, vc.shape[2], vc.shape[3])
    return _dense_cache_attention(cfg, q, gk, gv, positions)


def forward_with_pages(params, tokens, cfg: LlamaConfig, pool, page_table,
                       pos, live=None, logit_pos=None, logits_all=False):
    """``forward_with_cache`` over a PAGED KV pool (inference/paged_kv).

    tokens [B, T] run at absolute positions ``pos[b] .. pos[b]+T-1``
    per row (``pos``: [B] int32 — every slot at its OWN base position:
    T == 1 is a ragged decode tick, T > 1 a prefill chunk at context
    offset ``pos[b]``). ``pool``: {"k","v"} [L, num_pages, page_size,
    Hkv, D] flat page pools; ``page_table``: [B, max_pages] int32 —
    virtual page slot j of row b is physical page ``page_table[b, j]``.
    K/V rows scatter page-indirectly at their positions; ``live``
    ([B] bool, optional) routes retired slots' writes to the reserved
    trash page 0 instead (a frozen slot must never write a page the
    allocator may have handed to someone else), as do positions past
    the table. Returns (logits [B, V], updated pool) — or, with
    ``logits_all=True``, logits at EVERY query position ([B, T, V]): the
    speculative verify tick scores all K+1 drafted positions from the
    same single weight stream (SCALING §3j), so the lm_head matmul runs
    over the whole chunk instead of one gathered row."""
    dt = cfg.dtype
    B, T = tokens.shape
    psz = pool["k"].shape[2]
    max_pages = page_table.shape[1]
    x = params["embed"].astype(dt)[tokens]
    # r23 (ISSUE 18): sequence-parallel prefill slabs arrive with the
    # slab's ROW axis as the batch axis ([sp, C] — one C-token chunk of
    # the same prompt per row). When the live mesh carries an 'sp' axis
    # that divides B, hint GSPMD to shard the batch dim over it so the
    # per-layer QKV/MLP matmuls of an sp-slab run 1/sp-sized per device;
    # the paged gather in _paged_attention then reads cross-shard rows
    # through the (replicated) pool, which GSPMD serves with the same
    # neighbour exchanges the ring formulation hand-codes (see
    # ops/pallas/ring_attention.sp_slab_ring_attention for the manual
    # twin). On CPU/no-mesh (every test) this is a literal no-op, keeping
    # the bit-exact gather path.
    from ..parallel.mesh import get_mesh, with_sharding_constraint
    from jax.sharding import PartitionSpec as _P

    _mesh = get_mesh()
    if (_mesh is not None and "sp" in _mesh.axis_names
            and int(_mesh.shape["sp"]) > 1
            and B % int(_mesh.shape["sp"]) == 0):
        x = with_sharding_constraint(x, _P("sp", None, None), _mesh)
    pos = jnp.asarray(pos, jnp.int32).reshape(B)
    positions = pos[:, None] + jnp.arange(T)            # [B, T]
    # destination coordinates for the chunk's K/V rows — shared by all
    # layers (virtual page -> physical page via the table; dead slots
    # and rows past the table land in trash page 0)
    vpage = positions // psz
    prow = positions % psz
    phys = jnp.take_along_axis(page_table,
                               jnp.minimum(vpage, max_pages - 1), axis=1)
    writable = vpage < max_pages
    if live is not None:
        writable = writable & live[:, None]
    phys = jnp.where(writable, phys, 0)
    layer_weights = layer_params(params, cfg)

    # quantized pool: K/V pages carry a narrow dtype plus per-page fp32
    # scale planes (one scale per cache row — see init_paged_pool); new
    # rows quantize at write time and their scales land at the SAME
    # [phys, prow] coordinates, so trash-page routing, COW and spill
    # stay dtype-oblivious
    quant = "ks" in pool
    if quant:
        from ..quantization.serving import quantize_kv_rows

    fused_tick = T == 1 and _tick_fused_active(cfg)

    def _qkv(x, lp):
        return (_decode_qkv(cfg, x, lp, pos) if fused_tick
                else _qkv_proj(cfg, x, lp, positions))

    def _post(x, attn, lp):
        return (_decode_post(cfg, x, attn, lp) if fused_tick
                else _layer_post(cfg, x, attn, lp))

    def body(x, per_layer):
        if quant:
            lp, kc, vc, ks, vs = per_layer
        else:
            (lp, kc, vc), ks, vs = per_layer, None, None
        q, k_new, v_new = _qkv(x, lp)
        if quant:
            k_new, k_sc = quantize_kv_rows(k_new, kc.dtype)
            v_new, v_sc = quantize_kv_rows(v_new, vc.dtype)
            ks = ks.at[phys, prow].set(k_sc)
            vs = vs.at[phys, prow].set(v_sc)
        kc = kc.at[phys, prow].set(k_new.astype(kc.dtype))
        vc = vc.at[phys, prow].set(v_new.astype(vc.dtype))
        attn = _paged_attention(cfg, q, kc, vc, page_table, positions,
                                ks=ks, vs=vs)
        planes = (kc, vc, ks, vs) if quant else (kc, vc)
        return _post(x, attn, lp), planes

    plane_names = ("k", "v", "ks", "vs") if quant else ("k", "v")
    if cfg.scan_layers:
        x, planes = jax.lax.scan(
            body, x,
            (layer_weights,) + tuple(pool[n] for n in plane_names))
        new_pool = dict(zip(plane_names, planes))
    else:
        planes = {n: pool[n] for n in plane_names}
        for i in range(cfg.num_layers):
            lp = {kk: layer_weights[kk][i] for kk in layer_weights}
            q, k_new, v_new = _qkv(x, lp)
            if quant:
                k_new, k_sc = quantize_kv_rows(k_new, planes["k"].dtype)
                v_new, v_sc = quantize_kv_rows(v_new, planes["v"].dtype)
                planes["ks"] = planes["ks"].at[i, phys, prow].set(k_sc)
                planes["vs"] = planes["vs"].at[i, phys, prow].set(v_sc)
            planes["k"] = planes["k"].at[i, phys, prow].set(
                k_new.astype(planes["k"].dtype))
            planes["v"] = planes["v"].at[i, phys, prow].set(
                v_new.astype(planes["v"].dtype))
            attn = _paged_attention(
                cfg, q, planes["k"][i], planes["v"][i], page_table,
                positions,
                ks=planes["ks"][i] if quant else None,
                vs=planes["vs"][i] if quant else None)
            x = _post(x, attn, lp)
        new_pool = planes
    if fused_tick:
        from ..ops.pallas.tick_fusion import fused_rms_norm

        x = fused_rms_norm(x[:, 0], params["ln_f"], cfg.rms_eps)[:, None]
    else:
        x = _rms_norm(x, params["ln_f"], cfg.rms_eps)
    if logits_all:
        logits = _mm(x, params, "lm_head", dt)        # [B, T, V]
        return logits.astype(jnp.float32), new_pool
    if logit_pos is None:
        last = x[:, -1]
    elif getattr(logit_pos, "ndim", 0) == 1:
        last = x[jnp.arange(B), logit_pos]
    else:
        last = jax.lax.dynamic_index_in_dim(x, logit_pos, axis=1,
                                            keepdims=False)
    logits = _mm(last, params, "lm_head", dt)  # [B, V]
    return logits.astype(jnp.float32), new_pool


def init_paged_pool(cfg: LlamaConfig, num_pages: int, page_size: int,
                    dtype=None, quant=None) -> Dict[str, jax.Array]:
    """Flat paged K/V pool: [L, num_pages, page_size, Hkv, D]. Page 0 is
    the allocator's reserved trash page (see inference/paged_kv.py).

    ``quant`` ('int8' | 'fp8'): K/V pages store the narrow dtype and the
    pool carries per-page fp32 scale planes ``ks``/``vs``
    [L, num_pages, page_size] — one scale per cache row, keyed by
    physical page id so every page-granular mechanism (COW copies,
    refcounts, host-tier spill, fleet migration) moves scales with
    their pages without knowing the dtype."""
    if quant is not None:
        from ..quantization.serving import quant_dtype

        dtype = quant_dtype(quant)
    else:
        dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
             cfg.head_dim)
    pool = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if quant is not None:
        sshape = (cfg.num_layers, num_pages, page_size)
        pool["ks"] = jnp.zeros(sshape, jnp.float32)
        pool["vs"] = jnp.zeros(sshape, jnp.float32)
    return pool


def prompt_kv(params, prompt, cfg: LlamaConfig,
              max_len: Optional[int] = None):
    """KV rows for a prompt, standalone: the prefix-cache registration
    path (inference/prefix_cache.py) and its parity tests. Returns
    ({"k","v"} [L, B, S_pad, Hkv, D], logits [B, V]) where S_pad =
    ``max_len or S`` — rows past S are zeros. Rope is position-dependent,
    so these rows are reusable by ANY request whose prompt starts with
    ``prompt`` (the keys live at the same absolute positions)."""
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim == 1:
        prompt = prompt[None]
    B, S = prompt.shape
    cache = init_kv_cache(cfg, B, max_len or S)
    logits, cache = forward_with_cache(params, prompt, cfg, cache,
                                       jnp.int32(0))
    return cache, logits


def sample_filter_logits(logits, temperature, top_k=0, top_p=1.0):
    """Temperature/top-k/top-p filtered logits over the LAST dim (any
    leading dims): tokens outside the kept support are -inf, so the
    sampling distribution is exactly ``softmax(result)``. Shared by
    ``generate``'s per-step sampler, the serving engine's in-program
    samplers (including the speculative verify tick's [slots, K+1, V]
    batch), and the numpy-reference property tests. ``temperature`` must
    be > 0 — greedy (temperature 0) is the caller's static argmax
    branch."""
    logits = logits / temperature
    if top_k:
        k = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., k - 1:k]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        # nucleus sampling: keep the smallest prefix of the sorted probs
        # whose mass reaches top_p (the first token always survives)
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < top_p              # mass BEFORE this token
        keep = keep.at[..., 0].set(True)        # the top token always survives
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _sample(logits, temperature, top_k, key, top_p=1.0):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = sample_filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(params, prompt, cfg: LlamaConfig, max_new_tokens: int = 32,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 1.0, seed: int = 0) -> jax.Array:
    """Autoregressive generation: greedy at temperature 0, otherwise
    temperature sampling with optional top-k and/or nucleus (top-p)
    filtering. Returns [B, max_new_tokens] int32.

    Prefill is one jitted program; every decode token is one jitted step
    with the cache DONATED (in-place on device). Sampling and the position
    counter live INSIDE the step, so the host loop only threads device
    references — no per-token host->device transfers or syncs.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    B, S = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    # the last sampled token is returned but never written back to the
    # cache, so S + max_new_tokens - 1 slots suffice
    max_len = max_len or min(cfg.max_seq_len, S + max_new_tokens - 1)
    if S + max_new_tokens - 1 > max_len:
        raise ValueError(f"prompt ({S}) + max_new_tokens ({max_new_tokens}) "
                         f"needs {S + max_new_tokens - 1} cache slots but "
                         f"max_len is {max_len}")
    prefill = _prefill_program(cfg, max_len, float(temperature), int(top_k),
                               float(top_p))
    cache, nxt, pos, key = prefill(params, prompt, jax.random.PRNGKey(seed))
    if max_new_tokens == 1:
        return nxt[:, None]
    decode_all = _decode_program(cfg, max_new_tokens, float(temperature),
                                 int(top_k), float(top_p))
    toks, _ = decode_all(params, cache, nxt, pos, key)
    return jnp.concatenate([nxt[:, None], toks.T], axis=1)


# Compiled-program factories, cached SEPARATELY: varying prompt lengths
# re-specialise only prefill (through jit's own shape cache) while ONE
# decode program serves them all. NOTE: on the default path max_len is
# derived from S + max_new_tokens - 1, which couples BOTH programs to the
# request sizes — serving loops should pass a fixed max_len so the cache
# shape (and with it every compiled program) stays stable. The KV cache
# is allocated INSIDE prefill (on device from the start; decode then
# donates it cleanly).

@functools.lru_cache(maxsize=32)
def _prefill_program(cfg: LlamaConfig, max_len: int, temperature: float,
                     top_k: int, top_p: float = 1.0):
    @jax.jit
    def prefill(params, prompt, key):
        cache = init_kv_cache(cfg, prompt.shape[0], max_len)
        logits, cache = forward_with_cache(params, prompt, cfg, cache,
                                           jnp.int32(0))
        key, sub = jax.random.split(key)
        nxt = _sample(logits, temperature, top_k, sub, top_p)
        return cache, nxt, jnp.int32(prompt.shape[1]), key

    return prefill


@functools.lru_cache(maxsize=32)
def _decode_program(cfg: LlamaConfig, max_new_tokens: int,
                    temperature: float, top_k: int, top_p: float = 1.0):
    @functools.partial(jax.jit, donate_argnums=(1,))
    def decode_all(params, cache, nxt, pos, key):
        # the whole decode loop is ONE compiled program (lax.scan): zero
        # host round-trips per token — the TPU-native replacement for the
        # reference's per-token python generation loop
        def body(carry, _):
            cache, nxt, pos, key = carry
            logits, cache = forward_with_cache(params, nxt[:, None], cfg,
                                               cache, pos)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, temperature, top_k, sub, top_p)
            return (cache, nxt, pos + 1, key), nxt

        (cache, *_), toks = jax.lax.scan(
            body, (cache, nxt, pos, key), None, length=max_new_tokens - 1)
        # returning the final cache gives the donated input an aliasing
        # target (in-place update, no copy, no donation warning); callers
        # discard it
        return toks, cache  # toks: [T-1, B]

    return decode_all


# ---------------------------------------------------------------------------
# Beam search (reference: PaddleNLP generate(decode_strategy="beam_search")).
# Same one-program design as greedy/sampling decode: the whole beam loop is
# a single lax.scan; beam reordering gathers the KV cache along the
# flattened [B*num_beams] batch axis on device.
# ---------------------------------------------------------------------------


def beam_search_generate(params, prompt, cfg: LlamaConfig,
                         max_new_tokens: int = 32, num_beams: int = 4,
                         max_len: Optional[int] = None,
                         eos_token_id: Optional[int] = None,
                         length_penalty: float = 1.0) -> jax.Array:
    """Fixed-length beam search over the KV cache; returns the best beam's
    tokens [B, max_new_tokens]. ``eos_token_id`` (optional) freezes
    finished beams (their only continuation is another EOS at logprob 0).
    ``length_penalty`` rescales final scores by len**penalty as in the
    reference's BeamSearchScorer."""
    prompt = jnp.asarray(prompt, jnp.int32)
    B, S = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    max_len = max_len or min(cfg.max_seq_len, S + max_new_tokens - 1)
    if S + max_new_tokens - 1 > max_len:
        raise ValueError(f"prompt ({S}) + max_new_tokens ({max_new_tokens}) "
                         f"needs {S + max_new_tokens - 1} cache slots but "
                         f"max_len is {max_len}")

    prefill = _prefill_program(cfg, max_len, 0.0, 0)
    cache, _, pos, _ = prefill(params, prompt, jax.random.PRNGKey(0))
    # re-derive first logits (prefill returns the sampled token, not logits)
    # cheaply: one decode-shaped forward would advance the cache, so instead
    # run the beam program from the prefilled cache + prompt's last token
    beam = _beam_program(cfg, max_new_tokens, num_beams, eos_token_id,
                         float(length_penalty))
    return beam(params, cache, prompt[:, -1], pos - 1)


@functools.lru_cache(maxsize=16)
def _beam_program(cfg: LlamaConfig, max_new_tokens: int, num_beams: int,
                  eos_token_id: Optional[int], length_penalty: float):
    nb = num_beams

    # no donation: the cache changes shape when tiled to [B*nb] beams, so
    # the input buffer can never alias an output
    @jax.jit
    def beam_all(params, cache, last_tok, last_pos):
        # Step 0: recompute the prompt-final logits from the cached state
        # (position last_pos is already in the cache; masking makes the
        # duplicate write idempotent), then branch into nb beams.
        logits, cache = forward_with_cache(params, last_tok[:, None], cfg,
                                           cache, last_pos)
        B = logits.shape[0]
        lp = jax.nn.log_softmax(logits, axis=-1)
        scores, tok0 = jax.lax.top_k(lp, nb)              # [B, nb]
        cache = jax.tree.map(lambda c: jnp.repeat(c, nb, axis=1), cache)
        nxt = tok0.reshape(B * nb).astype(jnp.int32)
        hist = jnp.zeros((B, nb, max_new_tokens), jnp.int32)
        hist = hist.at[:, :, 0].set(tok0)
        finished = (tok0 == eos_token_id) if eos_token_id is not None \
            else jnp.zeros((B, nb), bool)
        lengths = jnp.ones((B, nb), jnp.float32)  # per-beam generated length
        pos = last_pos + 1

        def body(carry, i):
            cache, nxt, pos, scores, finished, hist, lengths = carry
            logits, cache = forward_with_cache(params, nxt[:, None], cfg,
                                               cache, pos)
            lp = jax.nn.log_softmax(logits, axis=-1)      # [B*nb, V]
            V = lp.shape[-1]
            if eos_token_id is not None:
                # finished beams may only emit EOS again, at logprob 0
                eos_only = jnp.full((V,), -jnp.inf).at[eos_token_id].set(0.0)
                lp = jnp.where(finished.reshape(B * nb)[:, None],
                               eos_only[None], lp)
            total = scores[:, :, None] + lp.reshape(B, nb, V)
            new_scores, idx = jax.lax.top_k(total.reshape(B, nb * V), nb)
            beam_idx = idx // V                           # [B, nb]
            tok = (idx % V).astype(jnp.int32)
            src = (jnp.arange(B)[:, None] * nb + beam_idx).reshape(B * nb)
            cache = jax.tree.map(lambda c: jnp.take(c, src, axis=1), cache)
            hist = jnp.take_along_axis(hist, beam_idx[:, :, None], axis=1)
            hist = hist.at[:, :, i].set(tok)
            lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
            if eos_token_id is not None:
                prev_finished = jnp.take_along_axis(finished, beam_idx,
                                                    axis=1)
                lengths = jnp.where(prev_finished, lengths, lengths + 1)
                finished = prev_finished | (tok == eos_token_id)
            else:
                lengths = lengths + 1
            nxt = tok.reshape(B * nb)
            return (cache, nxt, pos + 1, new_scores, finished, hist,
                    lengths), None

        carry = (cache, nxt, pos, scores, finished, hist, lengths)
        if max_new_tokens > 1:
            carry, _ = jax.lax.scan(body, carry,
                                    jnp.arange(1, max_new_tokens))
        _, _, _, scores, _, hist, lengths = carry
        # reference BeamSearchScorer: score = sum_logprobs / len**penalty,
        # each hypothesis normalised by its OWN length (EOS position) — at
        # the default penalty of 1.0 this is plain per-length averaging;
        # penalty 0.0 disables normalisation
        scores = scores / (lengths ** length_penalty)
        best = jnp.argmax(scores, axis=-1)                # [B]
        return jnp.take_along_axis(
            hist, best[:, None, None], axis=1)[:, 0]      # [B, T]

    return beam_all

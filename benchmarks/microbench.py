"""Shared slope-timing harness for on-chip microbenchmarks.

Methodology (see flash_micro.py for the original derivation): the
tunneled PJRT dispatch costs ~4 ms per host->device call, so per-call
host timing is latency-bound. Instead, chain n kernel calls inside ONE
jitted ``lax.scan`` and take the slope between two loop lengths, which
cancels the fixed dispatch/transfer overhead.

Anti-elision measures (each was observed to be necessary):
- the first argument is perturbed by an ADDITIVE near-zero carry that
  depends on the previous output — a multiplicative scalar gets factored
  out of pure matmuls by XLA's algebraic simplifier, making the body
  loop-invariant and the loop time nothing;
- the output is consumed QUADRATICALLY (sum(o*o)): a single-element read
  lets XLA slice through a dot and DCE the rest of the matmul (observed
  "13,825 TF/s"), and a LINEAR sum gets rewritten
  reduce(dot) -> dot(reduce, reduce), skipping the matmul too (observed
  "260% of peak"). sum(o*o) distributes over neither; the reduce
  epilogue is ~0.01 ms of HBM traffic.

(flash_micro.py keeps its own single-element consumption: its pallas
custom calls are opaque to XLA, so slicing/reduction rewrites cannot
reach inside them.)
"""
import time

import jax
import jax.numpy as jnp


def slope_timeit(fn, args, iters, reps=5):
    """Per-iteration seconds of ``fn(*args)``, slope-timed on device."""
    def loop(c, a0, rest, n):
        def body(carry, _):
            out = fn(a0 + (carry - 1.0).astype(a0.dtype), *rest)
            o = jax.tree.leaves(out)[0].astype(jnp.float32)
            s = jnp.sum(o * o)
            return 1.0 + 1e-24 * s, None
        c, _ = jax.lax.scan(body, c, None, length=n)
        return c

    jloop = jax.jit(loop, static_argnums=(3,))
    c = jnp.float32(1.0)
    times = {}
    for n in (iters, 2 * iters):
        float(jloop(c, args[0], args[1:], n))  # compile + warm
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            float(jloop(c, args[0], args[1:], n))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        times[n] = best
    return (times[2 * iters] - times[iters]) / iters


def parse_overrides(argv):
    """key=value CLI args -> LlamaConfig override dict (ints and bools
    coerced) — shared by perf_lab/step_profile/hlo_map/compiler_opt_probe."""
    ov = {}
    for a in argv:
        k, v = a.split("=", 1)
        try:
            v = int(v)
        except ValueError:
            v = {"True": True, "False": False}.get(v, v)
        ov[k] = v
    return ov

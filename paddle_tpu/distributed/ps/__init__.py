"""``paddle.distributed.ps`` — parameter-server training stack.

Reference counterpart: ``paddle/fluid/distributed/ps/`` (brpc dense/sparse
tables, ``BrpcPsServer/Client``, accessors, GeoSGD) + ``python/paddle/
distributed/ps/`` "TheOnePS" runtime (SURVEY.md §2.2 "Parameter server").

TPU-native stance (SURVEY.md §7.3 item 6): PS training is CPU-bound sparse
recommendation — orthogonal to the TPU compute path — so the scope here is a
**functional single/multi-host PS** over the same TCP control plane as
``distributed.rpc``: dense tables, sparse (hash) embedding tables with
on-first-touch initialisation, sync/async push-pull, and a GeoSGD-style
local-step accumulator. brpc itself (a vendored RPC framework) is replaced,
not ported.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional

import numpy as np

__all__ = ["PsServer", "PsClient", "DenseTable", "SparseTable"]


def _send(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack("!Q", len(data)) + data)


def _recv(sock):
    hdr = b""
    while len(hdr) < 8:
        c = sock.recv(8 - len(hdr))
        if not c:
            raise ConnectionError("ps peer closed")
        hdr += c
    n = struct.unpack("!Q", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        c = sock.recv(min(1 << 20, n - len(buf)))
        if not c:
            raise ConnectionError("ps peer closed mid-message")
        buf += c
    return pickle.loads(bytes(buf))


class DenseTable:
    """Dense parameter block with an SGD accessor (reference
    ``MemoryDenseTable`` + accessor)."""

    def __init__(self, shape, lr=0.01, init=None):
        self.param = (np.zeros(shape, np.float32) if init is None
                      else np.asarray(init, np.float32).copy())
        self.lr = lr
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            return self.param.copy()

    def push_grad(self, grad):
        with self.lock:
            self.param -= self.lr * np.asarray(grad, np.float32)

    def set(self, value):
        with self.lock:
            self.param = np.asarray(value, np.float32).copy()


class SparseTable:
    """Row-sparse embedding table keyed by int64 id (reference
    ``MemorySparseTable``): rows materialise on first pull (uniform init),
    gradients apply per-row SGD — the SelectedRows update."""

    def __init__(self, dim, lr=0.01, init_range=0.05, seed=0):
        self.dim = dim
        self.lr = lr
        self.init_range = init_range
        self.rows: Dict[int, np.ndarray] = {}
        self.rng = np.random.RandomState(seed)
        self.lock = threading.Lock()

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:
            r = self.rng.uniform(-self.init_range, self.init_range,
                                 self.dim).astype(np.float32)
            self.rows[i] = r
        return r

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(int(i)) for i in np.asarray(ids)])

    def push_grad(self, ids, grads):
        grads = np.asarray(grads, np.float32)
        with self.lock:
            for i, g in zip(np.asarray(ids), grads):
                self._row(int(i))
                self.rows[int(i)] = self.rows[int(i)] - self.lr * g

    def size(self):
        with self.lock:
            return len(self.rows)


class _PsHandler(socketserver.BaseRequestHandler):
    def handle(self):
        server: "PsServer" = self.server.ps  # type: ignore[attr-defined]
        while True:
            try:
                op, args = _recv(self.request)
            except ConnectionError:
                return
            try:
                result = getattr(server, "_op_" + op)(*args)
                _send(self.request, ("ok", result))
            except BaseException as e:
                _send(self.request, ("err", e))


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PsServer:
    """Hosts tables; serves pull/push over TCP (reference BrpcPsServer)."""

    def __init__(self, host="127.0.0.1", port=0):
        self.dense: Dict[int, DenseTable] = {}
        self.sparse: Dict[int, SparseTable] = {}
        self._bar: Dict[str, int] = {}
        self._bar_lock = threading.Lock()
        self._srv = _TCP((host, port), _PsHandler)
        self._srv.ps = self
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.host, self.port = self._srv.server_address

    # --- table management -------------------------------------------------
    def add_dense_table(self, table_id, shape, lr=0.01, init=None):
        self.dense[table_id] = DenseTable(shape, lr, init)

    def add_sparse_table(self, table_id, dim, lr=0.01, **kw):
        self.sparse[table_id] = SparseTable(dim, lr, **kw)

    # --- remote ops -------------------------------------------------------
    def _op_pull_dense(self, tid):
        return self.dense[tid].pull()

    def _op_push_dense_grad(self, tid, grad):
        self.dense[tid].push_grad(grad)

    def _op_set_dense(self, tid, value):
        self.dense[tid].set(value)

    def _op_pull_sparse(self, tid, ids):
        return self.sparse[tid].pull(ids)

    def _op_push_sparse_grad(self, tid, ids, grads):
        self.sparse[tid].push_grad(ids, grads)

    def _op_create_dense(self, tid, shape, lr, init):
        self.add_dense_table(tid, shape, lr, init)

    def _op_create_sparse(self, tid, dim, lr):
        self.add_sparse_table(tid, dim, lr)

    def _op_table_stats(self):
        return {"dense": sorted(self.dense),
                "sparse": {k: v.size() for k, v in self.sparse.items()}}

    def _op_barrier(self, key, world):
        with self._bar_lock:
            self._bar[key] = self._bar.get(key, 0) + 1
            return self._bar[key]

    def _op_barrier_stat(self, key):
        with self._bar_lock:
            return self._bar.get(key, 0)

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class PsClient:
    """Trainer-side stub (reference BrpcPsClient). One persistent socket;
    thread-safe via a lock (trainers are processes, not threads, in the
    reference deployment)."""

    def __init__(self, host, port, timeout=60.0):
        # retry until the server is up: under the launcher, trainers and
        # pservers start simultaneously and the server's interpreter may
        # still be importing when the first trainer connects
        import time as _time

        deadline = _time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout)
                break
            except OSError:
                if _time.time() > deadline:
                    raise
                _time.sleep(0.2)
        self._lock = threading.Lock()

    def _call(self, op, *args):
        with self._lock:
            _send(self._sock, (op, args))
            status, payload = _recv(self._sock)
        if status == "err":
            raise payload
        return payload

    def create_dense_table(self, table_id, shape, lr=0.01, init=None):
        self._call("create_dense", table_id, shape, lr, init)

    def create_sparse_table(self, table_id, dim, lr=0.01):
        self._call("create_sparse", table_id, dim, lr)

    def pull_dense(self, table_id) -> np.ndarray:
        return self._call("pull_dense", table_id)

    def push_dense_grad(self, table_id, grad) -> None:
        self._call("push_dense_grad", table_id, np.asarray(grad, np.float32))

    def set_dense(self, table_id, value) -> None:
        self._call("set_dense", table_id, np.asarray(value, np.float32))

    def pull_sparse(self, table_id, ids) -> np.ndarray:
        return self._call("pull_sparse", table_id, np.asarray(ids, np.int64))

    def push_sparse_grad(self, table_id, ids, grads) -> None:
        self._call("push_sparse_grad", table_id,
                   np.asarray(ids, np.int64), np.asarray(grads, np.float32))

    def table_stats(self):
        return self._call("table_stats")

    def barrier(self, key, world, timeout=60.0):
        """Block until ``world`` clients entered ``key`` (reference
        BrpcPsClient barrier). REUSABLE: the server counter is monotonic,
        so arrival n belongs to generation (n-1)//world and waits until
        the whole generation arrived — per-epoch barriers on one key work.
        (A TimeoutError leaves a stale arrival behind; re-create the
        server-side key rather than retrying the same generation.)"""
        import time as _time

        n = self._call("barrier", key, world)
        target = ((n - 1) // world + 1) * world
        deadline = _time.time() + timeout
        while self._call("barrier_stat", key) < target:
            if _time.time() > deadline:
                raise TimeoutError(f"ps barrier {key!r} timed out")
            _time.sleep(0.02)

    def close(self):
        self._sock.close()

"""BASELINE config 1: ResNet-50 ImageNet-geometry training throughput,
single chip (reference: PaddleClas ResNet50 default config).

Whole train step through the compiled path: ``to_static`` forward+loss (one
XLA program + its compiled vjp) and the optimizer's donated fused update.
Prints one JSON line: images/sec.
"""

import json
import os
import sys

# runnable standalone: the repo root (one level up) holds paddle_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run(batch=128, size=224, iters=10):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision import models

    model = models.resnet50(num_classes=1000)
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    # AMP O2 (pure bf16 with fp32 master weights) — the reference baseline
    # trains ResNet-50 in mixed precision (fp16/bf16 on tensor cores)
    model, opt = paddle.amp.decorate(models=model, optimizers=opt,
                                     level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            return ce(model(x), y)

    # fwd+bwd+optimizer as ONE compiled program per step (one dispatch)
    step_fn = paddle.jit.fused_train_step(loss_fn, opt, model=model)

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(batch, 3, size, size).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)))

    def one_step():
        return step_fn(x, y)

    loss = one_step()
    log(f"warmup loss {float(loss):.3f}")
    loss = one_step()
    float(loss)

    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = one_step()
        float(loss)  # forces completion (block_until_ready unreliable here)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    ips = iters * batch / best
    log(f"b{batch}: {ips:,.0f} img/s, step {best/iters*1e3:.1f} ms")
    return ips


def main():
    # one batch size per process: a failed (OOM) attempt leaves the chip's
    # allocator fragmented, poisoning smaller retries in the same process
    import subprocess

    if len(sys.argv) > 1:
        print(json.dumps({"ips": run(int(sys.argv[1]))}))
        return

    best = 0.0
    for batch in (128, 64, 32):
        proc = subprocess.run([sys.executable, __file__, str(batch)],
                              capture_output=True, text=True)
        log(proc.stderr[-500:])
        for line in proc.stdout.splitlines():
            try:
                best = json.loads(line)["ips"]
                break
            except (ValueError, KeyError):
                continue
        if best:
            break
    print(json.dumps({
        "metric": "resnet50_train_throughput", "value": round(best, 1),
        "unit": "images/sec",
        "vs_baseline": round(best / 2850.0, 4),  # A100 fp16 public ballpark
    }))


if __name__ == "__main__":
    main()

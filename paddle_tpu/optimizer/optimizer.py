"""Optimizer base + SGD family.

Reference: ``python/paddle/optimizer/optimizer.py`` (SURVEY.md §2.1). The
reference's perf trick is fused multi-tensor kernels (``fused_adamw``); the
TPU-native equivalent here is one ``jax.jit``-compiled update over the whole
parameter pytree with **donated** buffers — XLA fuses the elementwise update
chain across all parameters and reuses the parameter memory in place.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..enforce import InvalidArgumentError
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
           "ASGD", "Rprop"]


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    """Base optimizer over the eager tape's ``.grad`` accumulators."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                raise InvalidArgumentError("param groups not supported yet; pass a flat list")
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float):
            self._l2_coeff = weight_decay
        elif isinstance(weight_decay, L2Decay):
            self._l2_coeff = weight_decay.coeff
        else:
            self._l2_coeff = 0.0
        self._accumulators: Dict[int, Dict[str, Any]] = {}
        self._step_count = 0
        self._jit_update = None  # cached jitted fused step

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise InvalidArgumentError("set_lr not allowed when using an LRScheduler")
        self._learning_rate = float(value)

    # -- state ---------------------------------------------------------------
    def _state_names(self) -> List[str]:
        return []

    def _init_state(self, p: Tensor) -> Dict[str, jax.Array]:
        return {}

    def _ensure_state(self, p: Tensor) -> Dict[str, Any]:
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._init_state(p)
            self._accumulators[id(p)] = st
        return st

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"LR_Scheduler": {}, "master_weights": {}}
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        params = self._params()
        for i, p in enumerate(params):
            st = self._accumulators.get(id(p))
            if st is None:
                continue
            # export param-shaped state (the Pallas fused path keeps
            # accumulators as flat [rows, 128] segments between steps)
            st = self._shaped_state(p._value, st)
            for k, v in st.items():
                out[f"{p.name}.{k}"] = to_tensor(v) if not isinstance(v, Tensor) else v
        out["@step"] = self._step_count
        return out

    def set_state_dict(self, state: Dict[str, Any]):
        if isinstance(self._learning_rate, LRScheduler) and state.get("LR_Scheduler"):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        self._step_count = int(state.get("@step", 0))
        params = {p.name: p for p in self._params()}
        # group state entries per stored param name, preserving order
        grouped: Dict[str, Dict[str, Any]] = {}
        for key, val in state.items():
            if key in ("LR_Scheduler", "master_weights", "@step"):
                continue
            pname, _, sname = key.rpartition(".")
            grouped.setdefault(pname, {})[sname] = (
                val._value if isinstance(val, Tensor) else jnp.asarray(val)
            )
        matched = [n for n in grouped if n in params]
        if grouped and not matched:
            # Auto-generated tensor names are process-global, so a resumed
            # process may have shifted names — fall back to positional
            # mapping (state-dict insertion order vs parameter order).
            ordered = list(self._params())
            for (pname, st_vals), p in zip(grouped.items(), ordered):
                st = self._ensure_state(p)
                st.update(st_vals)
            return
        for pname in matched:
            p = params[pname]
            st = self._ensure_state(p)
            st.update(grouped[pname])

    # -- grads ---------------------------------------------------------------
    def _params(self) -> List[Tensor]:
        if self._parameter_list is None:
            raise InvalidArgumentError(
                "Optimizer was created without a parameters list"
            )
        return [p for p in self._parameter_list if not p.stop_gradient]

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._params():
            p.clear_grad()

    clear_gradients = clear_grad

    # -- the fused step -------------------------------------------------------
    def _update_one(self, p, g, state: Dict[str, Any], lr, step, extras=None):
        """Pure per-parameter update: returns (new_p, new_state)."""
        raise NotImplementedError

    def _per_param_extras(self, p) -> Dict[str, Any]:
        """Per-parameter traced scalars (e.g. AdamW's decay coefficient) —
        passed through the jit as data so host-side per-param decisions don't
        bake into the compiled program."""
        return {}

    def _apply_weight_decay_to_grad(self) -> bool:
        """L2-style decay folded into the gradient (Adam/SGD semantics)."""
        return True

    # elementwise-update optimizers (every _update_one math op is
    # per-element with scalar coefficients) may be FLAT-PACKED by
    # apply_updates: the multi-tensor fused path. Optimizers whose update
    # uses per-PARAM reductions (Lamb's trust ratio, LBFGS) must leave
    # this False.
    _elementwise_update = False
    _FLAT_PACK_MAX = 65536  # elements; larger tensors update solo
    # kind tag for the Pallas flat-buffer fused update
    # (ops/pallas/multi_tensor_update.py). None -> XLA packing only.
    # Lamb sets this DESPITE _elementwise_update=False: the kernel path
    # handles its per-tensor trust reduction via the plan's segment ids.
    _FUSED_PALLAS_KIND: Optional[str] = None

    def _fused_hyper(self, extras: Dict[str, Any]) -> Dict[str, Any]:
        """Static per-group scalars for the Pallas fused update (groups
        are split by ``extras``, so e.g. AdamW decay is one scalar)."""
        return {}

    def apply_updates(self, pvals, gvals, svals, evals, static_evals,
                      lr_, step_):
        """Per-param updates, FLAT-PACKED for elementwise optimizers (the
        reference's fused multi_tensor_momentum/adam kernels): a conv net
        holds hundreds of small tensors, and one compiled fusion per
        param is launch-bound — ~14 ms/step of the ResNet-50 profile
        against a ~0.5 ms HBM floor. Packing groups params whose dtype /
        state structure / extras agree, concatenates them flat, runs ONE
        update, and slices the results back (static offsets).

        ``static_evals`` are the HOST-side extras used for grouping (the
        traced ``evals`` values cannot key a dict at trace time).

        Only SMALL params pack (<= _FLAT_PACK_MAX elements): flattening a
        large tiled conv weight is a physical relayout copy on TPU
        (measured: packing everything traded 14 ms of launches for 32 ms
        of reshapes/copies on ResNet-50), while a big tensor's single
        fused update amortizes its launch anyway. Small 1-D/score tensors
        are exactly the launch-bound population.

        On TPU (flag ``use_pallas_fused_update``) supported optimizers
        route every group through the Pallas flat-buffer kernels instead
        (ops/pallas/multi_tensor_update.py): no stack/concat temporaries,
        params/moments updated in place via aliasing, and state kept in
        the flat layout between steps. CPU / meshes / unsupported kinds
        keep the XLA packing below."""
        n = len(pvals)
        kind = self._FUSED_PALLAS_KIND
        if kind is not None and n > 8:
            from ..ops.pallas import multi_tensor_update as _mtu

            if _mtu.fused_update_active(n, kind):
                return self._apply_updates_pallas(
                    _mtu, kind, pvals, gvals, svals, evals, static_evals,
                    lr_, step_)
        # state may arrive as flat [rows, 128] segments from an earlier
        # Pallas-fused program (the flag was live then); the XLA paths
        # below work on shaped state
        svals = [self._shaped_state(pv, sv)
                 for pv, sv in zip(pvals, svals)]
        if not self._elementwise_update or n <= 8:
            out = [self._update_one(p, g, s, lr_, step_, e)
                   for p, g, s, e in zip(pvals, gvals, svals, evals)]
            return [o[0] for o in out], [o[1] for o in out]
        import numpy as _np

        groups: Dict[Any, list] = {}
        for i, pv in enumerate(pvals):
            skey = tuple(sorted((k, str(v.dtype)) for k, v in
                                svals[i].items()))
            ekey = tuple(sorted((k, float(v)) for k, v in
                                (static_evals[i] or {}).items()))
            if int(_np.prod(pv.shape)) > self._FLAT_PACK_MAX:
                # big tensors STACK by identical shape on a new leading
                # axis — a pure memcpy concat of identically-tiled arrays
                # (flattening would relayout)
                key = ("stack", tuple(pv.shape), str(pv.dtype), skey, ekey)
            else:
                key = ("flat", str(pv.dtype), skey, ekey)
            groups.setdefault(key, []).append(i)
        new_p: list = [None] * n
        new_s: list = [None] * n
        for key, idxs in groups.items():
            if len(idxs) == 1:
                i = idxs[0]
                new_p[i], new_s[i] = self._update_one(
                    pvals[i], gvals[i], svals[i], lr_, step_, evals[i])
                continue
            if key[0] == "stack":
                pc = jnp.stack([pvals[i] for i in idxs])
                gc = jnp.stack([gvals[i] for i in idxs])
                sc = {k: jnp.stack([svals[i][k] for i in idxs])
                      for k in svals[idxs[0]]}
                npc, nsc = self._update_one(pc, gc, sc, lr_, step_,
                                            evals[idxs[0]])
                for j, i in enumerate(idxs):
                    new_p[i] = npc[j]
                    new_s[i] = {k: v[j] for k, v in nsc.items()}
                continue
            sizes = [int(_np.prod(pvals[i].shape)) for i in idxs]
            pc = jnp.concatenate([pvals[i].reshape(-1) for i in idxs])
            gc = jnp.concatenate([gvals[i].reshape(-1) for i in idxs])
            sc = {k: jnp.concatenate([svals[i][k].reshape(-1)
                                      for i in idxs])
                  for k in svals[idxs[0]]}
            npc, nsc = self._update_one(pc, gc, sc, lr_, step_,
                                        evals[idxs[0]])
            off = 0
            for i, sz in zip(idxs, sizes):
                new_p[i] = jax.lax.slice_in_dim(
                    npc, off, off + sz).reshape(pvals[i].shape)
                new_s[i] = {
                    k: jax.lax.slice_in_dim(v, off, off + sz).reshape(
                        svals[i][k].shape) for k, v in nsc.items()}
                off += sz
        return new_p, new_s

    def _shaped_state(self, pv, sv: Dict[str, Any]) -> Dict[str, Any]:
        """Undo the Pallas flat [rows, 128] state layout for paths that
        need param-shaped state (XLA packing after a flag flip, state
        export). Only kind-tagged optimizers can ever hold flat state."""
        if self._FUSED_PALLAS_KIND is None or not sv:
            return sv
        import numpy as _np
        n = int(_np.prod(pv.shape)) if len(pv.shape) else 1
        rows = -(-n // 128)
        out = {}
        for k, v in sv.items():
            if (hasattr(v, "ndim") and v.ndim == 2
                    and tuple(v.shape) == (rows, 128)
                    and tuple(pv.shape) != (rows, 128)):
                v = v.reshape(-1)[:n].reshape(tuple(pv.shape))
            out[k] = v
        return out

    def _apply_updates_pallas(self, mtu, kind, pvals, gvals, svals, evals,
                              static_evals, lr_, step_):
        """The flat-buffer fused path: one Pallas launch per (dtype,
        state-structure, static-extras) group, whole population — big
        conv weights included (the stack path's size split existed to
        bound XLA relayouts; the kernel has none)."""
        n = len(pvals)
        groups: Dict[Any, list] = {}
        for i, pv in enumerate(pvals):
            skey = tuple(sorted((k, str(v.dtype))
                                for k, v in svals[i].items()))
            ekey = tuple(sorted((k, float(v)) for k, v in
                                (static_evals[i] or {}).items()))
            groups.setdefault((str(pv.dtype), skey, ekey), []).append(i)
        new_p: list = [None] * n
        new_s: list = [None] * n
        for key, idxs in groups.items():
            if len(idxs) == 1:
                i = idxs[0]
                new_p[i], new_s[i] = self._update_one(
                    pvals[i], gvals[i],
                    self._shaped_state(pvals[i], svals[i]),
                    lr_, step_, evals[i])
                continue
            plan = mtu.FlatPlan([pvals[i].shape for i in idxs])
            hyper = self._fused_hyper(static_evals[idxs[0]] or {})
            npl, nsl = mtu.apply_flat_update(
                kind, plan, [pvals[i] for i in idxs],
                [gvals[i] for i in idxs], [svals[i] for i in idxs],
                hyper, lr_, step_)
            for j, i in enumerate(idxs):
                new_p[i] = npl[j]
                new_s[i] = nsl[j]
        return new_p, new_s

    def step(self):
        params = self._params()
        # SelectedRows grads (sparse embeddings) densify here: default-mode
        # Adam/SGD touch every row anyway (reference: non-lazy adam over
        # SelectedRows does the same merge+apply).
        pgs = [
            (p, (p.grad.to_dense()._value
                 if getattr(p.grad, "is_selected_rows", False)
                 else p.grad._value))
            for p in params if p.grad is not None
        ]
        if not pgs:
            return
        if self._grad_clip is not None:
            pgs = self._grad_clip(pgs)
        lr = self.get_lr()
        self._step_count += 1
        from ..observability import metrics as _obs

        _obs.counter("optimizer.steps").inc()
        _obs.gauge("optimizer.lr").set(float(lr))
        states = [self._ensure_state(p) for p, _ in pgs]
        state_keys = self._state_names()

        static_evals = [self._per_param_extras(p) for p, _ in pgs]
        # read by the jitted update AT TRACE TIME (a structure change in
        # the param pytree retraces, picking up the current list — a
        # closure captured at build time would go stale). A VALUE change
        # with the same pytree structure would NOT retrace, so the evals
        # repr is part of the cache key: any change drops the cached jit
        # (the stale grouping would silently mis-update fused groups).
        # The Pallas fused-update dispatch state rides the key too: a
        # runtime flag flip must rebuild the program (layout is traced).
        from ..ops.pallas.multi_tensor_update import fused_update_signature
        evals_key = repr((static_evals, fused_update_signature()))
        if getattr(self, "_static_evals_key", None) != evals_key:
            self._jit_update = None
            self._static_evals_key = evals_key
        self._static_evals = static_evals
        if self._jit_update is None:
            from ..jit import register_compiled_cache

            register_compiled_cache(self)  # analysis.recompile introspection
            l2 = self._l2_coeff
            decay_in_grad = self._apply_weight_decay_to_grad()
            opt = self

            @functools.partial(jax.jit, donate_argnums=(0, 2))
            def fused(pvals, gvals, svals, evals, lr_, step_):
                gvals = [g.astype(p.dtype) if g.dtype != p.dtype else g
                         for p, g in zip(pvals, gvals)]
                if l2 and decay_in_grad:
                    gvals = [g + l2 * p for p, g in zip(pvals, gvals)]
                return opt.apply_updates(pvals, gvals, svals, evals,
                                         opt._static_evals, lr_, step_)

            self._jit_update = fused

        pvals = [p._value for p, _ in pgs]
        gvals = [g for _, g in pgs]
        svals = [{k: s[k] for k in state_keys} for s in states]
        evals = static_evals
        new_p, new_s = self._jit_update(
            pvals, gvals, svals, evals, jnp.float32(lr), jnp.int32(self._step_count)
        )
        for (p, _), np_, ns_ in zip(pgs, new_p, new_s):
            p._inplace_set(np_)
            self._accumulators[id(p)] = ns_

    @jax.named_scope("optimizer_minimize")
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.graph import is_symbolic

        if is_symbolic(loss):
            # static mode: register the optimize spec on the loss's program —
            # the Executor computes grads inside the compiled replay and this
            # optimizer steps through its own donated-jit update (see
            # static/executor.py)
            prog = loss.block.program
            if parameters:
                params = [p for p in parameters if not p.stop_gradient]
            elif self._parameter_list is not None:
                params = self._params()
            else:
                params = [t for t in prog.captures.values() if not t.stop_gradient]
            if self._parameter_list is None:
                self._parameter_list = params
            prog._optimize_spec = (self, loss, params)
            prog._version += 1
            return None, None
        loss.backward()
        self.step()
        return None, None

    def cache_info(self):
        """Cache-key introspection (analysis.recompile): the donated jit
        update retraces per (static-extras, kernel-dispatch) signature;
        jax.jit handles shape keying underneath."""
        key = getattr(self, "_static_evals_key", None)
        return {"name": f"optimizer_update:{type(self).__name__}",
                "keys": [key] if key is not None else []}

    def _set_parameters(self, parameters):
        self._parameter_list = list(parameters)
        self._jit_update = None


class SGD(Optimizer):
    _elementwise_update = True
    _FUSED_PALLAS_KIND = "sgd"
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_one(self, p, g, state, lr, step, extras=None):
        return p - lr.astype(p.dtype) * g, state


class Momentum(Optimizer):
    _elementwise_update = True
    _FUSED_PALLAS_KIND = "momentum"
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _fused_hyper(self, extras):
        return {"momentum": self._momentum, "nesterov": self._nesterov}

    def _state_names(self):
        return ["velocity"]

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update_one(self, p, g, state, lr, step, extras=None):
        mu = self._momentum
        v = mu * state["velocity"] + g
        if self._nesterov:
            upd = g + mu * v
        else:
            upd = v
        return p - lr.astype(p.dtype) * upd, {"velocity": v}


class Adagrad(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _state_names(self):
        return ["moment"]

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._value, self._init_val)}

    def _update_one(self, p, g, state, lr, step, extras=None):
        m = state["moment"] + g * g
        return p - lr.astype(p.dtype) * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adadelta(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _state_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _init_state(self, p):
        return {
            "avg_squared_grad": jnp.zeros_like(p._value),
            "avg_squared_update": jnp.zeros_like(p._value),
        }

    def _update_one(self, p, g, state, lr, step, extras=None):
        rho, eps = self._rho, self._epsilon
        ag = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(ag + eps)
        au = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        return p - lr.astype(p.dtype) * upd, {
            "avg_squared_grad": ag, "avg_squared_update": au,
        }


class RMSProp(Optimizer):
    _elementwise_update = True
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _state_names(self):
        return ["mean_square", "mean_grad", "momentum"]

    def _init_state(self, p):
        return {
            "mean_square": jnp.zeros_like(p._value),
            "mean_grad": jnp.zeros_like(p._value),
            "momentum": jnp.zeros_like(p._value),
        }

    def _update_one(self, p, g, state, lr, step, extras=None):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        mg = state["mean_grad"]
        if self._centered:
            mg = rho * mg + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = mu * state["momentum"] + lr.astype(p.dtype) * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class ASGD(Optimizer):
    """Averaged SGD (reference ``paddle.optimizer.ASGD``): keeps the last
    ``batch_num`` gradients' running sum ``d`` (a cyclic buffer ``ys``
    holds the individual entries) and steps by lr * d / n."""

    # ys carries an extra leading [batch_num] dim, so the flat-pack
    # reshape(-1) grouping cannot treat it like a param-shaped state
    _elementwise_update = False

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._batch_num = int(batch_num)

    def _state_names(self):
        return ["d", "ys"]

    def _init_state(self, p):
        return {
            "d": jnp.zeros_like(p._value),
            "ys": jnp.zeros((self._batch_num,) + tuple(p._value.shape),
                            p._value.dtype),
        }

    def _update_one(self, p, g, state, lr, step, extras=None):
        bn = self._batch_num
        idx = (step - 1) % bn
        y_old = jax.lax.dynamic_index_in_dim(state["ys"], idx,
                                             keepdims=False)
        d = state["d"] - y_old + g
        ys = jax.lax.dynamic_update_index_in_dim(state["ys"], g, idx, 0)
        n = jnp.minimum(step, bn).astype(jnp.float32)
        new_p = p - (lr / n).astype(p.dtype) * d
        return new_p, {"d": d, "ys": ys}


class Rprop(Optimizer):
    """Resilient backprop (reference ``paddle.optimizer.Rprop``):
    per-ELEMENT step sizes grown/shrunk by gradient sign agreement;
    magnitude of the gradient is ignored."""

    _elementwise_update = True

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = (float(learning_rate_range[0]),
                                      float(learning_rate_range[1]))
        self._eta_n, self._eta_p = float(etas[0]), float(etas[1])
        self._init_lr = float(learning_rate)

    def _state_names(self):
        return ["prev_grad", "learning_rate"]

    def _init_state(self, p):
        return {
            "prev_grad": jnp.zeros_like(p._value),
            "learning_rate": jnp.full_like(p._value, self._init_lr),
        }

    def _update_one(self, p, g, state, lr, step, extras=None):
        sign = g * state["prev_grad"]
        lr_e = jnp.where(
            sign > 0,
            jnp.minimum(state["learning_rate"] * self._eta_p, self._lr_max),
            jnp.where(sign < 0,
                      jnp.maximum(state["learning_rate"] * self._eta_n,
                                  self._lr_min),
                      state["learning_rate"]))
        g_eff = jnp.where(sign < 0, jnp.zeros_like(g), g)
        new_p = p - jnp.sign(g_eff).astype(p.dtype) * lr_e.astype(p.dtype)
        return new_p, {"prev_grad": g_eff, "learning_rate": lr_e}

"""Weight initializers (reference: ``python/paddle/nn/initializer/``).

Each initializer is a callable ``(shape, dtype) -> jax array`` drawing from
the global RNG stream (``paddle_tpu.framework.random``).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.random import next_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "Bilinear", "set_global_initializer"]


def _fans(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.normal(next_key(), tuple(shape), dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        return (
            jax.random.truncated_normal(next_key(), self.a, self.b, tuple(shape), dtype)
            * self.std + self.mean
        )


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(next_key(), tuple(shape), dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_key(), tuple(shape), dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(next_key(), tuple(shape), dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ...core.tensor import Tensor

        v = self.value._value if isinstance(self.value, Tensor) else np.asarray(self.value)
        arr = jnp.asarray(v, dtype)
        assert tuple(arr.shape) == tuple(shape), (arr.shape, shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    ``paddle.nn.initializer.Bilinear``)."""

    def __call__(self, shape, dtype=jnp.float32):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D conv weight")
        kh, kw = shape[2], shape[3]
        # reference/Caffe formula: f = ceil(k/2), c = (2f - 1 - f%2)/(2f)
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        cy = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cx = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        yy, xx = np.mgrid[0:kh, 0:kw]
        filt = ((1 - np.abs(yy / fh - cy))
                * (1 - np.abs(xx / fw - cx))).astype(np.float32)
        # EVERY (in, out) channel slice gets the filter (the grouped
        # transposed-conv weight is [C, 1, kh, kw] — diagonal-only fill
        # would zero all channels but the first)
        out = np.broadcast_to(filt, shape).copy()
        return jnp.asarray(out, dtype)


_GLOBAL_INITIALIZER = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Default initializers for subsequently created parameters (reference
    ``paddle.nn.initializer.set_global_initializer``). Pass None to reset."""
    _GLOBAL_INITIALIZER["weight"] = weight_init
    _GLOBAL_INITIALIZER["bias"] = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_INITIALIZER["bias" if is_bias else "weight"]

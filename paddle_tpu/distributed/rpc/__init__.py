"""``paddle.distributed.rpc`` — point-to-point RPC between workers.

Reference counterpart: ``python/paddle/distributed/rpc/`` +
``paddle/fluid/distributed/rpc/`` (brpc-backed sync/async RPC for
heterogeneous workloads; SURVEY.md §2.2 "RPC").

TPU-native design: the data plane (tensors) rides XLA collectives; RPC is a
**control-plane** channel, so a length-prefixed pickle protocol over TCP
sockets (one serving thread pool per worker) replaces brpc — no native dep,
same API: ``init_rpc / rpc_sync / rpc_async / get_worker_info / shutdown``.
Worker discovery goes through the native C++ ``TCPStore`` (rendezvous at
``master_endpoint``), exactly like collective bootstrap.
"""

from __future__ import annotations

import concurrent.futures as _fut
import os
import pickle
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..store import TCPStore

__all__ = ["init_rpc", "shutdown", "rpc_sync", "rpc_async",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_state: Dict[str, Any] = {
    "server": None, "store": None, "workers": {}, "by_rank": {},
    "self": None, "pool": None,
}


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj)
    sock.sendall(struct.pack("!Q", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    n = struct.unpack("!Q", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed mid-message")
        buf += chunk
    return pickle.loads(bytes(buf))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            fn, args, kwargs = _recv_msg(self.request)
        except ConnectionError:
            return
        try:
            result = fn(*args, **(kwargs or {}))
            _send_msg(self.request, ("ok", result))
        except BaseException as e:  # ship the exception back to the caller
            _send_msg(self.request, ("err", e))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def _reachable_ip(master_host: str) -> str:
    """The local address peers can dial: the source IP of a route toward the
    master (no packets sent — connected UDP socket trick)."""
    if master_host in ("127.0.0.1", "localhost", "0.0.0.0", ""):
        return "127.0.0.1"
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((master_host, 9))
            return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """Start this worker's RPC server and rendezvous with peers.

    Env fallbacks mirror the launcher contract: ``PADDLE_TRAINER_ID``,
    ``PADDLE_TRAINERS_NUM``, ``PADDLE_MASTER``.
    """
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
                  if world_size is None else world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:0")
    host, port = master_endpoint.rsplit(":", 1)

    server = _Server(("0.0.0.0", 0), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    my_port = server.server_address[1]
    my_ip = os.environ.get("PADDLE_LOCAL_IP") or _reachable_ip(host)

    store = TCPStore(host=host, port=int(port), is_master=(rank == 0),
                     world_size=world_size)
    me = WorkerInfo(name, rank, my_ip, my_port)
    store.set(f"rpc/worker/{rank}", pickle.dumps(me))
    workers, by_rank = {}, {}
    for r in range(world_size):
        info: WorkerInfo = pickle.loads(store.get(f"rpc/worker/{r}"))
        workers[info.name] = info
        by_rank[r] = info

    _state.update(server=server, store=store, workers=workers,
                  by_rank=by_rank, self=me,
                  pool=_fut.ThreadPoolExecutor(max_workers=8))


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    if name is None:
        return _state["self"]
    return _state["workers"][name]


def get_all_worker_infos():
    return list(_state["by_rank"].values())


def _invoke(to: str, fn, args, kwargs, timeout: float):
    info = _state["workers"][to] if isinstance(to, str) else _state["by_rank"][to]
    with socket.create_connection((info.ip, info.port), timeout=timeout or None) as s:
        _send_msg(s, (fn, args, kwargs))
        status, payload = _recv_msg(s)
    if status == "err":
        raise payload
    return payload


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: float = 180.0):
    """Blocking remote call; returns the result (reference ``rpc_sync``)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=(), kwargs=None, timeout: float = 180.0):
    """Non-blocking remote call; returns a Future with ``.wait()``."""
    fut = _state["pool"].submit(_invoke, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle Future API compat
    return fut


def shutdown() -> None:
    """Barrier across workers, then stop serving (reference ``shutdown``)."""
    store: TCPStore = _state["store"]
    if store is None:
        return
    world = store.world_size
    store.add("rpc/shutdown", 1)
    # wait for every rank to arrive before tearing the servers down
    import time
    deadline = time.time() + 180
    while time.time() < deadline:
        if store.add("rpc/shutdown", 0) >= world:
            break
        time.sleep(0.02)
    _state["pool"].shutdown(wait=True)
    _state["server"].shutdown()
    _state["server"].server_close()
    store.close()
    _state.update(server=None, store=None, workers={}, by_rank={},
                  self=None, pool=None)

"""Model-zoo smoke tests: forward shapes at reduced resolution.

Reference: ``test/legacy_test/test_vision_models.py`` pattern — construct,
forward, check logits shape.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _run(model, size=64, classes=10):
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 3, size, size).astype(np.float32))
    model.eval()
    out = model(x)
    assert tuple(out.shape) == (2, classes)
    assert np.all(np.isfinite(out.numpy()))


@pytest.mark.parametrize("factory,size", [
    (models.alexnet, 96),
    (models.squeezenet1_0, 64),
    (models.squeezenet1_1, 64),
    (models.mobilenet_v1, 64),
    # the two fattest zoo forwards (~25 s + ~18 s measured r19) run in
    # the chip lane / -m slow only — the remaining zoo keeps tier-1's
    # construct+forward coverage of every block type they use
    pytest.param(models.mobilenet_v3_small, 64,
                 marks=pytest.mark.slow),
    (models.mobilenet_v3_large, 64),
    (models.shufflenet_v2_x0_5, 64),
    pytest.param(models.densenet121, 64, marks=pytest.mark.slow),
    (models.googlenet, 64),
])
def test_model_forward(factory, size):
    _run(factory(num_classes=10), size=size)


def test_inception_v3():
    # inception needs a larger minimum input (stem has three stride-2 stages)
    _run(models.inception_v3(num_classes=10), size=128)


def test_model_zoo_train_mode_batchnorm():
    """BatchNorm statistics update in train mode without error."""
    m = models.mobilenet_v1(num_classes=4, scale=0.25)
    m.train()
    x = paddle.to_tensor(
        np.random.RandomState(1).rand(4, 3, 32, 32).astype(np.float32))
    out = m(x)
    loss = paddle.mean(out)
    loss.backward()
    grads = [p.grad for p in m.parameters() if p.grad is not None]
    assert len(grads) > 0


def test_resnext_forward():
    from paddle_tpu.vision import models

    m = models.resnext50_32x4d(num_classes=10)
    _run(m, size=64)


def test_resnet_nhwc_matches_nchw():
    """data_format="NHWC" (reference PaddleClas option): channel-last
    network must match the channel-first one numerically."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.vision import models

    paddle.seed(0)
    m1 = models.resnet18(num_classes=10)
    paddle.seed(0)
    m2 = models.resnet18(num_classes=10, data_format="NHWC")
    m1.eval()
    m2.eval()
    x = np.random.RandomState(0).rand(2, 3, 32, 32).astype("float32")
    o1 = m1(paddle.to_tensor(x)).numpy()
    o2 = m2(paddle.to_tensor(x.transpose(0, 2, 3, 1))).numpy()
    np.testing.assert_allclose(o2, o1, rtol=1e-4, atol=2e-4)

"""``paddle.utils`` — extension loading and misc helpers."""

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension"]

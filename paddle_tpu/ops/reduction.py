"""Reduction ops (reference: ``paddle/phi/kernels/*/reduce_*``, ``funcs/ReduceKernel``;
Python surface ``python/paddle/tensor/stat.py``/``math.py``; SURVEY.md §2.1)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from .dispatch import run_op
from .registry import register_op

__all__ = [
    "sum", "mean", "max", "min", "amax", "amin", "prod", "std", "var",
    "argmax", "argmin", "all", "any", "count_nonzero", "logsumexp", "median",
    "nanmedian", "nansum", "nanmean", "norm", "quantile", "mode", "kthvalue",
]


def _axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, list):
        axis = tuple(axis)
    return axis


@register_op()
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return run_op("sum", lambda a: jnp.sum(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def mean(x, axis=None, keepdim=False, name=None):
    return run_op("mean", lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def max(x, axis=None, keepdim=False, name=None):
    return run_op("max", lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def min(x, axis=None, keepdim=False, name=None):
    return run_op("min", lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


amax = max
amin = min


@register_op()
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return run_op("prod", lambda a: jnp.prod(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return run_op("std", lambda a: jnp.std(a, axis=_axis(axis), ddof=ddof, keepdims=keepdim), x)


@register_op()
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return run_op("var", lambda a: jnp.var(a, axis=_axis(axis), ddof=ddof, keepdims=keepdim), x)


@register_op(differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        r = jnp.argmax(a, axis=_axis(axis), keepdims=keepdim if axis is not None else False)
        return r

    return run_op("argmax", f, x)


@register_op(differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        r = jnp.argmin(a, axis=_axis(axis), keepdims=keepdim if axis is not None else False)
        return r

    return run_op("argmin", f, x)


@register_op(differentiable=False)
def all(x, axis=None, keepdim=False, name=None):
    return run_op("all", lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op(differentiable=False)
def any(x, axis=None, keepdim=False, name=None):
    return run_op("any", lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op(differentiable=False)
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return run_op(
        "count_nonzero",
        lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim),
        x,
    )


@register_op()
def logsumexp(x, axis=None, keepdim=False, name=None):
    return run_op(
        "logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x
    )


@register_op()
def median(x, axis=None, keepdim=False, name=None):
    return run_op("median", lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def nanmedian(x, axis=None, keepdim=False, name=None):
    return run_op("nanmedian", lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return run_op("nansum", lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def nanmean(x, axis=None, keepdim=False, name=None):
    return run_op("nanmean", lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


@register_op()
def norm(x, p=2, axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" or (p == 2 and axis is None):
            return jnp.sqrt(jnp.sum(a * a, axis=_axis(axis), keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=_axis(axis), keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=_axis(axis), keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=_axis(axis), keepdims=keepdim) ** (1.0 / p)

    return run_op("norm", f, x)


@register_op()
def quantile(x, q, axis=None, keepdim=False, name=None):
    return run_op("quantile", lambda a: jnp.quantile(a, q, axis=_axis(axis), keepdims=keepdim), x)


@register_op(differentiable=False)
def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        moved = jnp.moveaxis(a, axis, -1)
        eq = moved[..., :, None] == moved[..., None, :]
        counts = eq.sum(-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(moved == vals[..., None], axis=-1)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    return run_op("mode", f, x, n_diff_outputs=1)


@register_op()
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        order = jnp.argsort(a, axis=axis)
        i = jnp.take(order, k - 1, axis=axis)
        v = jnp.take_along_axis(a, jnp.expand_dims(i, axis), axis=axis)
        if not keepdim:
            v = jnp.squeeze(v, axis)
            idx = i
        else:
            idx = jnp.expand_dims(i, axis)
        return v, idx

    return run_op("kthvalue", f, x, n_diff_outputs=1)

"""Strategy meta-optimizer tests: GradientMerge, DGC, ASP, FP16AllReduce,
LocalSGD (reference: ``test/collective/fleet`` meta-optimizer unit tests)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.meta_optimizers import (
    ASPOptimizer, DGCOptimizer, FP16AllReduceOptimizer,
    GradientMergeOptimizer, LocalSGDOptimizer)


def _linear_and_data(seed=0):
    rng = np.random.RandomState(seed)
    lin = nn.Linear(4, 1)
    x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 1).astype(np.float32))
    return lin, x, y


def test_gradient_merge_equals_large_batch():
    """k accumulated micro-steps == one step on the averaged grad."""
    lin, x, y = _linear_and_data()
    w0 = lin.weight.numpy().copy()

    # reference: single step with grads averaged over two halves
    lin_ref, _, _ = _linear_and_data()
    lin_ref.weight._inplace_set(paddle.to_tensor(w0.copy())._value)
    lin_ref.bias._inplace_set(paddle.to_tensor(lin.bias.numpy().copy())._value)
    opt_ref = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin_ref.parameters())
    loss = paddle.mean((lin_ref(x) - y) ** 2)
    loss.backward()
    opt_ref.step()

    opt = GradientMergeOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters()), k_steps=2)
    for half in (slice(0, 4), slice(4, 8)):
        # per-half grads; mean over half-batch then averaged by merge = the
        # full-batch mean (equal halves)
        loss = paddle.mean((lin(x[half]) - y[half]) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(lin.weight.numpy(), lin_ref.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_gradient_merge_no_update_midway():
    lin, x, y = _linear_and_data()
    w0 = lin.weight.numpy().copy()
    opt = GradientMergeOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters()), k_steps=3)
    loss = paddle.mean((lin(x) - y) ** 2)
    loss.backward()
    opt.step()
    np.testing.assert_allclose(lin.weight.numpy(), w0)  # no real step yet


def test_dgc_sparsifies_but_converges():
    lin, x, y = _linear_and_data()
    opt = DGCOptimizer(
        paddle.optimizer.SGD(learning_rate=0.05,
                             parameters=lin.parameters()),
        rampup_begin_step=0, sparsity=0.5, momentum=0.0)
    losses = []
    for _ in range(60):
        loss = paddle.mean((lin(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_asp_2_4_mask():
    lin = nn.Linear(8, 8)
    opt = ASPOptimizer(paddle.optimizer.SGD(
        learning_rate=0.01, parameters=lin.parameters()))
    opt.prune_model()
    w = lin.weight.numpy().reshape(-1, 4)
    nz = (w != 0).sum(axis=1)
    assert np.all(nz <= 2), nz
    # sparsity survives an update step
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(
        np.float32))
    loss = paddle.mean(lin(x) ** 2)
    loss.backward()
    opt.step()
    w2 = lin.weight.numpy().reshape(-1, 4)
    assert np.all(((w2 != 0).sum(axis=1)) <= 2)


def test_fp16_allreduce_single_rank():
    lin, x, y = _linear_and_data()
    opt = FP16AllReduceOptimizer(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=lin.parameters()))
    l0 = None
    for _ in range(20):
        loss = paddle.mean((lin(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        l0 = l0 or float(loss)
    assert float(loss) < l0


def test_localsgd_single_rank_noop_sync():
    lin, x, y = _linear_and_data()
    opt = LocalSGDOptimizer(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=lin.parameters()), k_steps=2)
    for _ in range(4):
        loss = paddle.mean((lin(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert np.all(np.isfinite(lin.weight.numpy()))

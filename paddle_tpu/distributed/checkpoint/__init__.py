"""Distributed checkpoint: sharded save/load with reshard-on-load.

Reference counterpart: ``python/paddle/distributed/checkpoint/``
(SURVEY.md §2.2 "Distributed checkpoint", §5.4): every rank writes its shard
of the (TP/PP/ZeRO-partitioned) state dict plus a metadata manifest; load
reshards when the target mesh/strategy differs from the saved one — plus the
Fleet offline merge tools.

TPU-native mapping: **orbax-checkpoint is the engine** (already the standard
for JAX sharded state): ``save_state_dict`` writes each array's global value
from its distributed shards (OCDBT format, one logical manifest);
``load_state_dict`` restores *into the shardings of the passed state dict*,
so loading a checkpoint saved on one mesh into a model sharded over another
IS the reshard-on-load path — no offline merge tooling needed, which is the
point of keeping parameters logical in this framework.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _checkpointer(asynchronous: bool = False):
    import orbax.checkpoint as ocp

    if asynchronous:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.StandardCheckpointer()


def _items(container):
    """Uniform (key, value) iteration over dicts and lists/tuples — list
    entries get index keys, so per-param lists survive the round trip."""
    if isinstance(container, dict):
        return container.items()
    return ((str(i), v) for i, v in enumerate(container))


def _flatten(state_dict, prefix: str = "") -> Dict[str, Any]:
    flat = {}
    for k, v in _items(state_dict):
        key = f"{prefix}{k}"
        if isinstance(v, (dict, list, tuple)):
            flat.update(_flatten(v, key + "/"))
        elif isinstance(v, Tensor):
            flat[key] = v._value
        elif v is None or isinstance(v, (str, bytes)):
            continue  # non-array metadata (e.g. scheduler type tags)
        else:
            try:
                arr = np.asarray(v)
                if arr.dtype == object:
                    raise TypeError(f"object dtype from {type(v).__name__}")
            except Exception as e:
                raise TypeError(
                    f"state_dict entry '{key}' of type {type(v).__name__} is "
                    "not checkpointable (expected Tensor/array/number or a "
                    "dict/list of those)"
                ) from e
            flat[key] = arr
    return flat


# async saves in flight: [(checkpointer, path)] — drained by
# wait_async_save() or at interpreter exit (the reference's async
# save handle/Future)
_pending_async = []


def wait_async_save() -> None:
    """Block until all async_save=True checkpoints are durable."""
    while _pending_async:
        ckptr, _ = _pending_async.pop()
        ckptr.wait_until_finished()
        ckptr.close()


import atexit as _atexit

_atexit.register(wait_async_save)


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False) -> None:
    """Write ``state_dict`` (Tensors may be sharded over any mesh) to
    ``path``. Signature follows the reference's
    ``dist.save_state_dict(state_dict, path)``. With ``async_save=True`` the
    write overlaps training (orbax AsyncCheckpointer); call
    ``wait_async_save()`` (or rely on the atexit hook) before reading it
    back."""
    flat = _flatten(state_dict)
    path = os.path.abspath(path)
    if async_save:
        import orbax.checkpoint as ocp

        ckptr = _checkpointer(asynchronous=True)
        ckptr.save(path, args=ocp.args.StandardSave(flat), force=True)
        _pending_async.append((ckptr, path))
        return
    ckptr = _checkpointer()
    ckptr.save(path, flat, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, unique_id=None,
                    offload: bool = False) -> None:
    """Restore ``path`` into ``state_dict`` IN PLACE, resharding every array
    to the sharding the corresponding target tensor currently has (the
    reference's reshard-on-load across different meshes/strategies)."""
    tensor_targets: Dict[str, Tensor] = {}
    plain_targets: Dict[str, tuple] = {}  # key → (parent dict, dict key)
    template: Dict[str, Any] = {}

    def walk(d, prefix=""):
        for k, v in _items(d):
            key = f"{prefix}{k}"
            if isinstance(v, (dict, list, tuple)):
                walk(v, key + "/")
            elif isinstance(v, Tensor):
                tensor_targets[key] = v
                template[key] = jax.ShapeDtypeStruct(
                    v._value.shape, v._value.dtype,
                    sharding=getattr(v._value, "sharding", None))
            elif v is None or isinstance(v, (str, bytes)):
                continue
            else:
                try:
                    template[key] = np.asarray(v)
                    plain_targets[key] = (d, k if isinstance(d, dict) else int(k))
                except Exception as e:
                    raise TypeError(
                        f"state_dict entry '{key}' of type {type(v).__name__} "
                        "is not checkpointable"
                    ) from e

    walk(state_dict)
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    restored = ckptr.restore(path, template)
    ckptr.close()
    for k, t in tensor_targets.items():
        t._inplace_set(restored[k])
    for k, (parent, pk) in plain_targets.items():
        val = restored[k]
        orig = parent[pk]
        if np.isscalar(orig) or (hasattr(orig, "ndim") and orig.ndim == 0):
            val = np.asarray(val).reshape(()).item() if not hasattr(
                orig, "dtype") else np.asarray(val, dtype=orig.dtype).reshape(())
        parent[pk] = val

"""Attach op methods & dunders to Tensor.

The reference exposes tensor methods from pybind (``eager_method.cc``) plus
monkey-patching in ``python/paddle/tensor/__init__.py`` — same discipline
here: the op corpus is the single source, and this module wires it onto the
``Tensor`` class at import time.
"""

from __future__ import annotations

from ..core.tensor import Tensor
from . import (creation, linalg, logic, manipulation, math, reduction,
               special, tail)


def attach():
    T = Tensor
    # arithmetic dunders
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(o, s)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(o, s)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    T.__mod__ = lambda s, o: math.mod(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(o, s)
    T.__matmul__ = lambda s, o: math.matmul(s, o)
    T.__rmatmul__ = lambda s, o: math.matmul(o, s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__invert__ = lambda s: logic.bitwise_not(s)
    T.__and__ = lambda s, o: logic.bitwise_and(s, o)
    T.__or__ = lambda s, o: logic.bitwise_or(s, o)
    T.__xor__ = lambda s, o: logic.bitwise_xor(s, o)
    # comparisons
    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)
    T.__hash__ = object.__hash__  # identity hash despite __eq__, like paddle

    # method surface (paddle.Tensor methods)
    # tail/special last: the earlier modules' names win collisions
    for mod in (math, reduction, manipulation, logic, creation, linalg,
                special, tail):
        for name in getattr(mod, "__all__", []):
            fn = getattr(mod, name)
            if not callable(fn) or hasattr(T, name):
                continue
            setattr(T, name, fn)

    # aliases / specialisations
    T.add = math.add
    T.t = lambda s: manipulation.transpose(s)
    T.mT = property(lambda s: manipulation.swapaxes(s, -1, -2))
    T.T = property(lambda s: manipulation.transpose(s))
    T.pow = math.pow
    T.abs = math.abs
    T.sum = reduction.sum
    T.mean = reduction.mean
    T.max = reduction.max
    T.min = reduction.min
    T.unsqueeze = manipulation.unsqueeze
    T.squeeze = manipulation.squeeze
    T.reshape = manipulation.reshape
    T.flatten = manipulation.flatten
    T.transpose = manipulation.transpose
    T.matmul = math.matmul
    T.norm = reduction.norm
    T.split = manipulation.split
    T.chunk = manipulation.chunk
    T.gather = manipulation.gather
    T.topk = manipulation.topk
    T.argmax = reduction.argmax
    T.argmin = reduction.argmin
    T.argsort = manipulation.argsort
    T.sort = manipulation.sort
    T.tile = manipulation.tile
    T.expand = manipulation.expand
    T.flip = manipulation.flip
    T.roll = manipulation.roll
    T.where = lambda s, x, y: manipulation.where(s, x, y)
    T.exp = math.exp
    T.log = math.log
    T.sqrt = math.sqrt
    T.rsqrt = math.rsqrt
    T.tanh = math.tanh
    T.sigmoid = lambda s: math.reciprocal(math.add(math.exp(math.neg(s)), 1.0))
    T.clip = math.clip
    T.scale = math.scale
    T.cumsum = math.cumsum
    T.clone = T.clone  # defined on class

    # dense → sparse conversions (reference: Tensor.to_sparse_coo / pybind
    # eager_method sparse conversions); lazy import to keep ops→sparse acyclic
    def _to_sparse_coo(s, sparse_dim):
        from .. import sparse as _sp
        import jax.numpy as jnp
        import numpy as np

        arr = s._value
        sd = int(sparse_dim)
        import jax

        if isinstance(arr, jax.core.Tracer):
            raise ValueError(
                "Tensor.to_sparse_coo needs concrete values: the sparsity "
                "pattern is data-dependent and cannot be traced under jit/"
                "static capture (the reference's DenseToCoo kernel has a "
                "data-dependent output shape for the same reason).")
        dense = np.asarray(arr)
        mask = (dense.reshape(dense.shape[:sd] + (-1,)) != 0).any(-1) \
            if dense.ndim > sd else dense != 0
        idx = np.stack(np.nonzero(mask)).astype(np.int64)
        # gather values through run_op so autograd flows from the sparse
        # tensor's values back to the dense source
        vals = run_op_gather(s, idx)
        return _sp.SparseCooTensor(
            _sp.to_tensor(jnp.asarray(idx)), vals, list(arr.shape))

    def run_op_gather(s, idx):
        from .dispatch import run_op
        import jax.numpy as jnp

        def fn(a):
            return a[tuple(jnp.asarray(idx))]

        return run_op("dense_to_sparse_values", fn, s)

    T.to_sparse_coo = _to_sparse_coo
    T.to_sparse_csr = lambda s: _to_sparse_coo(s, 2).to_sparse_csr()

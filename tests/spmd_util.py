"""Shared helper for the multi-process SPMD launch tests: the
single-process reference computation that the launcher-spawned workers'
loss must match (same tiny llama step on this pytest process's own
virtual devices)."""

import numpy as np


def single_process_llama_loss(dp, mp, batch=4, seq=64, seed=0, lr=1e-3):
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.models import llama
    from paddle_tpu.parallel import create_hybrid_mesh, host_to_global, set_mesh

    mesh = create_hybrid_mesh(dp=dp, mp=mp)
    try:
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg)
        opt = llama.init_opt_state(params)
        ps = llama.param_specs(cfg)
        os_ = llama.opt_state_specs(cfg)
        gp = {k: host_to_global(np.asarray(v), ps[k], mesh)
              for k, v in params.items()}
        go = {
            "step": host_to_global(np.asarray(opt["step"]), P(), mesh),
            "m": {k: host_to_global(np.asarray(v), os_[k], mesh)
                  for k, v in opt["m"].items()},
            "v": {k: host_to_global(np.asarray(v), os_[k], mesh)
                  for k, v in opt["v"].items()},
        }
        tokens = np.random.RandomState(seed).randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        gtok = host_to_global(tokens, P(("dp", "sharding"), None), mesh)
        step = llama.make_sharded_train_step(cfg, mesh, lr=lr)
        _, _, loss = step(gp, go, gtok, gtok)
        return float(np.asarray(loss))
    finally:
        set_mesh(None)

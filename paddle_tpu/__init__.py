"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the capabilities of the reference framework
(wishgale/Paddle, a PaddlePaddle fork — see SURVEY.md) for TPU hardware:
jax/XLA is the device runtime + kernel library + graph compiler, Pallas
provides the hand-tuned kernels, ``jax.sharding`` over device meshes is the
distributed substrate, and the dygraph-style eager API with ``.backward()``
runs on a tape of XLA VJPs.

Public surface mirrors ``import paddle`` where it makes sense
(``paddle_tpu.to_tensor``, ``paddle_tpu.nn.Layer``, ``paddle_tpu.optimizer``,
``paddle_tpu.distributed`` …).
"""

from __future__ import annotations

__version__ = "0.1.0"

from . import flags as _flags_mod
from .flags import get_flags, set_flags

from .core import (
    CPUPlace,
    CUDAPlace,
    CustomPlace,
    Place,
    TPUPlace,
    Tensor,
    enable_grad,
    get_device,
    is_compiled_with_tpu,
    no_grad,
    set_device,
    set_grad_enabled,
    to_tensor,
)
from .core.dtype import (
    bfloat16,
    bool_,
    complex64,
    complex128,
    dtype,
    finfo,
    float16,
    float32,
    float64,
    iinfo,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from .core.autograd import is_grad_enabled

# op corpus onto the top-level namespace (paddle.add, paddle.matmul, ...)
from .ops import *  # noqa: F401,F403
from .ops import creation, linalg, logic, manipulation, math, reduction  # noqa: F401
from .ops.registry import all_ops

from .framework.random import (get_cuda_rng_state, get_rng_state, seed,
                               set_cuda_rng_state, set_rng_state)
from .framework.io import load, save

from . import _C_ops  # noqa: F401
from . import amp  # noqa: F401
from . import fft  # noqa: F401
from . import geometric  # noqa: F401
from . import hub  # noqa: F401
from . import onnx  # noqa: F401
from . import autograd  # noqa: F401
from . import device  # noqa: F401
from . import distribution  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import quantization  # noqa: F401
from . import sparse  # noqa: F401
from . import signal  # noqa: F401
from . import audio  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import sysconfig  # noqa: F401
from . import version  # noqa: F401
from .hapi import callbacks  # noqa: F401  (paddle.callbacks alias)
from . import static  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401
from . import inference  # noqa: F401
from . import observability  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi.model_summary import flops, summary  # noqa: F401
from .nn.layer.layers import ParamAttr  # noqa: F401
from .ops import linalg  # noqa: F401

# paddle.DataParallel / distributed entry points live in paddle_tpu.distributed
# (imported lazily to keep single-process import light)

_LAZY_SUBMODULES = ("distributed", "incubate", "analysis")


def __getattr__(name):
    # PEP 562: `import paddle_tpu as paddle; paddle.distributed.…` must work
    # (the reference's documented entry pattern) without paying the
    # distributed-stack import at plain-`import paddle_tpu` time. The import
    # system sets the attribute on this package, so the hook fires once.
    if name in _LAZY_SUBMODULES:
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def DataParallel(layers, **kwargs):
    from .distributed.parallel import DataParallel as _DP

    return _DP(layers, **kwargs)

import jax as _jax


def is_compiled_with_cuda() -> bool:
    return any(d.platform == "gpu" for d in _jax.devices())


def is_compiled_with_xpu() -> bool:
    return False


def in_dynamic_mode() -> bool:
    """Eager (dygraph) mode is the default; ``enable_static()`` switches to
    program-recording mode (see paddle_tpu/static/graph.py)."""
    from .static.graph import in_static_mode

    return not in_static_mode()


def disable_static():
    from .static.graph import disable_static as _ds

    _ds()


def enable_static():
    from .static.graph import enable_static as _es

    _es()


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
         only_inputs=True, allow_unused=False):
    """``paddle.grad`` analog over the eager tape."""
    from .autograd import grad as _grad

    return _grad(outputs, inputs, grad_outputs, retain_graph, create_graph,
                 only_inputs, allow_unused)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor print formatting (reference: ``paddle.set_printoptions``) —
    tensors render through numpy, so this maps onto numpy printoptions."""
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    _np.set_printoptions(**kw)


class LazyGuard:
    """API parity with the reference's deferred-parameter-init guard.
    Initialisation here is eager numpy on host (cheap) and device buffers
    only materialise on first use, so the guard has nothing to defer; it
    exists so reference scripts run unchanged."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """Old-style reader decorator (reference: ``paddle.batch``): wraps an
    item-yielding reader into a batch-yielding one."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def disable_signal_handler():
    """API parity: the reference uninstalls its C++ crash handlers; this
    runtime installs none, so there is nothing to disable."""

"""Shared-prefix KV cache (r7 tentpole, VERDICT r5 stretch item 9).

Reference counterpart: the prefix/prompt caches in production serving
stacks (vLLM's block-level prefix caching, SGLang's RadixAttention; the
reference's serving engines cache system-prompt KV the same way): when
many requests share a prompt prefix — a system prompt, few-shot
exemplars, a long document — the prefix's KV rows are identical across
requests (greedy prefill is deterministic and rope keys depend only on
absolute position), so prefilling it once and copying rows is pure win
over recomputing it per request.

TPU-native shape of the idea: entries are **contiguous row blocks of the
slot-layout cache** ([L, plen, Hkv, D] device arrays), not paged block
tables — the serving engine's cache is slot-contiguous (ragged, unpaged;
see inference/serving.py), so a prefix "hit" is ONE dynamic_update_slice
of the reused rows into the admit window followed by a *suffix-only*
prefill, all inside the fused segment program. Matching is exact-token
and block-aligned, over a flat LRU of entries (entry count is small —
dozens — so an O(entries) host scan beats maintaining a radix tree, and
it naturally credits PARTIAL overlaps: a prompt sharing only the first
64 of a cached 128-row prefix still reuses those 64 rows).

Population is admission-driven: after a segment admits a request cold,
the engine harvests rows [0, plen_b) of its slot (they hold exactly the
prompt's keys until the slot is reused) and inserts them — so the FIRST
request of a shared-prefix burst warms the cache for the rest, with no
workload declaration needed. ``put_prompt`` additionally lets a caller
register a known prefix (system prompt) ahead of traffic via
``llama.prompt_kv``.

Capacity is bounded in KV tokens held; eviction is LRU over entries.
All lookup state is host-side; only the KV rows live on device.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..observability import flight as _flight
from ..observability import metrics as _metrics
from .paged_kv import _notify as _pool_notify

__all__ = ["PrefixCache", "PrefixMatch", "PagedPrefixCache",
           "PagedPrefixMatch", "make_prefix_cache"]


def make_prefix_cache(engine, block: int = 32,
                      capacity_tokens: int = 16384):
    """The ONE prefix cache for ONE engine (r12 fleet isolation): a
    paged engine gets a ``PagedPrefixCache`` wrapping ITS pager (page
    refs must bump the allocator the slots actually draw from — sharing
    a cache across engines would retain pages of the wrong pool), a
    contiguous engine gets a ``PrefixCache`` at the engine-independent
    block. The fleet router builds one per replica through here
    (``prefix_caches="auto"``); nothing in this module is process-global
    state, so N engines in one process never alias lookup state.

    **Why:** the caches assume their entries' device rows / page ids
    belong to the engine that harvested them; keyed-off-the-engine
    construction makes that assumption structural instead of
    conventional."""
    if getattr(engine, "paged", False):
        return PagedPrefixCache(engine.pager,
                                capacity_pages=max(
                                    1, capacity_tokens
                                    // engine.pager.page_size))
    return PrefixCache(block=block, capacity_tokens=capacity_tokens)


@dataclass
class _Entry:
    tokens: np.ndarray   # [n] int32, n a multiple of block
    k: object            # [L, n, Hkv, D] device array
    v: object            # [L, n, Hkv, D]


@dataclass
class PrefixMatch:
    length: int          # reusable rows (block multiple, < len(prompt))
    k: object            # [L, >=length, Hkv, D] — slice [:, :length] to use
    v: object


def _common_prefix(a: np.ndarray, b: np.ndarray) -> int:
    n = min(len(a), len(b))
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return n if len(neq) == 0 else int(neq[0])


class PrefixCache:
    def __init__(self, block: int = 32, capacity_tokens: int = 16384):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self.block = int(block)
        self.capacity_tokens = int(capacity_tokens)
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._tokens_held = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0       # KV rows NOT re-prefilled thanks to hits
        self.evictions = 0

    # --- alignment helpers (admission code paths share one rule) ---------
    def round_down(self, n: int) -> int:
        return (int(n) // self.block) * self.block

    def round_up(self, n: int) -> int:
        return -(-int(n) // self.block) * self.block

    @staticmethod
    def _key(tokens: np.ndarray) -> bytes:
        return tokens.tobytes()

    # --- lookup / population ---------------------------------------------
    def match(self, prompt) -> Optional[PrefixMatch]:
        """Longest block-aligned common prefix between ``prompt`` and any
        cached entry — STRICT (never the whole prompt: at least one
        token must remain to prefill, since admission samples the first
        generated token from the prompt's last position)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cap = self.round_down(len(prompt))
        if cap == len(prompt):
            cap -= self.block
        best_l, best_key = 0, None
        if cap > 0:
            for key, ent in self._entries.items():
                m = self.round_down(min(_common_prefix(prompt, ent.tokens),
                                        cap))
                if m > best_l:
                    best_l, best_key = m, key
        if best_key is None:
            self.misses += 1
            _metrics.counter("serving.prefix_cache.misses").inc()
            return None
        ent = self._entries[best_key]
        self._entries.move_to_end(best_key)
        self.hits += 1
        self.hit_tokens += best_l
        _metrics.counter("serving.prefix_cache.hits").inc()
        _metrics.counter("serving.prefix_cache.hit_tokens").inc(best_l)
        _flight.record("prefix_hit", rows=best_l,
                       prompt_len=int(len(prompt)))
        return PrefixMatch(best_l, ent.k, ent.v)

    def insert(self, tokens, k, v) -> None:
        """Insert KV rows for ``tokens`` (len must be a block multiple;
        ``k``/``v`` [L, len, Hkv, D] device arrays). An entry already
        covering these tokens (it starts with them) makes this a no-op;
        an existing entry that is a PREFIX of the new tokens is replaced
        (the longer entry serves every lookup the shorter one did)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n % self.block or n == 0:
            raise ValueError(
                f"prefix length {n} is not a positive multiple of "
                f"block {self.block}")
        stale = []
        for key, ent in self._entries.items():
            m = _common_prefix(tokens, ent.tokens)
            if m == n and len(ent.tokens) >= n:
                self._entries.move_to_end(key)
                return                      # already covered
            if m == len(ent.tokens):
                stale.append(key)           # subsumed by the new entry
        for key in stale:
            old = self._entries.pop(key)
            self._tokens_held -= len(old.tokens)
        self._entries[self._key(tokens)] = _Entry(tokens, k, v)
        self._tokens_held += n
        while self._tokens_held > self.capacity_tokens and \
                len(self._entries) > 1:
            _, old = self._entries.popitem(last=False)
            self._tokens_held -= len(old.tokens)
            self.evictions += 1
            _metrics.counter("serving.prefix_cache.evictions").inc()
            _flight.record("prefix_evict", rows=len(old.tokens),
                           tokens_held=self._tokens_held)
        _metrics.gauge("serving.prefix_cache.tokens_held").set(
            self._tokens_held)

    def put_prompt(self, params, tokens, cfg) -> None:
        """Ahead-of-traffic registration: prefill ``tokens`` standalone
        (``llama.prompt_kv``) and insert the block-trimmed rows."""
        from ..models import llama

        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = self.round_down(len(tokens))
        if n == 0:
            raise ValueError(
                f"prompt of {len(tokens)} tokens is shorter than one "
                f"block ({self.block})")
        cache, _ = llama.prompt_kv(params, tokens[:n], cfg)
        self.insert(tokens[:n], cache["k"][:, 0], cache["v"][:, 0])

    def reset(self) -> None:
        """Drop all entries and zero counters (the scheduler's warm-run
        isolation hook — warmup must not pre-populate measured hits)."""
        self.__init__(block=self.block,
                      capacity_tokens=self.capacity_tokens)

    # --- stats ------------------------------------------------------------
    @property
    def tokens_held(self) -> int:
        return self._tokens_held

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "hit_tokens": self.hit_tokens,
                "tokens_held": self._tokens_held,
                "entries": len(self._entries),
                "evictions": self.evictions}


# ---------------------------------------------------------------------------
# Paged prefix cache (r11): page-ref LRU — a hit is a ref bump, not a copy
# ---------------------------------------------------------------------------


@dataclass
class _PagedEntry:
    tokens: np.ndarray   # [n] int32, n a multiple of page_size
    pages: list          # physical page ids, one per page_size tokens


@dataclass
class PagedPrefixMatch:
    length: int          # reusable rows (page multiple, < len(prompt))
    pages: list          # the physical pages holding those rows


class PagedPrefixCache:
    """Shared-prefix cache over the PAGED KV pool (the r7 row-copy LRU
    rewritten for inference/paged_kv.py): entries hold page IDS, not KV
    arrays. Insertion retains the admitted request's prompt pages (one
    refcount bump per page — the rows are harvested by REFERENCE, the
    slot and the cache literally share physical pages); a hit hands the
    same page ids to the new request's reservation, which retains them
    again. Zero KV rows are copied anywhere in the hit path — the r7
    cache's dynamic_update_slice of reused rows into the admit window
    is gone, and "reuse" is true dedup across every live request +
    the cache (N sharers of a 192-row prefix hold its pages ONCE).

    Granularity is whole pages (the page IS the block — sharers must
    never write a shared page, and suffix writes start at the page
    boundary after the hit, so the serving path never needs a COW
    break). Matching is exact-token over a flat LRU, same policy as the
    r7 cache; capacity is bounded in PAGES held and eviction releases
    page refs (a page shared with a live slot frees only when that slot
    retires — eviction can't corrupt anyone). ``evict_until`` lets the
    admission path reclaim cache-held pages under page pressure before
    deferring a request (the cache must yield to live traffic)."""

    def __init__(self, pager, capacity_pages: int = 512):
        self.pager = pager
        self.block = pager.page_size      # alignment rule = the page
        self.capacity_pages = int(capacity_pages)
        self._entries: "OrderedDict[bytes, _PagedEntry]" = OrderedDict()
        self._pages_held = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0

    def round_down(self, n: int) -> int:
        return (int(n) // self.block) * self.block

    def round_up(self, n: int) -> int:
        return -(-int(n) // self.block) * self.block

    # --- lookup -----------------------------------------------------------
    def match(self, prompt) -> Optional[PagedPrefixMatch]:
        """Longest whole-page common prefix between ``prompt`` and any
        cached entry — STRICT (at least one token must remain to
        prefill). Returns page ids WITHOUT retaining them: the
        reservation (``PagedKVCache.reserve``) takes the refs, so a
        deferred admission leaves no dangling count."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cap = self.round_down(len(prompt))
        if cap == len(prompt):
            cap -= self.block
        best_l, best_key = 0, None
        if cap > 0:
            for key, ent in self._entries.items():
                m = self.round_down(min(_common_prefix(prompt, ent.tokens),
                                        cap))
                if m > best_l:
                    best_l, best_key = m, key
        if best_key is None:
            self.misses += 1
            _metrics.counter("serving.prefix_cache.misses").inc()
            return None
        ent = self._entries[best_key]
        self._entries.move_to_end(best_key)
        self.hits += 1
        self.hit_tokens += best_l
        _metrics.counter("serving.prefix_cache.hits").inc()
        _metrics.counter("serving.prefix_cache.hit_tokens").inc(best_l)
        _flight.record("prefix_hit", rows=best_l,
                       prompt_len=int(len(prompt)),
                       pages=best_l // self.block)
        return PagedPrefixMatch(best_l, ent.pages[:best_l // self.block])

    # --- population -------------------------------------------------------
    def insert(self, tokens, pages) -> None:
        """Insert the prefix ``tokens`` held by the given LIVE pages
        (one page per ``page_size`` tokens, currently referenced by the
        admitted slot). The cache RETAINS them — harvest by reference.
        Covered/subsumed entries are handled like the r7 cache."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n % self.block or n == 0:
            raise ValueError(
                f"prefix length {n} is not a positive multiple of "
                f"page_size {self.block}")
        if len(pages) != n // self.block:
            raise ValueError(f"{len(pages)} pages cannot hold {n} rows "
                             f"at {self.block}/page")
        stale = []
        for key, ent in self._entries.items():
            m = _common_prefix(tokens, ent.tokens)
            if m == n and len(ent.tokens) >= n:
                self._entries.move_to_end(key)
                return                      # already covered
            if m == len(ent.tokens):
                stale.append(key)           # subsumed by the new entry
        for key in stale:
            self._evict(key)
        self.pager.allocator.retain(pages)
        _pool_notify("cache_retain", len(pages), self.pager.allocator)
        self._entries[tokens.tobytes()] = _PagedEntry(tokens, list(pages))
        self._pages_held += len(pages)
        while self._pages_held > self.capacity_pages and \
                len(self._entries) > 1:
            self._evict(next(iter(self._entries)), count=True)
        _metrics.gauge("serving.prefix_cache.pages_held").set(
            self._pages_held)

    def _evict(self, key: bytes, count: bool = False) -> None:
        ent = self._entries.pop(key)
        self.pager.release_pages(ent.pages)
        _pool_notify("cache_release", len(ent.pages), self.pager.allocator)
        self._pages_held -= len(ent.pages)
        if count:
            self.evictions += 1
            _metrics.counter("serving.prefix_cache.evictions").inc()
            _flight.record("page_evict", pages=len(ent.pages),
                           pages_held=self._pages_held)

    def evict_until(self, pages_free: int) -> int:
        """Release LRU entries until the allocator has ``pages_free``
        free pages (or the cache is empty). The page-pressure valve:
        admission calls this before deferring a request, so cache-held
        history never starves live traffic. Returns entries evicted."""
        n = 0
        while (self._entries
               and self.pager.allocator.pages_free < pages_free):
            self._evict(next(iter(self._entries)), count=True)
            n += 1
        return n

    def clear(self) -> None:
        while self._entries:
            self._evict(next(iter(self._entries)))

    def reset(self) -> None:
        """Release all page refs and zero counters (warm-run isolation —
        same hook as ``PrefixCache.reset``; the PAGER keeps its pool)."""
        self.clear()
        self.hits = self.misses = self.hit_tokens = self.evictions = 0

    # --- stats ------------------------------------------------------------
    @property
    def pages_held(self) -> int:
        return self._pages_held

    def reclaimable_pages(self) -> int:
        """Pages eviction would actually return to the free list RIGHT
        NOW: cache-held pages not also referenced by a live slot (a
        shared page only frees when its last reference dies, so the
        slot-shared subset is pinned regardless of what the cache
        does). The r18 capacity plane's 'free + reclaimable'
        availability term — host set arithmetic over the pager's
        mirrors."""
        held = {p for ent in self._entries.values() for p in ent.pages}
        live = {p for pages in self.pager.slot_pages for p in pages}
        return len(held - live)

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "hit_tokens": self.hit_tokens,
                "pages_held": self._pages_held,
                "tokens_held": self._pages_held * self.block,
                "entries": len(self._entries),
                "evictions": self.evictions}

"""Distributed checkpoint (reshard-on-load) + inference Predictor tests
(reference: SURVEY.md §5.4 checkpoint/resume, §3.6 AnalysisPredictor)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.parallel import create_hybrid_mesh, set_mesh


class TestDistributedCheckpoint:
    def test_roundtrip_dense(self, tmp_path):
        lin = paddle.nn.Linear(8, 4)
        sd = lin.state_dict()
        dckpt.save_state_dict(sd, str(tmp_path / "ckpt"))
        w_orig = lin.weight.numpy().copy()
        lin.weight.set_value(np.zeros_like(w_orig))
        dckpt.load_state_dict(lin.state_dict(), str(tmp_path / "ckpt"))
        np.testing.assert_allclose(lin.weight.numpy(), w_orig)

    def test_reshard_on_load(self, tmp_path):
        """Save sharded over (dp=8), load into a model sharded over (mp=8):
        the reference's cross-strategy reshard path."""
        mesh = create_hybrid_mesh(dp=8)
        try:
            paddle.seed(77)
            m1 = paddle.nn.Linear(16, 8)
            d = dist.shard_tensor(
                m1.weight,
                dist.ProcessMesh(np.arange(8), dim_names=["z"]),
                [dist.Shard(0)])
            m1.weight._inplace_set(d._value)
            w_orig = m1.weight.numpy().copy()
            dckpt.save_state_dict(m1.state_dict(), str(tmp_path / "c2"))

            paddle.seed(78)
            m2 = paddle.nn.Linear(16, 8)
            d2 = dist.shard_tensor(
                m2.weight,
                dist.ProcessMesh(np.arange(8), dim_names=["z"]),
                [dist.Shard(1)])  # DIFFERENT placement than saved
            m2.weight._inplace_set(d2._value)
            dckpt.load_state_dict(m2.state_dict(), str(tmp_path / "c2"))
            np.testing.assert_allclose(m2.weight.numpy(), w_orig)
            # target sharding preserved (restored INTO Shard(1) layout)
            pls = dist.auto_parallel.to_placements(
                m2.weight._value,
                dist.ProcessMesh(np.arange(8), dim_names=["z"]))
            assert pls[0] == dist.Shard(1)
        finally:
            set_mesh(None)

    def test_nested_state_dict(self, tmp_path):
        opt_state = {"lr": np.float32(0.1),
                     "m": {"w": paddle.to_tensor(np.ones((4,), "float32"))}}
        dckpt.save_state_dict(opt_state, str(tmp_path / "c3"))
        target = {"lr": np.float32(0.0),
                  "m": {"w": paddle.to_tensor(np.zeros((4,), "float32"))}}
        dckpt.load_state_dict(target, str(tmp_path / "c3"))
        np.testing.assert_allclose(target["m"]["w"].numpy(), np.ones(4))


class TestInference:
    def test_predictor_end_to_end(self, tmp_path):
        from paddle_tpu import inference as paddle_infer
        from paddle_tpu.static import InputSpec

        paddle.seed(5)
        net = paddle.nn.Sequential(
            paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
        prefix = str(tmp_path / "model")
        paddle.jit.save(net, prefix,
                        input_spec=[InputSpec([2, 8], "float32")])

        config = paddle_infer.Config(prefix)
        config.enable_use_gpu(100, 0)
        predictor = paddle_infer.create_predictor(config)
        names = predictor.get_input_names()
        assert len(names) == 1
        x = np.random.randn(2, 8).astype("float32")
        predictor.get_input_handle(names[0]).copy_from_cpu(x)
        assert predictor.run()
        out = predictor.get_output_handle(
            predictor.get_output_names()[0]).copy_to_cpu()
        ref = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-6)

    def test_config_api_surface(self):
        from paddle_tpu import inference as paddle_infer

        c = paddle_infer.Config("some/prefix")
        c.switch_ir_optim(True)
        c.enable_memory_optim()
        c.enable_tensorrt_engine(max_batch_size=4)
        c.disable_gpu()
        assert not c.use_gpu()
        assert "some/prefix" in c.summary()


def test_ckpt_list_entries_roundtrip(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import checkpoint as dckpt
    import numpy as np

    sd = {
        "moments": [paddle.to_tensor(np.ones(3, "float32")),
                    paddle.to_tensor(np.full(2, 2.0, "float32"))],
        "step": 7,
    }
    dckpt.save_state_dict(sd, str(tmp_path / "ck_list"))
    tgt = {
        "moments": [paddle.to_tensor(np.zeros(3, "float32")),
                    paddle.to_tensor(np.zeros(2, "float32"))],
        "step": 0,
    }
    dckpt.load_state_dict(tgt, str(tmp_path / "ck_list"))
    np.testing.assert_allclose(tgt["moments"][0].numpy(), 1.0)
    np.testing.assert_allclose(tgt["moments"][1].numpy(), 2.0)
    assert tgt["step"] == 7


def test_ckpt_unpicklable_entry_raises(tmp_path):
    from paddle_tpu.distributed import checkpoint as dckpt
    import pytest

    with pytest.raises(TypeError, match="not checkpointable"):
        dckpt.save_state_dict({"bad": object()}, str(tmp_path / "ck_bad"))


def test_predictor_output_handle_before_run(tmp_path):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference

    net = paddle.nn.Linear(4, 2)
    path = str(tmp_path / "pred_model")
    paddle.jit.save(net, path, input_spec=[paddle.static.InputSpec([3, 4])])
    config = inference.Config(path)
    predictor = inference.create_predictor(config)
    names = predictor.get_output_names()
    handle = predictor.get_output_handle(names[0])  # before any run()
    inp = predictor.get_input_handle(predictor.get_input_names()[0])
    inp.copy_from_cpu(np.ones((3, 4), "float32"))
    predictor.run()
    out = handle.copy_to_cpu()
    assert out.shape == (3, 2)


def test_int8_ptq_model_through_predictor(tmp_path):
    """PTQ-converted (real int8 matmul) model exports via jit.save and
    serves through the Predictor — the reference's slim/int8 deploy flow."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import inference, nn, quantization as Q
    from paddle_tpu.static import InputSpec

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.act = nn.ReLU()
            self.fc2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    net = Net()
    net.eval()
    cfg = Q.QuantConfig(activation=Q.quanter(Q.MovingAverageAbsmaxObserver))
    ptq = Q.PTQ(cfg)
    net = ptq.quantize(net)
    rng = np.random.RandomState(0)
    calib = paddle.to_tensor(rng.randn(32, 8).astype(np.float32))
    ref = net(calib).numpy()  # observers collect
    net = ptq.convert(net)
    int8_out = net(calib).numpy()

    prefix = str(tmp_path / "int8_model")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([32, 8], "float32")])

    config = inference.Config(prefix)
    config.precision = inference.PrecisionType.Int8
    pred = inference.create_predictor(config)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(calib.numpy())
    assert pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, int8_out, rtol=1e-4, atol=1e-5)
    # and the int8 path stays close to the fp32 reference
    rel = np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9)
    assert rel < 0.05, rel


def test_jit_save_dynamic_batch_predictor(tmp_path):
    """InputSpec dims of None export as jax.export symbolic dimensions —
    one saved program serves every batch size (the reference's dynamic
    first-dim .pdmodel convention)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    from paddle_tpu.static import InputSpec

    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    net.eval()
    prefix = str(tmp_path / "dyn")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([None, 16], "float32")])
    cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
    pred = inference.create_predictor(cfg)
    for bs in (8, 3):
        h = pred.get_input_handle(pred.get_input_names()[0])
        x = np.random.RandomState(bs).rand(bs, 16).astype(np.float32)
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        ref = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-4)


def test_jit_save_multi_input_dynamic_and_string_dims(tmp_path):
    """None dims at the same axis position unify across input specs
    (a+b broadcasting survives export); string dims name independent
    symbolic extents; jit.enable_to_static(False) runs eagerly."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.static import InputSpec

    class Add(nn.Layer):
        def forward(self, a, b):
            return a + b

    prefix = str(tmp_path / "add")
    paddle.jit.save(Add(), prefix,
                    input_spec=[InputSpec([None, 16]), InputSpec([None, 16])])
    m = paddle.jit.load(prefix)
    x = np.random.rand(5, 16).astype(np.float32)
    np.testing.assert_allclose(
        m(paddle.to_tensor(x), paddle.to_tensor(x)).numpy(), 2 * x)

    class Cat(nn.Layer):
        def forward(self, a, b):
            return paddle.concat([a, b], axis=0)

    prefix2 = str(tmp_path / "cat")
    paddle.jit.save(Cat(), prefix2,
                    input_spec=[InputSpec(["qlen", 8]), InputSpec(["klen", 8])])
    m2 = paddle.jit.load(prefix2)
    out = m2(paddle.to_tensor(np.ones((3, 8), np.float32)),
             paddle.to_tensor(np.ones((5, 8), np.float32)))
    assert out.shape == [8, 8]

    @paddle.jit.to_static
    def f(x):
        return x * 2

    t = paddle.to_tensor(np.ones(3, np.float32))
    f(t)
    paddle.jit.enable_to_static(False)
    try:
        np.testing.assert_allclose(f(t).numpy(), 2.0)
    finally:
        paddle.jit.enable_to_static(True)

"""Static HBM liveness auditor (r24, ISSUE 19): peak live bytes per
program, from the optimized HLO alone.

The r9 passes pin syncs/compiles/relayout/donation; r18 meters pool
occupancy at runtime — but nothing statically bounded a program's
**peak live HBM**, the number that actually OOMs a chip. This pass
computes it the way a buffer assigner would, as a deterministic ledger
over the compiled text (``jitted.lower(...).compile().as_text()`` —
the module is ``is_scheduled=true``, so text order IS the instruction
schedule):

* **buffer sizes** come from result shapes (``hlo._shape_bytes``);
* **intervals** are def→last-use over the schedule; entry parameters
  live the whole program (the caller owns their buffers);
* **aliasing is free**: ``tuple`` / ``get-tuple-element`` / ``bitcast``
  / ``optimization-barrier`` / ``copy-done`` produce views, and a
  ``while`` donates its carry through iterations (result aliases the
  operand) — alias results cost 0 bytes and extend their operands'
  lifetimes instead;
* **donation counts once**: ``input_output_alias`` entries zero the
  root operand at the aliased output index — the donated carry (the
  paged pool, optimizer flat state) is billed as its parameter only,
  never as parameter + fresh output;
* **fusion interiors collapse** to the fusion instruction's output
  (interior temporaries live in registers/scratch, not HBM); while
  bodies / conditional branches / calls recurse — their internal peak
  (parameters excluded: they alias caller operands) lands at the call
  site's schedule point;
* **sharded dims divide per-device**: a post-SPMD module
  (``num_partitions=N`` > 1) already carries per-device shapes; for
  un-partitioned text audited against a mesh, per-instruction GSPMD
  ``sharding={devices=[...]}`` annotations divide that buffer, and an
  explicit ``devices=`` divisor covers fully-replicated views.

``peak_live`` returns the per-program ``peak_bytes``, the peak-point
live set (top-N buffers with op/shape/op_name attribution) and a
timeline; ``budgets.Budget.peak_bytes_max`` pins it per canonical
program (cpu-scoped like the other byte ledgers) and ``python -m
paddle_tpu.analysis --gate`` enforces it.

``chip_fit`` joins the liveness result with the §3c weight arithmetic
and the §3f page-pool arithmetic into the **static HBM envelope**
(weights + KV pool + peak transient) — the will-this-replica-fit
surface ``capacity_plan`` embeds and ROADMAP item 3's autoscaler
consumes, cross-validated within ±10% of the r18 PoolMonitor
high-water on a recorded serve (SCALING §3s).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import hlo as hlo_passes

__all__ = ["BufferInterval", "MemoryReport", "peak_live", "hot_transients",
           "page_bytes_for", "pool_bytes_for", "transient_estimate",
           "chip_fit", "family_envelopes", "V5E_HBM_BYTES"]

# per-chip HBM capacity the envelope is priced against by default (the
# same v5e datasheet the §3c roofline constants come from: 16 GiB/chip)
V5E_HBM_BYTES = 16 * (1 << 30)


# Ops whose result aliases an existing buffer — zero new bytes; the
# operands' lifetimes extend to the alias's last use instead. ``while``
# is here because XLA threads the carry in place (loop inputs donate
# into outputs); elements the body forwards untouched come back as
# get-tuple-elements and so never double-bill either.
_ALIAS_OPS = frozenset((
    "tuple", "get-tuple-element", "bitcast", "optimization-barrier",
    "copy-done", "while",
))

# Instruction attrs that name computations whose buffers DO occupy HBM
# while the instruction runs (recursed); fusion `calls=` interiors and
# reduce/scatter/sort `to_apply=` scalar combinators are excluded.
_CALLEE_ATTRS = {
    "while": (re.compile(r"body=%?([\w.\-]+)"),
              re.compile(r"condition=%?([\w.\-]+)")),
    "conditional": (re.compile(r"branch_computations=\{([^}]*)\}"),
                    re.compile(r"true_computation=%?([\w.\-]+)"),
                    re.compile(r"false_computation=%?([\w.\-]+)")),
    "call": (re.compile(r"to_apply=%?([\w.\-]+)"),),
}

_DEF_RE = re.compile(
    r"^(ROOT\s+)?%([\w.\-]+)\s*=\s*(.*?)\s([\w\-]+)\(")
_USE_RE = re.compile(r"%([\w.\-]+)")
_META_RE = re.compile(r",?\s*metadata=\{[^}]*\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")
_SHARDING_DEVICES_RE = re.compile(r"sharding=\{[^}]*devices=\[([\d,]+)\]")
_ALIAS_PAIR_RE = re.compile(r"\{\s*(\d*)[\d,\s]*\}:\s*\((\d+)")


@dataclass
class BufferInterval:
    name: str
    op: str
    shape: str
    bytes: int
    start: int
    end: int
    computation: str
    donated: bool = False      # bytes zeroed: aliases a donated param
    param: bool = False        # entry parameter (lives whole program)
    metadata: str = ""         # op_name= source attribution


@dataclass
class MemoryReport:
    program: str
    peak_bytes: int
    peak_index: int
    peak_instruction: str
    param_bytes: int
    donated_param_bytes: int
    transient_bytes: int       # peak_bytes - param_bytes (the working set)
    live_at_peak: List[BufferInterval]
    callee_at_peak: int        # sub-computation contribution at the peak
    timeline: List[Tuple[int, int]]
    num_partitions: int
    devices: int
    schedule_len: int
    intervals: List[BufferInterval] = field(default_factory=list)

    def format(self) -> str:
        mib = 1 / (1 << 20)
        lines = [f"== memory: {self.program} ==",
                 f"  peak {self.peak_bytes * mib:.2f} MiB at "
                 f"#{self.peak_index}/{self.schedule_len} "
                 f"{self.peak_instruction} "
                 f"(params {self.param_bytes * mib:.2f} MiB + transient "
                 f"{self.transient_bytes * mib:.2f} MiB)"]
        for b in self.live_at_peak:
            tag = "param" if b.param else ("donated" if b.donated
                                           else "live")
            lines.append(f"  {tag:>7} {b.bytes * mib:8.3f} MiB {b.name} "
                         f"{b.op} {b.shape}"
                         + (f" [{b.metadata}]" if b.metadata else ""))
        return "\n".join(lines)


def _aliased_output_pairs(hlo_text: str) -> List[Tuple[Optional[int], int]]:
    """[(output tuple index or None for a non-tuple root, param number)]
    from the module's ``input_output_alias`` map."""
    body = hlo_passes._extract_braced(hlo_text, "input_output_alias=")
    if body is None:
        return []
    out = []
    for oi, pnum in _ALIAS_PAIR_RE.findall(body):
        out.append((int(oi) if oi else None, int(pnum)))
    return out


def _sharding_divisor(line: str) -> int:
    """Tile-device product of a per-instruction GSPMD sharding
    annotation (pre-partition modules only). ``last_tile_dim_replicate``
    marks the trailing tile dim as replication, not a shard."""
    m = _SHARDING_DEVICES_RE.search(line)
    if m is None:
        return 1
    dims = [int(d) for d in m.group(1).split(",") if d]
    n = 1
    for d in dims:
        n *= d
    if "last_tile_dim_replicate" in line and dims:
        n //= max(1, dims[-1])
    return max(1, n)


def _parse_instructions(lines, comp_name, entry, divide, shard_aware):
    """One computation's schedule: [(name, op, shape, bytes, raw_line)]
    in text order (= XLA schedule order: the module is is_scheduled)."""
    out = []
    for raw in lines:
        m = _DEF_RE.match(raw)
        if m is None:
            continue
        is_root, name, shape_text, op = (bool(m.group(1)), m.group(2),
                                         m.group(3), m.group(4))
        if op in _ALIAS_OPS:
            nbytes = 0
        elif op == "parameter" and not entry:
            nbytes = 0          # aliases the caller's operand buffer
        else:
            nbytes = hlo_passes._shape_bytes(shape_text)
            div = divide * (_sharding_divisor(raw) if shard_aware else 1)
            if div > 1:
                nbytes = -(-nbytes // div)
        out.append((name, op, shape_text, nbytes, raw, is_root))
    return out


def _comp_peak(comp_name: str, comps: Dict[str, list], fused: set,
               divide: int, shard_aware: bool, memo: Dict[str, int],
               stack: set) -> int:
    """Internal peak of a non-entry computation (params billed 0: they
    alias caller operands, already live at the call site)."""
    if comp_name in memo:
        return memo[comp_name]
    if comp_name not in comps or comp_name in stack:
        return 0
    stack = stack | {comp_name}
    instrs = _parse_instructions(comps[comp_name], comp_name, False,
                                 divide, shard_aware)
    peak, _idx, _live, _callee = _liveness(instrs, comp_name, comps,
                                           fused, divide, shard_aware,
                                           memo, stack, entry=False)
    memo[comp_name] = peak
    return peak


def _callees(op: str, raw: str, fused: set) -> List[str]:
    pats = _CALLEE_ATTRS.get(op)
    if not pats:
        return []
    names: List[str] = []
    for pat in pats:
        m = pat.search(raw)
        if not m:
            continue
        for tok in m.group(1).split(","):
            tok = tok.strip().lstrip("%")
            if tok and tok not in fused:
                names.append(tok)
    return names


def _liveness(instrs, comp_name, comps, fused, divide, shard_aware,
              memo, stack, entry, alias_pairs=()):
    """Sweep one computation's schedule; returns (peak, peak_idx,
    intervals, callee_peak_at_idx)."""
    n = len(instrs)
    if n == 0:
        return 0, 0, [], {}
    index = {name: i for i, (name, *_r) in enumerate(instrs)}
    last_use = {name: i for name, *_r in instrs
                for i in (index[name],)}
    # last textual use of each value (metadata stripped so quoted
    # op_name paths can't fake a reference; % prefix required)
    for i, (_name, _op, _shape, _b, raw, _root) in enumerate(instrs):
        rhs = _META_RE.sub("", raw.split("=", 1)[1] if "=" in raw else raw)
        for u in _USE_RE.findall(rhs):
            if u in index and index[u] < i:
                last_use[u] = max(last_use[u], i)
    # alias results extend their operands' lifetimes (reverse order
    # resolves chains: gte(while(tuple(x))) pins x to the gte's end)
    for i in range(n - 1, -1, -1):
        name, op, _shape, _b, raw, _root = instrs[i]
        if op not in _ALIAS_OPS:
            continue
        rhs = _META_RE.sub("", raw.split("=", 1)[1])
        for u in set(_USE_RE.findall(rhs)):
            if u in index and index[u] < i:
                last_use[u] = max(last_use[u], last_use[name])

    root_i = next((i for i in range(n - 1, -1, -1) if instrs[i][5]), n - 1)
    root_name, root_op = instrs[root_i][0], instrs[root_i][1]
    last_use[root_name] = n - 1

    # donated outputs: the root operand at an aliased output index
    # reuses the parameter's buffer — bill it 0 (counted once, as the
    # parameter). Applies to the entry computation only.
    donated_ops: set = set()
    if entry and alias_pairs:
        rhs = _META_RE.sub("", instrs[root_i][4].split("=", 1)[1])
        root_operands = [u for u in _USE_RE.findall(rhs) if u in index]
        for out_idx, _pnum in alias_pairs:
            if out_idx is None and root_op != "tuple":
                donated_ops.add(root_name)
            elif root_op == "tuple" and out_idx is not None \
                    and out_idx < len(root_operands):
                donated_ops.add(root_operands[out_idx])

    intervals: List[BufferInterval] = []
    delta = [0] * (n + 1)
    meta = {}
    for i, (name, op, shape, nbytes, raw, _root) in enumerate(instrs):
        is_param = entry and op == "parameter"
        donated = name in donated_ops and not is_param
        billed = 0 if donated else nbytes
        start = 0 if is_param else i
        end = (n - 1) if is_param else max(i, last_use.get(name, i))
        m = _OPNAME_RE.search(raw)
        meta[name] = m.group(1) if m else ""
        if billed or is_param or donated:
            intervals.append(BufferInterval(
                name=name, op=op, shape=shape, bytes=billed, start=start,
                end=end, computation=comp_name, donated=donated,
                param=is_param, metadata=meta[name]))
        delta[start] += billed
        delta[end + 1] -= billed

    callee_peak = {}
    for i, (_name, op, _shape, _b, raw, _root) in enumerate(instrs):
        names = _callees(op, raw, fused)
        if names:
            callee_peak[i] = max(
                _comp_peak(c, comps, fused, divide, shard_aware, memo,
                           stack) for c in names)

    peak = peak_idx = 0
    live = 0
    for i in range(n):
        live += delta[i]
        total = live + callee_peak.get(i, 0)
        if total > peak:
            peak, peak_idx = total, i
    return peak, peak_idx, intervals, callee_peak


def peak_live(hlo_text: str, *, program: str = "program",
              devices: int = 1, top_n: int = 8,
              timeline_points: int = 128) -> MemoryReport:
    """Liveness sweep over an optimized HLO module's entry schedule.

    ``devices`` divides EVERY buffer — the per-device view of a
    replicated (un-partitioned) module lowered for a ``devices``-wide
    mesh. A post-SPMD module (``num_partitions`` > 1 in the header)
    already carries per-device shapes, so leave ``devices=1`` there;
    per-instruction ``sharding=`` annotations additionally divide
    their own buffer in un-partitioned text.
    """
    header = hlo_text.split("\n", 1)[0]
    m = _NUM_PARTITIONS_RE.search(header)
    num_partitions = int(m.group(1)) if m else 1
    shard_aware = num_partitions <= 1
    comps = {}
    entry_name, entry_lines = None, []
    for name, is_entry, lines in hlo_passes._computations(hlo_text):
        comps[name] = lines
        if is_entry:
            entry_name, entry_lines = name, lines
    fused = hlo_passes._fusion_computations(hlo_text)
    fused |= {c for c in comps if "fused_computation" in c}
    alias_pairs = _aliased_output_pairs(hlo_text)
    instrs = _parse_instructions(entry_lines, entry_name or "entry",
                                 True, devices, shard_aware)
    memo: Dict[str, int] = {}
    peak, peak_idx, intervals, callee_peak = _liveness(
        instrs, entry_name or "entry", comps, fused, devices,
        shard_aware, memo, {entry_name or "entry"}, entry=True,
        alias_pairs=alias_pairs)

    param_bytes = sum(b.bytes for b in intervals if b.param)
    donated_param_bytes = sum(
        p.bytes for p in hlo_passes.entry_parameters(hlo_text)
        if p.aliased)
    if devices > 1:
        donated_param_bytes = -(-donated_param_bytes // devices)

    live_at_peak = sorted(
        (b for b in intervals if b.start <= peak_idx <= b.end
         and (b.bytes or b.donated)),
        key=lambda b: -b.bytes)[:top_n]
    peak_instr = instrs[peak_idx][0] if instrs else ""

    # decimated live-bytes timeline (callee contributions included)
    n = len(instrs)
    stride = max(1, n // max(1, timeline_points))
    delta = [0] * (n + 1)
    for b in intervals:
        delta[b.start] += b.bytes
        delta[b.end + 1] -= b.bytes
    timeline, live = [], 0
    for i in range(n):
        live += delta[i]
        if i % stride == 0 or i == peak_idx:
            timeline.append((i, live + callee_peak.get(i, 0)))

    return MemoryReport(
        program=program, peak_bytes=peak, peak_index=peak_idx,
        peak_instruction=peak_instr, param_bytes=param_bytes,
        donated_param_bytes=donated_param_bytes,
        transient_bytes=max(0, peak - param_bytes),
        live_at_peak=live_at_peak,
        callee_at_peak=callee_peak.get(peak_idx, 0),
        timeline=timeline, num_partitions=num_partitions,
        devices=devices, schedule_len=n, intervals=intervals)


def hot_transients(report: MemoryReport, *, frac_bytes: float = 0.33,
                   frac_span: float = 0.6) -> List[BufferInterval]:
    """Non-parameter buffers that dominate the peak AND stay live
    across most of the schedule — the logits_all-across-steps class: a
    per-step value accumulated whole instead of reduced. These are the
    liveness blowups a peak-budget regression usually decomposes into.
    """
    n = max(1, report.schedule_len)
    return [b for b in report.intervals
            if not b.param and not b.donated
            and b.bytes >= frac_bytes * max(1, report.peak_bytes)
            and (b.end - b.start + 1) >= frac_span * n]


# ---------------------------------------------------------------------------
# The static HBM envelope: weights + KV pool + peak transient (§3s)
# ---------------------------------------------------------------------------


def page_bytes_for(cfg, page_size: int, quant: Optional[str] = None) -> int:
    """Bytes one pool page occupies across all layers: K + V planes
    [L, page_size, Hkv, D] (+ the fp32 ``ks``/``vs`` scale planes under
    per-page quantization) — the §3f page arithmetic, byte-priced."""
    if quant is not None:
        from ..quantization.serving import quant_dtype
        import jax.numpy as jnp

        itemsize = jnp.dtype(quant_dtype(quant)).itemsize
    else:
        import jax.numpy as jnp

        itemsize = jnp.dtype(cfg.dtype).itemsize
    kv = 2 * cfg.num_layers * page_size * cfg.num_kv_heads * cfg.head_dim \
        * itemsize
    scales = (2 * cfg.num_layers * page_size * 4) if quant else 0
    return kv + scales


def pool_bytes_for(cfg, num_pages: int, page_size: int,
                   quant: Optional[str] = None) -> int:
    """Provisioned pool bytes (``llama.init_paged_pool`` arithmetic):
    every page is allocated up front, including the trash page."""
    return num_pages * page_bytes_for(cfg, page_size, quant)


def transient_estimate(cfg, *, n_pad: int, s_max: int,
                       tokens_per_tick: int = 1) -> int:
    """Analytic peak-transient model for one serving tick/admit wave:
    the fp32 logits block (× tokens_per_tick — a verify tick or a
    ``logits_all`` program holds one per emitted position) plus a
    working set of hidden-width activations over the admit window.
    Validated against the measured liveness transient of the canonical
    gate programs (tests/test_memory_analysis.py) — an ESTIMATE for
    sizing real replicas, not a budget; budgets pin the measured pass.
    """
    import jax.numpy as jnp

    itemsize = jnp.dtype(cfg.dtype).itemsize
    logits = n_pad * tokens_per_tick * cfg.vocab_size * 4
    hidden = 6 * n_pad * s_max * cfg.hidden_size * itemsize
    scores = n_pad * cfg.num_heads * s_max * s_max * itemsize
    return int(logits + hidden + scores)


def chip_fit(cfg=None, params=None, *, pool=None, page_size=None,
             num_pages=None, quant=None, mesh_devices: int = 1,
             hbm_bytes: int, weights_bytes: Optional[int] = None,
             transient_bytes: Optional[int] = None,
             n_pad: Optional[int] = None, s_max: Optional[int] = None,
             live_pages: Optional[int] = None,
             trace_stats: Optional[dict] = None,
             program_family: str = "pseg") -> dict:
    """Static will-this-replica-fit: the §3s HBM envelope.

    ``envelope_bytes = weights + provisioned KV pool + peak transient``
    — all three per-device (weights and the pool shard over
    ``mesh_devices`` on the kv-head/output dims). ``pool`` may be a
    live ``PagedKVCache`` (its planes are summed exactly) or pool
    geometry (``page_size``/``num_pages``). The live-KV prediction
    (``kv_live_bytes``) prices the §3f span arithmetic at high-water —
    the term cross-validated ±10% against the r18 PoolMonitor on a
    recorded serve.
    """
    if weights_bytes is None:
        import jax

        weights_bytes = sum(
            int(x.size) * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(params))
    weights_bytes = -(-int(weights_bytes) // max(1, mesh_devices))

    if pool is not None:
        pool_b = sum(int(v.size) * v.dtype.itemsize
                     for v in pool.pool.values())
        pool_b += int(pool.page_table.size) * pool.page_table.dtype.itemsize
        page_size = pool.page_size
        num_pages = pool.num_pages
        page_b = page_bytes_for(cfg, page_size, quant)
    else:
        page_b = page_bytes_for(cfg, page_size, quant)
        pool_b = num_pages * page_b
    pool_b = -(-pool_b // max(1, mesh_devices))
    page_b = -(-page_b // max(1, mesh_devices))

    if transient_bytes is None:
        transient_bytes = transient_estimate(
            cfg, n_pad=n_pad if n_pad is not None else 4,
            s_max=s_max if s_max is not None else 4 * (page_size or 16))
    transient_bytes = int(transient_bytes)

    if live_pages is None and trace_stats is not None:
        S = float(trace_stats["mean_prompt_tokens"])
        G = float(trace_stats["mean_new_tokens"])
        span = max(1, math.ceil((S + G - 1) / page_size))
        conc = float(trace_stats.get("concurrency",
                                     trace_stats.get("slots", 1)))
        live_pages = int(math.ceil(conc * span))
    kv_live_bytes = (live_pages * page_b if live_pages is not None
                     else None)

    envelope = weights_bytes + pool_b + transient_bytes
    headroom = hbm_bytes - envelope
    return {
        "arithmetic": "SCALING §3s static HBM envelope: weights + "
                      "provisioned pool + peak transient",
        "program_family": program_family,
        "mesh_devices": int(mesh_devices),
        "hbm_bytes": int(hbm_bytes),
        "weights_bytes": int(weights_bytes),
        "pool_bytes": int(pool_b),
        "page_bytes": int(page_b),
        "num_pages": int(num_pages) if num_pages else None,
        "transient_bytes": transient_bytes,
        "envelope_bytes": int(envelope),
        "fits": bool(envelope <= hbm_bytes),
        "headroom_bytes": int(headroom),
        "headroom_pages": int(headroom // page_b) if headroom > 0 else 0,
        "utilization": round(envelope / hbm_bytes, 4),
        "predicted_high_water_pages": live_pages,
        "kv_live_bytes": (int(kv_live_bytes)
                          if kv_live_bytes is not None else None),
    }


def family_envelopes(engine, envelope, *, hbm_bytes: Optional[int] = None,
                     mesh_devices: int = 1) -> Dict[str, dict]:
    """Per-family static envelopes over the engine's declared program
    space: for every family the workload envelope reaches, price its
    WIDEST enumerated key (max admit width × window) through the §3s
    arithmetic. The autoscaler's per-family chip-fit table — weights
    and pool are shared; only the transient differs per family."""
    from ..inference.program_space import PROGRAM_SPACE

    by_fam = PROGRAM_SPACE.enumerate_by_family(engine, envelope)
    cfg = engine.cfg
    pager = getattr(engine, "pager", None)
    out: Dict[str, dict] = {}
    for fam_name, keys in sorted(by_fam.items()):
        if not keys:
            continue
        # enumerate_by_family returns a set of key tuples; order it so
        # the widest-key tie-break is deterministic across runs
        keys = sorted(keys, key=repr)
        fam = PROGRAM_SPACE.family(fam_name)
        widest_pad, widest_span, widest_tok = 1, 1, 1
        widest_key = keys[0]
        for key in keys:
            kw = dict(zip(fam.axes, key[1:]))
            n_pad = int(kw.get("n_pad", getattr(engine, "slots", 1)) or 1)
            span = max(int(kw.get(a, 0) or 0)
                       for a in ("s_max", "C", "chunk", "width")) or 1
            tok = int(kw.get("K", 0) or 0) + 1
            if n_pad * span * tok >= widest_pad * widest_span * widest_tok:
                widest_pad, widest_span, widest_tok = n_pad, span, tok
                widest_key = key
        transient = transient_estimate(cfg, n_pad=widest_pad,
                                       s_max=widest_span,
                                       tokens_per_tick=widest_tok)
        entry = {"keys": len(keys), "widest_key": widest_key,
                 "transient_bytes": transient,
                 "budget_program": fam.budget_program}
        if hbm_bytes is not None and pager is not None:
            entry["fit"] = chip_fit(
                cfg, engine.params, page_size=pager.page_size,
                num_pages=pager.num_pages,
                quant=getattr(engine, "quant", None),
                mesh_devices=mesh_devices, hbm_bytes=hbm_bytes,
                transient_bytes=transient, program_family=fam_name)
        out[fam_name] = entry
    return out

"""BASELINE config 4: LLaMA hybrid-parallel step (TP + ZeRO-3) — dry run.

Multi-chip hardware isn't present in this environment; this script compiles
and executes the FULL hybrid train step on the virtual 8-device CPU mesh
(the same program the driver validates via __graft_entry__.dryrun_multichip)
and reports compile+step wall time. Run with:

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/llama_multichip_dryrun.py
"""

import json
import os
import sys

# runnable standalone: the repo root (one level up) holds paddle_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time


def main():
    import __graft_entry__ as g

    t0 = time.perf_counter()
    # call the in-process impl: this script's documented env already provides
    # the 8-device CPU platform, and timing must exclude subprocess startup
    g._dryrun_impl(8)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "llama_hybrid_dryrun_wall", "value": round(dt, 2),
        "unit": "seconds", "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    sys.exit(main())

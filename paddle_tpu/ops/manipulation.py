"""Shape / layout / indexing manipulation ops.

Reference: ``paddle/phi/kernels`` (reshape, transpose, concat, gather/scatter,
…) + ``python/paddle/tensor/manipulation.py`` (SURVEY.md §2.1). All lower to
XLA ops that are free (reshape/transpose fold into layouts) or fuse well.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..enforce import InvalidArgumentError
from .dispatch import run_op
from .registry import register_op

__all__ = [
    "reshape", "reshape_", "flatten", "unflatten", "transpose", "moveaxis",
    "swapaxes", "numel", "rank", "block_diag", "combinations",
    "cartesian_prod",
    "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "concat", "stack",
    "split", "chunk", "unbind", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "flip", "rot90", "roll", "gather", "gather_nd",
    "scatter", "scatter_nd_add", "index_select", "index_sample", "index_add",
    "index_put", "masked_select", "masked_fill", "where", "nonzero",
    "take_along_axis", "put_along_axis", "sort", "argsort", "topk", "unique",
    "unique_consecutive", "searchsorted", "bucketize", "pad", "repeat_interleave",
    "diagonal", "tensordot", "einsum", "unstack", "strided_slice", "crop",
    "tolist", "chunk", "dsplit", "hsplit", "vsplit", "as_real", "as_complex",
    "view", "view_as", "atleast_1d", "atleast_2d", "atleast_3d",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int,)):
        return (shape,)
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)


@register_op()
def reshape(x, shape, name=None):
    shp = _shape_arg(shape)
    return run_op("reshape", lambda a: jnp.reshape(a, shp), x)


def reshape_(x, shape, name=None):
    return x._inplace_set(jnp.reshape(x._value, _shape_arg(shape)))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    from ..core.dtype import convert_dtype

    return run_op("view_dtype", lambda a: a.view(convert_dtype(shape_or_dtype)), x)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


@register_op()
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis if start_axis >= 0 else start_axis + nd
        e = stop_axis if stop_axis >= 0 else stop_axis + nd
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1 :]
        return jnp.reshape(a, new_shape)

    return run_op("flatten", f, x)


def numel(x, name=None):
    """0-D integer tensor holding the element count (reference:
    ``paddle.numel``; int64 there — here the widest enabled int, since
    x64 is off by default under jax)."""
    n = x.size if isinstance(x, Tensor) else np.asarray(x).size
    return to_tensor(np.asarray(n, np.int64))


def rank(x, name=None):
    """0-D int32 tensor holding the number of dimensions (reference:
    ``paddle.rank``)."""
    nd = x.ndim if isinstance(x, Tensor) else jnp.asarray(x).ndim
    return to_tensor(jnp.asarray(int(nd), jnp.int32))


@register_op()
def unflatten(x, axis, shape, name=None):
    """Expand ``axis`` into ``shape`` (reference: ``paddle.unflatten``,
    ``python/paddle/tensor/manipulation.py``). One entry of ``shape`` may
    be -1 (inferred)."""
    shape = tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                  for s in shape)

    def f(a):
        ax = axis if axis >= 0 else axis + a.ndim
        return jnp.reshape(a, a.shape[:ax] + shape + a.shape[ax + 1:])

    return run_op("unflatten", f, x)


@register_op()
def transpose(x, perm=None, name=None):
    if perm is None:
        return run_op("transpose", lambda a: jnp.transpose(a), x)
    p = tuple(perm)
    return run_op("transpose", lambda a: jnp.transpose(a, p), x)


@register_op()
def moveaxis(x, source, destination, name=None):
    return run_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


@register_op()
def swapaxes(x, axis0, axis1, name=None):
    return run_op("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), x)


@register_op()
def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return run_op("squeeze", f, x)


def squeeze_(x, axis=None, name=None):
    return x._inplace_set(squeeze(x.detach(), axis)._value)


@register_op()
def unsqueeze(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(
        int(a.item()) if isinstance(a, Tensor) else int(a) for a in axis
    )
    return run_op("unsqueeze", lambda a: jnp.expand_dims(a, axes), x)


def unsqueeze_(x, axis, name=None):
    return x._inplace_set(unsqueeze(x.detach(), axis)._value)


@register_op()
def concat(x: Sequence[Tensor], axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    tensors = list(x)
    return run_op("concat", lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors)


@register_op()
def stack(x: Sequence[Tensor], axis=0, name=None):
    tensors = list(x)
    return run_op("stack", lambda *arrs: jnp.stack(arrs, axis=axis), *tensors)


@register_op()
def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise InvalidArgumentError(
                f"split: dimension {axis} (size {dim}) is not divisible by "
                f"num_or_sections={num_or_sections}"
            )
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [s if s != -1 else None for s in num_or_sections]
        import builtins

        known = builtins.sum(s for s in sizes if s is not None)
        sizes = [s if s is not None else dim - known for s in sizes]
    offsets = [0]
    for s in sizes:
        offsets.append(offsets[-1] + s)
    idx = [(offsets[i], offsets[i + 1]) for i in range(len(sizes))]

    def f(a):
        return tuple(jax.lax.slice_in_dim(a, lo, hi, axis=axis) for lo, hi in idx)

    return list(run_op("split", f, x))


@register_op()
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def dsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=2)


def hsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=0)


@register_op()
def unbind(x, axis=0, name=None):
    n = x.shape[axis]

    def f(a):
        return tuple(jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis))

    return list(run_op("unbind", f, x))


unstack = unbind


@register_op()
def tile(x, repeat_times, name=None):
    rt = _shape_arg(repeat_times)
    return run_op("tile", lambda a: jnp.tile(a, rt), x)


@register_op()
def expand(x, shape, name=None):
    shp = _shape_arg(shape)

    def f(a):
        target = tuple(
            a.shape[i - (len(shp) - a.ndim)] if s == -1 else s for i, s in enumerate(shp)
        )
        return jnp.broadcast_to(a, target)

    return run_op("expand", f, x)


@register_op()
def expand_as(x, y, name=None):
    return run_op("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


@register_op()
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    arrs = jnp.broadcast_arrays(*[t._value for t in inputs])
    shp = arrs[0].shape
    return [expand(t, shp) for t in inputs]


@register_op()
def flip(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return run_op("flip", lambda a: jnp.flip(a, axis=axes), x)


@register_op()
def rot90(x, k=1, axes=(0, 1), name=None):
    return run_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


@register_op()
def roll(x, shifts, axis=None, name=None):
    return run_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), x)


# -- gather / scatter --------------------------------------------------------

@register_op()
def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return run_op("gather", lambda a, i: jnp.take(a, i, axis=axis), x, index)


@register_op()
def gather_nd(x, index, name=None):
    def f(a, idx):
        k = idx.shape[-1]
        flat = tuple(idx[..., j] for j in range(k))
        return a[flat]

    return run_op("gather_nd", f, x, index)


@register_op()
def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].add(u)

    return run_op("scatter", f, x, index, updates)


@register_op()
def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, u):
        k = idx.shape[-1]
        flat = tuple(idx[..., j] for j in range(k))
        return a.at[flat].add(u)

    return run_op("scatter_nd_add", f, x, index, updates)


@register_op()
def index_select(x, index, axis=0, name=None):
    return run_op("index_select", lambda a, i: jnp.take(a, i, axis=axis), x, index)


@register_op()
def index_sample(x, index, name=None):
    return run_op(
        "index_sample", lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index
    )


@register_op()
def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[i].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return run_op("index_add", f, x, index, value)


@register_op()
def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)

    return run_op("index_put", f, x, value, *indices)


@register_op()
def masked_select(x, mask, name=None):
    # dynamic-shaped output: mask is resolved host-side (not jittable, like
    # the reference CPU path), but the gather itself goes through run_op so
    # gradients flow back into x.
    import numpy as np

    flat_idx = np.nonzero(np.asarray(mask._value).reshape(-1))[0]
    return run_op(
        "masked_select", lambda a: jnp.take(a.reshape(-1), flat_idx), x
    )


@register_op()
def masked_fill(x, mask, value, name=None):
    v = value._value if isinstance(value, Tensor) else value
    return run_op("masked_fill", lambda a, m: jnp.where(m, v, a), x, mask)


@register_op()
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    from .math import _coerce

    x = _coerce(x, y if isinstance(y, Tensor) else None)
    y = _coerce(y, x)
    return run_op("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


@register_op(differentiable=False)
def nonzero(x, as_tuple=False, name=None):
    idx = jnp.nonzero(x._value)  # host sync; dynamic shape like reference
    if as_tuple:
        return tuple(to_tensor(i) for i in idx)
    return to_tensor(jnp.stack(idx, axis=1))


@register_op()
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return run_op(
        "take_along_axis", lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices
    )


@register_op()
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if jnp.ndim(v) else jnp.full(i.shape, v, a.dtype)
        ii = jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij")
        ii[axis] = i
        if reduce == "add":
            return a.at[tuple(ii)].add(v)
        if reduce == "multiply":
            return a.at[tuple(ii)].multiply(v)
        return a.at[tuple(ii)].set(v)

    return run_op("put_along_axis", f, arr, indices, values)


# -- sort / search -----------------------------------------------------------

@register_op()
def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return run_op("sort", f, x)


@register_op(differentiable=False)
def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        i = jnp.argsort(a, axis=axis)
        i = jnp.flip(i, axis=axis) if descending else i
        return i

    return run_op("argsort", f, x)


@register_op()
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def f(a):
        moved = jnp.moveaxis(a, axis, -1)
        src = moved if largest else -moved
        v, i = jax.lax.top_k(src, k)
        if not largest:
            v = -v
        return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)

    return run_op("topk", f, x, n_diff_outputs=1)


@register_op(differentiable=False)
def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    res = jnp.unique(
        x._value, return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis,
    )
    if not (return_index or return_inverse or return_counts):
        return to_tensor(res)
    return tuple(to_tensor(r) for r in res)


@register_op(differentiable=False)
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    a = x.numpy()
    import numpy as np

    if axis is None:
        a = a.reshape(-1)
    keep = np.ones(a.shape[0], bool)
    keep[1:] = (a[1:] != a[:-1]).reshape(a.shape[0] - 1, -1).any(axis=-1) if a.ndim > 1 else a[1:] != a[:-1]
    out = to_tensor(a[keep])
    results = [out]
    if return_inverse:
        results.append(to_tensor(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, a.shape[0]))
        results.append(to_tensor(counts))
    return results[0] if len(results) == 1 else tuple(results)


@register_op(differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def f(s, v):
        r = jnp.searchsorted(s, v, side=side)
        return r.astype(jnp.int32)

    return run_op("searchsorted", f, sorted_sequence, values)


@register_op(differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


# -- padding / misc ----------------------------------------------------------

@register_op()
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad = _shape_arg(pad) if not isinstance(pad, (list, tuple)) else list(pad)

    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle/torch semantics: (low, high) pairs apply starting from
            # the LAST dim backwards — pad[0:2] pads dim -1, pad[2:4] dim -2…
            k = len(pad) // 2
            cfg = [(0, 0)] * nd
            for i in range(k):
                cfg[nd - 1 - i] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)

    return run_op("pad", f, x)


@register_op()
def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        # repeats is host-side data (determines output shape); close over it
        # so gradients still flow through x.
        import numpy as np

        r = np.asarray(repeats._value)
        return run_op("repeat_interleave", lambda a: jnp.repeat(a, r, axis=axis), x)
    return run_op("repeat_interleave", lambda a: jnp.repeat(a, repeats, axis=axis), x)


@register_op()
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return run_op("diagonal", lambda a: jnp.diagonal(a, offset, axis1, axis2), x)


@register_op()
def tensordot(x, y, axes=2, name=None):
    return run_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


@register_op()
def einsum(equation, *operands, name=None):
    ops = list(operands[0]) if len(operands) == 1 and isinstance(operands[0], (list, tuple)) else list(operands)
    return run_op("einsum", lambda *arrs: jnp.einsum(equation, *arrs), *ops)


@register_op()
def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice(s, e, st)
        return a[tuple(idx)]

    return run_op("strided_slice", f, x)


@register_op()
def crop(x, shape=None, offsets=None, name=None):
    shp = _shape_arg(shape)
    offs = _shape_arg(offsets) if offsets is not None else (0,) * len(shp)

    def f(a):
        idx = tuple(slice(o, o + (s if s != -1 else a.shape[i] - o)) for i, (o, s) in enumerate(zip(offs, shp)))
        return a[idx]

    return run_op("crop", f, x)


def as_real(x, name=None):
    return run_op("as_real", lambda a: jnp.stack([a.real, a.imag], axis=-1), x)


def as_complex(x, name=None):
    return run_op("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def atleast_1d(*xs, name=None):
    outs = [reshape(x, [-1]) if x.ndim == 0 else x for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs, name=None):
    outs = []
    for x in xs:
        while x.ndim < 2:
            x = unsqueeze(x, 0)
        outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs, name=None):
    outs = []
    for x in xs:
        while x.ndim < 3:
            x = unsqueeze(x, -1) if x.ndim >= 2 else unsqueeze(x, 0)
        outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def tolist(x):
    return x.tolist()



def block_diag(inputs, name=None):
    """Block-diagonal matrix from blocks of rank <= 2 (reference
    ``paddle.block_diag``; higher ranks are rejected there too).
    Differentiable — inputs go through run_op untouched."""
    from jax.scipy.linalg import block_diag as _bd

    tensors = [x if isinstance(x, Tensor) else to_tensor(jnp.asarray(x))
               for x in inputs]
    for i, t in enumerate(tensors):
        if t.ndim > 2:
            raise InvalidArgumentError(
                f"block_diag inputs must have ndim <= 2; input {i} has "
                f"shape {list(t.shape)}")

    def f(*vs):
        return _bd(*[v if v.ndim == 2 else v.reshape(1, -1) for v in vs])

    return run_op("block_diag", f, *tensors)


def combinations(x, r=2, with_replacement=False, name=None):
    """r-length combinations of a 1-D tensor's elements (reference
    ``paddle.combinations``). Index sets are host math (shapes must be
    static); the gather is traced."""
    import itertools

    if x.ndim != 1:
        raise InvalidArgumentError(
            f"combinations expects a 1-D tensor, got shape {list(x.shape)}")
    n = int(x.shape[0])
    it = (itertools.combinations_with_replacement if with_replacement
          else itertools.combinations)
    idx = np.asarray(list(it(range(n), r)), np.int32).reshape(-1, r)

    def f(a):
        return a[idx]

    return run_op("combinations", f, x)


def cartesian_prod(xs, name=None):
    """Cartesian product of 1-D tensors (reference
    ``paddle.cartesian_prod``): [prod(n_i), len(xs)]."""
    def f(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.ravel() for g in grids], axis=-1)

    return run_op("cartesian_prod", f, *xs)

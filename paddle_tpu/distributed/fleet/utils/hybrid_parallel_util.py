"""Hybrid-parallel gradient-sync helpers.

Reference counterpart: ``python/paddle/distributed/fleet/utils/
hybrid_parallel_util.py`` (SURVEY.md §2.2 "Fused comm utils"):
``fused_allreduce_gradients`` fuses parameter grads into flat buffers and
all-reduces them over the data-parallel group — the manual grad-sync call
used by models that disable the DataParallel reducer.

TPU-native: gradients of globally-sharded computations are already global
sums over dp (XLA inserts the reductions inside backward), so the fused
all-reduce is an identity; what remains useful — and is implemented — is the
layout half: re-placing grads onto the mesh so subsequent sharded optimizer
programs keep one device set.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ....parallel.mesh import get_mesh, named_sharding

__all__ = ["fused_allreduce_gradients", "sharding_reduce_gradients",
           "broadcast_input_data", "broadcast_mp_parameters",
           "broadcast_dp_parameters", "broadcast_sharding_parameters"]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Ensure grads live on the hybrid mesh (the reductions themselves are
    already inside XLA's backward)."""
    mesh = get_mesh()
    if mesh is None:
        return
    for p in parameter_list:
        g = getattr(p, "grad", None)
        if g is None:
            continue
        v = g._value
        if hasattr(v, "sharding") and len(v.sharding.device_set) == mesh.size:
            continue
        g._inplace_set(jax.device_put(v, named_sharding(P(*([None] * v.ndim)))))


def sharding_reduce_gradients(parameter_list, hcg=None):
    fused_allreduce_gradients(parameter_list, hcg)


def broadcast_input_data(hcg, *inputs, **kwargs):
    """Single-controller: every "rank" sees the same input batch already."""
    return inputs if not kwargs else (inputs, kwargs)


def broadcast_mp_parameters(model, hcg=None):
    """No-op under GSPMD: one logical parameter, not per-rank copies."""


def broadcast_dp_parameters(model, hcg=None):
    """No-op under GSPMD (see broadcast_mp_parameters)."""


def broadcast_sharding_parameters(model, hcg=None):
    """No-op under GSPMD (see broadcast_mp_parameters)."""

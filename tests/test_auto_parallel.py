"""auto_parallel API tests (reference: test/auto_parallel/ — placement
semantics, shard_tensor round trips, reshard transitions; SURVEY.md §4)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh2x4():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])


class TestPlacements:
    def test_types(self):
        assert dist.Shard(0).is_shard()
        assert dist.Shard(1).is_shard(1)
        assert not dist.Shard(1).is_shard(0)
        assert dist.Replicate().is_replicate()
        assert dist.Partial().is_partial()
        assert dist.Shard(0) == dist.Shard(0)
        assert dist.Shard(0) != dist.Shard(1)

    def test_process_mesh(self, mesh2x4):
        assert mesh2x4.shape == [2, 4]
        assert mesh2x4.dim_names == ["x", "y"]
        assert mesh2x4.process_ids == list(range(8))
        assert mesh2x4.get_dim_size("y") == 4


class TestShardTensor:
    def test_shard_and_read_back(self, mesh2x4):
        x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
        d = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Shard(1)])
        np.testing.assert_allclose(d.numpy(), x.numpy())
        pls = dist.auto_parallel.to_placements(d._value, mesh2x4)
        assert pls[0] == dist.Shard(0)
        assert pls[1] == dist.Shard(1)

    def test_replicate(self, mesh2x4):
        x = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        d = dist.shard_tensor(x, mesh2x4, [dist.Replicate(), dist.Replicate()])
        assert d._value.sharding.is_fully_replicated
        pls = dist.auto_parallel.to_placements(d._value, mesh2x4)
        assert all(p.is_replicate() for p in pls)

    def test_reshard_transition(self, mesh2x4):
        x = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
        d = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Replicate()])
        r = dist.reshard(d, mesh2x4, [dist.Replicate(), dist.Shard(1)])
        np.testing.assert_allclose(r.numpy(), x.numpy())
        pls = dist.auto_parallel.to_placements(r._value, mesh2x4)
        assert pls[0].is_replicate() and pls[1] == dist.Shard(1)

    def test_dtensor_from_fn(self, mesh2x4):
        d = dist.dtensor_from_fn(paddle.ones, mesh2x4,
                                 [dist.Shard(0), dist.Replicate()], [8, 4])
        np.testing.assert_allclose(d.numpy(), np.ones((8, 4)))

    def test_ops_on_dist_tensors(self, mesh2x4):
        """GSPMD propagates shardings through ordinary ops (the reference's
        per-op SPMD rules)."""
        a = dist.shard_tensor(
            paddle.to_tensor(np.random.randn(8, 16).astype("float32")),
            mesh2x4, [dist.Shard(0), dist.Replicate()])
        b = dist.shard_tensor(
            paddle.to_tensor(np.random.randn(16, 4).astype("float32")),
            mesh2x4, [dist.Replicate(), dist.Replicate()])
        c = paddle.matmul(a, b)
        np.testing.assert_allclose(
            c.numpy(), a.numpy() @ b.numpy(), rtol=2e-5, atol=1e-5)

    def test_backward_through_dist_tensor(self, mesh2x4):
        a = dist.shard_tensor(
            paddle.to_tensor(np.random.randn(8, 4).astype("float32"),
                             stop_gradient=False),
            mesh2x4, [dist.Shard(0), dist.Replicate()], stop_gradient=False)
        loss = paddle.mean(a * a)
        loss.backward()
        assert a.grad is not None
        np.testing.assert_allclose(a.grad.numpy(), 2 * a.numpy() / a.numpy().size,
                                   rtol=1e-5)


class TestShardLayer:
    def test_shard_layer_places_params(self, mesh2x4):
        layer = paddle.nn.Linear(16, 8)

        def shard_fn(name, sublayer, mesh):
            for pname, p in sublayer.named_parameters(include_sublayers=False):
                if p.ndim == 2:
                    d = dist.shard_tensor(p, mesh,
                                          [dist.Replicate(), dist.Shard(1)])
                    p._inplace_set(d._value)

        dist.shard_layer(layer, mesh2x4, shard_fn)
        assert not layer.weight._value.sharding.is_fully_replicated
        x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"))
        y = layer(x)
        ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
        np.testing.assert_allclose(y.numpy(), ref, rtol=2e-5, atol=2e-5)


class TestReviewRegressions:
    def test_reshard_preserves_autograd(self, mesh2x4):
        x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"),
                             stop_gradient=False)
        y = x * 2
        r = dist.reshard(y, mesh2x4, [dist.Shard(0), dist.Replicate()])
        loss = paddle.mean(r * r)
        loss.backward()
        assert x.grad is not None
        np.testing.assert_allclose(x.grad.numpy(),
                                   8 * x.numpy() / x.numpy().size, rtol=1e-5)

    def test_dense_tensor_dist_attrs_default_none(self):
        t = paddle.to_tensor([1.0])
        assert t.process_mesh is None
        assert t.placements is None

    def test_disjoint_mesh_harmonization(self):
        m1 = dist.ProcessMesh(np.arange(4), dim_names=["x"])
        m2 = dist.ProcessMesh(np.arange(4, 8), dim_names=["x"])
        a = dist.shard_tensor(
            paddle.to_tensor(np.ones((8, 2), dtype="float32")), m1, [dist.Shard(0)])
        b = dist.shard_tensor(
            paddle.to_tensor(np.ones((8, 2), dtype="float32")), m2, [dist.Shard(0)])
        c = paddle.add(a, b)
        np.testing.assert_allclose(c.numpy(), 2 * np.ones((8, 2)))

    def test_dtensor_from_fn_sharded_output(self, mesh2x4):
        d = dist.dtensor_from_fn(paddle.ones, mesh2x4,
                                 [dist.Shard(0), dist.Replicate()], [8, 4])
        assert not d._value.sharding.is_fully_replicated
        np.testing.assert_allclose(d.numpy(), np.ones((8, 4)))

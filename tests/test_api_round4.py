"""Round-4 API-breadth additions (OpTest pattern: numpy references).

Pre-emptive closure of the next probe ring: exp2/logaddexp2/shard_index/
triu-tril indices, adaptive/fractional/lp pooling completions, the loss
family (multi-margin, triplet-with-distance, npair, dice, log), adaptive
log-softmax, class-center sampling, and their nn.Layer wrappers.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestSmallOps:
    def test_exp2_logaddexp2(self):
        x = np.array([-1.0, 0.5, 3.0], np.float32)
        y = np.array([0.0, 1.0, 2.0], np.float32)
        np.testing.assert_allclose(paddle.exp2(_t(x)).numpy(), np.exp2(x),
                                   rtol=1e-6)
        np.testing.assert_allclose(paddle.logaddexp2(_t(x), _t(y)).numpy(),
                                   np.logaddexp2(x, y), rtol=1e-5)

    def test_bitwise_invert_is_floating_point(self):
        v = paddle.bitwise_invert(_t(np.array([0, -1, 5], np.int32)))
        np.testing.assert_array_equal(v.numpy(), [-1, 0, -6])
        assert paddle.is_floating_point(_t(np.float32(1)))
        assert not paddle.is_floating_point(_t(np.int32(1)))

    def test_shard_index(self):
        ids = _t(np.arange(8, dtype=np.int64))
        out = paddle.shard_index(ids, 8, 2, 1, ignore_value=-7)
        np.testing.assert_array_equal(out.numpy(),
                                      [-7, -7, -7, -7, 0, 1, 2, 3])
        with pytest.raises(Exception):
            paddle.shard_index(ids, 8, 2, 5)

    def test_triu_tril_indices(self):
        np.testing.assert_array_equal(
            paddle.triu_indices(3, 4, offset=1).numpy(),
            np.stack(np.triu_indices(3, k=1, m=4)))
        np.testing.assert_array_equal(
            paddle.tril_indices(4).numpy(),
            np.stack(np.tril_indices(4)))


class TestPoolingCompletions:
    def test_adaptive_max_pool1d(self):
        x = np.random.RandomState(0).randn(2, 3, 8).astype(np.float32)
        got = F.adaptive_max_pool1d(_t(x), 4).numpy()
        np.testing.assert_allclose(got, x.reshape(2, 3, 4, 2).max(-1))
        out, mask = F.adaptive_max_pool1d(_t(x), 4, return_mask=True)
        np.testing.assert_allclose(out.numpy(), got)
        np.testing.assert_array_equal(
            mask.numpy(), x.reshape(2, 3, 4, 2).argmax(-1)
            + np.arange(4)[None, None, :] * 2)

    def test_adaptive_avg_pool3d(self):
        x = np.random.RandomState(1).randn(1, 2, 4, 4, 6).astype(np.float32)
        got = F.adaptive_avg_pool3d(_t(x), (2, 2, 3)).numpy()
        ref = x.reshape(1, 2, 2, 2, 2, 2, 3, 2).mean((3, 5, 7))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        got_l = nn.AdaptiveAvgPool3D((2, 2, 3))(_t(x)).numpy()
        np.testing.assert_allclose(got_l, ref, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("u", [0.25, 0.61])
    def test_fractional_max_pool2d_windows_tile(self, u):
        rng = np.random.RandomState(2)
        x = rng.randn(1, 2, 7, 11).astype(np.float32)
        O = (3, 5)
        out, mask = F.fractional_max_pool2d(_t(x), O, random_u=u,
                                            return_mask=True)
        assert out.shape == [1, 2, 3, 5]
        # every window max must equal the value its mask index points to
        o = out.numpy()
        m = mask.numpy()
        flat = x.reshape(1, 2, -1)
        np.testing.assert_allclose(
            o, np.take_along_axis(flat, m.reshape(1, 2, -1),
                                  axis=-1).reshape(o.shape))
        # windows tile the input: union of picked windows covers max of x
        assert np.isclose(o.max(), x.max())

    def test_fractional_max_pool3d_shape(self):
        x = np.random.RandomState(3).randn(1, 1, 6, 6, 6).astype(np.float32)
        out = F.fractional_max_pool3d(_t(x), (2, 3, 2), random_u=0.4)
        assert out.shape == [1, 1, 2, 3, 2]
        assert np.isclose(out.numpy().max(), x.max())

    def test_unpool_and_lp_layers(self):
        rng = np.random.RandomState(4)
        x = _t(rng.randn(1, 2, 8, 8).astype(np.float32))
        out, mask = F.max_pool2d(x, 2, 2, return_mask=True)
        up = nn.MaxUnPool2D(2, 2)(out, mask)
        assert up.shape == [1, 2, 8, 8]
        lp = nn.LPPool2D(2.0, 2, 2)(x)
        assert lp.shape == [1, 2, 4, 4]
        s = nn.Silu()(x)
        np.testing.assert_allclose(
            s.numpy(), x.numpy() / (1 + np.exp(-x.numpy())), rtol=1e-5)


class TestLossCompletions:
    def test_multi_margin_loss(self):
        rng = np.random.RandomState(5)
        x = rng.randn(4, 6).astype(np.float32)
        y = rng.randint(0, 6, (4,)).astype(np.int64)
        w = rng.rand(6).astype(np.float32)
        margin = 0.7
        h = np.maximum(0.0, margin - x[np.arange(4), y][:, None] + x)
        h[np.arange(4), y] = 0.0
        ref = (h.sum(-1) / 6).mean()
        np.testing.assert_allclose(
            F.multi_margin_loss(_t(x), _t(y), margin=margin).numpy(), ref,
            rtol=1e-5)
        ref_w = ((h * w[y][:, None]).sum(-1) / 6).sum()
        np.testing.assert_allclose(
            F.multi_margin_loss(_t(x), _t(y), margin=margin, weight=_t(w),
                                reduction="sum").numpy(), ref_w, rtol=1e-5)
        got_layer = nn.MultiMarginLoss(margin=margin)(_t(x), _t(y))
        np.testing.assert_allclose(got_layer.numpy(), ref, rtol=1e-5)

    def test_triplet_margin_with_distance_loss(self):
        rng = np.random.RandomState(6)
        a, p, n = (rng.randn(5, 8).astype(np.float32) for _ in range(3))
        dp = np.sqrt(((a - p) ** 2).sum(-1))
        dn = np.sqrt(((a - n) ** 2).sum(-1))
        ref = np.maximum(0.0, dp - dn + 1.0).mean()
        got = F.triplet_margin_with_distance_loss(_t(a), _t(p), _t(n))
        np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4)
        # swap uses min(dn, d(p, n))
        dpn = np.sqrt(((p - n) ** 2).sum(-1))
        ref_s = np.maximum(0.0, dp - np.minimum(dn, dpn) + 1.0).mean()
        got_s = nn.TripletMarginWithDistanceLoss(swap=True)(
            _t(a), _t(p), _t(n))
        np.testing.assert_allclose(got_s.numpy(), ref_s, rtol=1e-4)
        # custom distance function (L1)
        got_l1 = F.triplet_margin_with_distance_loss(
            _t(a), _t(p), _t(n),
            distance_function=lambda u, v: paddle.sum(paddle.abs(u - v),
                                                      axis=-1))
        dl = np.abs(a - p).sum(-1) - np.abs(a - n).sum(-1) + 1.0
        np.testing.assert_allclose(got_l1.numpy(),
                                   np.maximum(0, dl).mean(), rtol=1e-4)

    def test_npair_dice_log_losses(self):
        rng = np.random.RandomState(7)
        a = rng.randn(4, 6).astype(np.float32)
        p = rng.randn(4, 6).astype(np.float32)
        y = np.array([0, 1, 0, 2], np.int64)
        tgt = (y[:, None] == y[None, :]).astype(np.float32)
        tgt /= tgt.sum(1, keepdims=True)
        sim = a @ p.T
        logp = sim - np.log(np.exp(sim - sim.max(1, keepdims=True)).sum(
            1, keepdims=True)) - sim.max(1, keepdims=True)
        ce = (-tgt * logp).sum(1).mean()
        l2 = ((a * a).sum() + (p * p).sum()) / 4 * (0.002 * 0.25)
        np.testing.assert_allclose(
            F.npair_loss(_t(a), _t(p), _t(y)).numpy(), ce + l2, rtol=1e-4)

        probs = rng.rand(3, 5, 4).astype(np.float32)
        lab = rng.randint(0, 4, (3, 5, 1)).astype(np.int64)
        onehot = np.eye(4, dtype=np.float32)[lab[..., 0]]
        inter = (probs * onehot).sum((1, 2))
        union = probs.sum((1, 2)) + onehot.sum((1, 2))
        ref = (1 - 2 * inter / (union + 1e-5)).mean()
        np.testing.assert_allclose(
            F.dice_loss(_t(probs), _t(lab)).numpy(), ref, rtol=1e-5)

        pr = rng.rand(6).astype(np.float32)
        yy = rng.randint(0, 2, (6,)).astype(np.float32)
        ref = -yy * np.log(pr + 1e-4) - (1 - yy) * np.log(1 - pr + 1e-4)
        np.testing.assert_allclose(F.log_loss(_t(pr), _t(yy)).numpy(), ref,
                                   rtol=1e-5)

    def test_temperature_scaled_softmax_and_zeropad(self):
        x = np.random.RandomState(8).randn(3, 5).astype(np.float32)
        got = F.temperature_scaled_softmax(_t(x), 2.5).numpy()
        e = np.exp(x / 2.5 - (x / 2.5).max(-1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                                   rtol=1e-5)
        z = F.zeropad2d(_t(np.ones((1, 1, 2, 2), np.float32)),
                        [1, 2, 3, 4]).numpy()
        assert z.shape == (1, 1, 9, 5)
        assert z.sum() == 4.0 and z[0, 0, 3, 1] == 1.0


class TestAdaptiveLogSoftmax:
    def test_normalizes_and_matches_manual(self):
        """Exactness contract: the implied class distribution normalizes
        to 1 and the returned values are the true-class log-probs."""
        rng = np.random.RandomState(9)
        N, D = 3, 6
        cutoffs = [4, 8]  # shortlist 4, one tail cluster of classes 4..7
        x = rng.randn(N, D).astype(np.float32)
        hw = rng.randn(D, 4 + 1).astype(np.float32)  # shortlist + 1 cluster
        proj = rng.randn(D, 3).astype(np.float32)
        cls = rng.randn(3, 4).astype(np.float32)

        total = np.zeros(N)
        logps = {}
        for c in range(8):
            y = np.full((N,), c, np.int64)
            out, loss = F.adaptive_log_softmax_with_loss(
                _t(x), _t(y), _t(hw), [(_t(proj), _t(cls))], cutoffs)
            logps[c] = out.numpy()
            total += np.exp(out.numpy())
            np.testing.assert_allclose(loss.numpy(), -out.numpy().mean(),
                                       rtol=1e-5)
        np.testing.assert_allclose(total, np.ones(N), rtol=1e-4)
        # head classes match a plain log_softmax over the head logits
        head = x @ hw
        head_lp = head - np.log(np.exp(
            head - head.max(1, keepdims=True)).sum(1, keepdims=True)) \
            - head.max(1, keepdims=True)
        for c in range(4):
            np.testing.assert_allclose(logps[c], head_lp[:, c], rtol=1e-4)


class TestClassCenterSample:
    def test_positives_kept_and_remapped(self):
        lab = _t(np.array([3, 7, 3, 50], np.int64))
        remapped, sampled = F.class_center_sample(lab, 100, 8)
        s = sampled.numpy()
        assert len(s) == 8 and len(np.unique(s)) == 8
        for c in (3, 7, 50):
            assert c in s
        r = remapped.numpy()
        np.testing.assert_array_equal(s[r], lab.numpy())


def test_host_randomness_tied_to_paddle_seed():
    """Review finding: host-geometry randomness (fractional windows,
    class-center sampling) must be reproducible under paddle.seed."""
    x = _t(np.random.RandomState(0).randn(1, 1, 7, 7).astype(np.float32))
    paddle.seed(123)
    a = F.fractional_max_pool2d(x, (3, 3)).numpy()
    lab = _t(np.array([1, 2], np.int64))
    _, s1 = F.class_center_sample(lab, 50, 8)
    paddle.seed(123)
    b = F.fractional_max_pool2d(x, (3, 3)).numpy()
    _, s2 = F.class_center_sample(lab, 50, 8)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(s1.numpy(), s2.numpy())


def test_fractional_no_mask_path_matches_mask_path():
    x = _t(np.random.RandomState(11).randn(2, 3, 9, 7).astype(np.float32))
    out_m, _ = F.fractional_max_pool2d(x, (4, 3), random_u=0.37,
                                       return_mask=True)
    out = F.fractional_max_pool2d(x, (4, 3), random_u=0.37)
    np.testing.assert_allclose(out.numpy(), out_m.numpy())


def test_zeropad2d_nhwc():
    z = F.zeropad2d(_t(np.ones((1, 2, 2, 1), np.float32)), [1, 0, 0, 2],
                    data_format="NHWC").numpy()
    assert z.shape == (1, 4, 3, 1)
    assert z.sum() == 4.0 and z[0, 0, 1, 0] == 1.0


class TestDistributedCompletions:
    def test_alltoall_single_and_gather(self):
        import jax

        import paddle_tpu.distributed as dist
        from paddle_tpu.parallel import create_hybrid_mesh, set_mesh

        mesh = create_hybrid_mesh(dp=4, devices=jax.devices()[:4])
        try:
            dist.init_parallel_env()
            x = _t(np.arange(8, dtype=np.float32).reshape(4, 2))
            out = dist.alltoall_single(x)
            assert out.shape == [4, 2]
            with pytest.raises(NotImplementedError, match="unequal"):
                dist.alltoall_single(x, in_split_sizes=[1, 3])
            got = []
            chunks = dist.gather(_t(np.ones((2,), np.float32)), got, dst=0)
            assert len(chunks) >= 1
            # single-process world: dst receives the list
            assert len(got) == len(chunks)
        finally:
            set_mesh(None)

    def test_broadcast_object_list_world_of_one(self):
        import paddle_tpu.distributed as dist

        objs = [{"a": 1}, [1, 2, 3]]
        out = dist.broadcast_object_list(objs, src=0)
        assert out == [{"a": 1}, [1, 2, 3]]

    def test_unshard_dtensor_roundtrip(self):
        import jax

        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import ProcessMesh, Replicate, Shard
        from paddle_tpu.parallel import set_mesh

        try:
            pm = ProcessMesh(np.arange(4), ["x"])
            d = dist.shard_tensor(np.arange(8, dtype=np.float32),
                                  pm, [Shard(0)])
            u = dist.unshard_dtensor(d)
            np.testing.assert_allclose(u.numpy(),
                                       np.arange(8, dtype=np.float32))
            assert getattr(u, "process_mesh", None) is None
        finally:
            set_mesh(None)


def test_subset_random_sampler():
    from paddle_tpu.io import SubsetRandomSampler

    s = SubsetRandomSampler([3, 7, 9])
    got = sorted(list(iter(s)))
    assert got == [3, 7, 9] and len(s) == 3


class TestVisionOpsCompletions:
    def test_deform_conv2d_zero_offsets_equals_conv(self):
        """v1 with all-zero offsets IS the dense conv — exact parity."""
        rng = np.random.RandomState(21)
        x = rng.randn(2, 4, 7, 7).astype(np.float32)
        w = rng.randn(6, 4, 3, 3).astype(np.float32)
        b = rng.randn(6).astype(np.float32)
        off = np.zeros((2, 2 * 9, 7, 7), np.float32)
        from paddle_tpu.vision.ops import deform_conv2d

        got = deform_conv2d(_t(x), _t(off), _t(w), bias=_t(b),
                            padding=1).numpy()
        ref = F.conv2d(_t(x), _t(w), bias=_t(b), padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_deform_conv2d_v2_mask_scales(self):
        rng = np.random.RandomState(22)
        x = rng.randn(1, 2, 5, 5).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 5, 5), np.float32)
        from paddle_tpu.vision.ops import deform_conv2d

        full = deform_conv2d(_t(x), _t(off), _t(w), padding=1,
                             mask=_t(np.ones((1, 9, 5, 5), np.float32)))
        half = deform_conv2d(_t(x), _t(off), _t(w), padding=1,
                             mask=_t(np.full((1, 9, 5, 5), 0.5,
                                             np.float32)))
        np.testing.assert_allclose(half.numpy(), full.numpy() * 0.5,
                                   rtol=1e-5, atol=1e-5)

    def test_prior_box_geometry(self):
        feat = _t(np.zeros((1, 1, 2, 2), np.float32))
        img = _t(np.zeros((1, 3, 32, 32), np.float32))
        from paddle_tpu.vision.ops import prior_box

        boxes, var = prior_box(feat, img, min_sizes=[16])
        assert boxes.shape == [2, 2, 1, 4]
        b00 = boxes.numpy()[0, 0, 0]
        # cell (0,0) center at (8, 8) px, box 16x16 -> [0, 0, 16, 16]/32
        np.testing.assert_allclose(b00, [0.0, 0.0, 0.5, 0.5], atol=1e-6)
        np.testing.assert_allclose(var.numpy()[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2])

    def test_yolo_box_decode_single_cell(self):
        from paddle_tpu.vision.ops import yolo_box

        A, C = 1, 1
        x = np.zeros((1, A * (5 + C), 1, 1), np.float32)
        x[0, 4] = 10.0  # conf ~ 1
        x[0, 5] = 10.0  # class ~ 1
        boxes, scores = yolo_box(_t(x), _t(np.array([[32, 32]], np.int32)),
                                 [16, 16], C, 0.5, downsample_ratio=32,
                                 clip_bbox=False)
        # sigmoid(0)=0.5 -> center (0.5, 0.5) of the 1x1 grid; w=h=16/32
        np.testing.assert_allclose(boxes.numpy()[0, 0],
                                   [8.0, 8.0, 24.0, 24.0], atol=1e-3)
        assert scores.numpy()[0, 0, 0] > 0.99

    def test_distribute_fpn_and_psroi(self):
        from paddle_tpu.vision.ops import distribute_fpn_proposals, psroi_pool

        rois = _t(np.array([[0, 0, 20, 20], [0, 0, 220, 220],
                            [0, 0, 500, 500]], np.float32))
        outs, restore, nums = distribute_fpn_proposals(rois, 2, 5, 4, 224)
        sizes = [int(np.asarray(n.numpy())[0]) for n in nums]
        assert sum(sizes) == 3
        # small roi -> low level, big roi -> high level
        assert sizes[0] >= 1 and sizes[-1] >= 1
        cat = np.concatenate([o.numpy() for o in outs if o.shape[0]])
        np.testing.assert_allclose(cat[restore.numpy()], rois.numpy())

        # psroi: constant per-channel input -> output equals the channel
        # group's constant
        x = np.zeros((1, 4, 4, 4), np.float32)  # out_c=1, ph=pw=2
        for c in range(4):
            x[0, c] = c
        out = psroi_pool(_t(x), _t(np.array([[0, 0, 3, 3]], np.float32)),
                         _t(np.array([1], np.int32)), 2).numpy()
        np.testing.assert_allclose(out[0, 0].reshape(-1), [0, 1, 2, 3])


class TestTransformCompletions:
    def test_pad_grayscale_shapes_and_values(self):
        from paddle_tpu.vision import transforms as T

        img = np.ones((3, 4, 4), np.float32)
        assert T.Pad(2)(img).shape == (3, 8, 8)
        assert T.Pad((1, 2))(img).shape == (3, 8, 6)
        g = T.Grayscale(1)(img)
        assert g.shape == (1, 4, 4)
        np.testing.assert_allclose(g, 1.0, rtol=1e-5)

    def test_random_transforms_deterministic_under_seed(self):
        from paddle_tpu.vision import transforms as T

        img = np.random.RandomState(0).rand(3, 16, 16).astype(np.float32)
        outs = []
        for _ in range(2):
            np.random.seed(77)
            outs.append((T.ColorJitter(0.3, 0.3, 0.3, 0.1)(img),
                         T.RandomRotation(25)(img),
                         T.RandomResizedCrop(8)(img)))
        for a, b in zip(outs[0], outs[1]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        assert outs[0][2].shape[-2:] == (8, 8)


class TestIncubateCompletions:
    def test_segment_minmax_and_masked_softmax(self):
        import paddle_tpu.incubate as inc

        d = _t(np.array([[1.0, 5], [3, 2], [0, 9]], np.float32))
        ids = _t(np.array([0, 0, 1]))
        np.testing.assert_allclose(inc.segment_max(d, ids).numpy(),
                                   [[3, 5], [0, 9]])
        np.testing.assert_allclose(inc.segment_min(d, ids).numpy(),
                                   [[1, 2], [0, 9]])
        x = np.random.RandomState(3).randn(2, 4, 4).astype(np.float32)
        sm = inc.softmax_mask_fuse_upper_triangle(_t(x)).numpy()
        assert np.allclose(np.triu(sm[0], 1), 0.0)
        np.testing.assert_allclose(sm.sum(-1), 1.0, rtol=1e-5)
        assert hasattr(inc.autograd, "jacobian")
        np.testing.assert_allclose(
            float(inc.identity_loss(_t(np.array([2.0, 4.0], np.float32)),
                                    reduction="sum")), 6.0)


class TestVisionOpsReviewFixes:
    def test_yolo_box_multicell_grid_alignment(self):
        """Review finding: boxes on an H>1 grid must stay aligned with
        their cells (the scores-path transpose scrambled them)."""
        from paddle_tpu.vision.ops import yolo_box

        A, C, H, W = 1, 1, 2, 2
        x = np.zeros((1, A * (5 + C), H, W), np.float32)
        x[0, 4] = 10.0
        x[0, 5] = 10.0
        boxes, scores = yolo_box(_t(x), _t(np.array([[64, 64]], np.int32)),
                                 [32, 32], C, 0.5, downsample_ratio=32,
                                 clip_bbox=False)
        b = boxes.numpy().reshape(H, W, 4)
        # cell (i, j) center at ((j+0.5)/W, (i+0.5)/H) of a 64px image,
        # box 32x32: x range = 64*(j+0.5)/2 +- 16
        for i in range(H):
            for j in range(W):
                cx = 64 * (j + 0.5) / W
                cy = 64 * (i + 0.5) / H
                np.testing.assert_allclose(
                    b[i, j], [cx - 16, cy - 16, cx + 16, cy + 16],
                    atol=1e-3)

    def test_deform_conv2d_bias_with_mask(self):
        """Review finding: bias must be rest[0] even when a mask is also
        passed (DCNv2's standard call)."""
        from paddle_tpu.vision.ops import deform_conv2d

        rng = np.random.RandomState(23)
        x = rng.randn(1, 2, 5, 5).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        b = rng.randn(3).astype(np.float32)
        off = np.zeros((1, 18, 5, 5), np.float32)
        ones_mask = np.ones((1, 9, 5, 5), np.float32)
        with_b = deform_conv2d(_t(x), _t(off), _t(w), bias=_t(b),
                               padding=1, mask=_t(ones_mask)).numpy()
        no_b = deform_conv2d(_t(x), _t(off), _t(w), padding=1,
                             mask=_t(ones_mask)).numpy()
        np.testing.assert_allclose(with_b - no_b,
                                   np.broadcast_to(b.reshape(1, -1, 1, 1),
                                                   with_b.shape),
                                   rtol=1e-4, atol=1e-5)

    def test_prior_box_min_max_order(self):
        from paddle_tpu.vision.ops import prior_box

        feat = _t(np.zeros((1, 1, 1, 1), np.float32))
        img = _t(np.zeros((1, 3, 32, 32), np.float32))
        kw = dict(min_sizes=[8], max_sizes=[16], aspect_ratios=[2.0])
        b_def, _ = prior_box(feat, img, **kw)
        b_mm, _ = prior_box(feat, img, min_max_aspect_ratios_order=True,
                            **kw)
        wdef = (b_def.numpy()[0, 0, :, 2] - b_def.numpy()[0, 0, :, 0]) * 32
        wmm = (b_mm.numpy()[0, 0, :, 2] - b_mm.numpy()[0, 0, :, 0]) * 32
        # default: [min(8), ar2, max(sqrt(128))]; flag: [min, max, ar2]
        np.testing.assert_allclose(wdef[0], 8, atol=1e-4)
        np.testing.assert_allclose(wmm[1], np.sqrt(8 * 16), atol=1e-4)
        assert set(np.round(wdef, 3)) == set(np.round(wmm, 3))

    def test_random_rotation_expand_and_center(self):
        from paddle_tpu.vision import transforms as T

        img = np.ones((1, 10, 20), np.float32)
        np.random.seed(5)
        out = T.RandomRotation((90, 90), expand=True)(img)
        # 90-degree rotation of 10x20 -> canvas ~20x10
        assert abs(out.shape[1] - 20) <= 1 and abs(out.shape[2] - 10) <= 1
        # most content preserved under expand (nearest-neighbor resampling
        # clips a boundary row/col at exact 90 degrees)
        assert out.sum() >= img.sum() * 0.85
        np.random.seed(5)
        out_c = T.RandomRotation((90, 90), center=(5.0, 5.0))(img)
        assert out_c.shape == img.shape


class TestFinalCompletions:
    def test_saved_tensors_hooks_pack_unpack(self):
        """paddle.autograd.saved_tensors_hooks: pack transforms saved
        tensors at record time, unpack restores at backward — gradients
        must be exact, and the hooks must actually fire."""
        calls = {"pack": 0, "unpack": 0}

        def pack(t):
            calls["pack"] += 1
            return np.asarray(t.numpy())  # e.g. offload to host

        def unpack(v):
            calls["unpack"] += 1
            return paddle.to_tensor(v)

        x = _t(np.array([2.0, 3.0], np.float32))
        x.stop_gradient = False
        with paddle.autograd.saved_tensors_hooks(pack, unpack):
            y = paddle.sum(x * x)
        assert calls["pack"] > 0 and calls["unpack"] == 0
        y.backward()
        assert calls["unpack"] > 0
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0], rtol=1e-6)
        # without hooks: unchanged behavior
        x2 = _t(np.array([2.0, 3.0], np.float32))
        x2.stop_gradient = False
        paddle.sum(x2 * x2).backward()
        np.testing.assert_allclose(x2.grad.numpy(), [4.0, 6.0], rtol=1e-6)

    def test_cosine_warm_restarts(self):
        from paddle_tpu.optimizer.lr import CosineAnnealingWarmRestarts

        s = CosineAnnealingWarmRestarts(0.1, T_0=4, T_mult=2)
        lrs = []
        for _ in range(12):
            lrs.append(s.get_lr())
            s.step()
        assert abs(lrs[0] - 0.1) < 1e-9          # epoch 0: max
        assert lrs[2] < lrs[1] < lrs[0]          # annealing
        assert abs(lrs[4] - 0.1) < 1e-9          # restart at T_0
        assert abs(lrs[12 - 8 + 4] - lrs[4]) > 0  # second period longer
        assert all(v <= 0.1 + 1e-9 for v in lrs)

    def test_jit_debug_knobs_and_translated_layer(self, capsys, tmp_path):
        import paddle_tpu.jit as jit

        jit.set_code_level(100)
        try:
            @jit.to_static
            def f(x):
                if x.sum() > 0:
                    y = x + 1
                else:
                    y = x - 1
                return y

            out = f(_t(np.ones(2, np.float32)))
            np.testing.assert_allclose(out.numpy(), [2, 2])
            assert "dy2static" in capsys.readouterr().out
        finally:
            jit.set_code_level(0)
        jit.set_verbosity(3)
        jit.set_verbosity(0)

        # TranslatedLayer round-trip through jit.save/load
        lin = paddle.nn.Linear(4, 2)
        xs = _t(np.random.RandomState(0).randn(3, 4).astype(np.float32))
        ref = lin(xs).numpy()
        path = str(tmp_path / "m")
        paddle.jit.save(lin, path, input_spec=[
            paddle.static.InputSpec([None, 4], "float32")])
        loaded = paddle.jit.load(path)
        assert isinstance(loaded, jit.TranslatedLayer)
        np.testing.assert_allclose(loaded(xs).numpy(), ref, rtol=1e-5)

"""Flash attention.

Counterpart of the reference's ``flash_attn`` fused kernel
(``paddle/phi/kernels/fusion`` wrapping the FlashAttention CUDA lib;
SURVEY.md §2.1). Two paths:

* ``_pallas_flash_attention`` — tiled online-softmax kernel in VMEM for TPU
  (MXU-sized q/k blocks, numerically stable running max/sum rescaling).
* ``_xla_attention`` — plain jnp formulation for CPU tests and as the
  reference implementation; XLA fuses it reasonably but materialises the
  [S, S] score matrix.

Layout convention (paddle flash_attn): [batch, seq, num_heads, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ... import flags


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _xla_attention(q, k, v, mask=None, is_causal=False, scale=None):
    # q,k,v: [B, S, H, D] -> scores over S. Matmuls keep the input dtype
    # (bf16 on TPU) with fp32 ACCUMULATION via preferred_element_type — the
    # MXU's native mode; casting inputs to fp32 first would run the matmul
    # at 1/8 MXU rate (this path is also the flash-VJP's recompute, so it
    # sets the backward-pass speed).
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel (forward). Grid: (batch*heads, q_blocks); the kv loop runs
# inside the kernel with a running (max, sum) online softmax.
# ---------------------------------------------------------------------------

def _make_pallas_fwd(block_q: int, block_k: int, is_causal: bool, scale: float,
                     causal_offset: int = 0):
    """``causal_offset`` aligns the causal diagonal when sq != sk (KV-cache
    decode): query row i sits at absolute position i + offset, matching the
    XLA fallback's ``tril(..., k=sk-sq)`` convention."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(q_ref, k_ref, v_ref, o_ref):
        # q_ref: [1, block_q, d]; k_ref/v_ref: [1, S, d] (this head's K/V)
        qb = q_ref[0].astype(jnp.float32) * scale
        S = k_ref.shape[1]
        q_idx = pl.program_id(1)

        def body(start, carry):
            acc, m_prev, l_prev = carry
            kb = k_ref[0, pl.ds(start * block_k, block_k), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(start * block_k, block_k), :].astype(jnp.float32)
            s = qb @ kb.T  # [block_q, block_k]
            if is_causal:
                q_pos = causal_offset + q_idx * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = start * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            # guard fully-masked rows (m == -inf)
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - m_safe[:, None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + p @ vb
            return acc, m_new, l_new

        n_k = S // block_k
        if is_causal:
            # only blocks up to the diagonal contribute
            last = jax.lax.div(
                causal_offset + (q_idx + 1) * block_q + block_k - 1,
                jnp.int32(block_k),
            )
            n_iter = jnp.minimum(n_k, last)
        else:
            n_iter = n_k
        acc0 = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
        m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((block_q,), jnp.float32)
        acc, m, l = jax.lax.fori_loop(0, n_iter, body, (acc0, m0, l0))
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)

    return kernel


def _pallas_flash_attention(q, k, v, is_causal=False, scale=None,
                            block_q: int = 256, block_k: int = 256):
    """Forward flash attention via Pallas. [B, S, H, D] layout."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        return _xla_attention(q, k, v, is_causal=is_causal, scale=scale)

    # fold batch & heads into the grid's first axis: [B*H, S, D]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kernel = _make_pallas_fwd(block_q, block_k, is_causal, scale,
                              causal_offset=sk - sq)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def dot_product_attention(q, k, v, mask=None, is_causal=False):
    """Public entry: picks Pallas on TPU (when enabled and mask-free),
    XLA reference elsewhere. Differentiable (backward via XLA autodiff of the
    reference path when pallas is active — see flash_attention custom VJP
    TODO in M3 notes)."""
    use_pallas = (
        _on_tpu()
        and flags.get_flags("use_pallas_kernels")["use_pallas_kernels"]
        and mask is None
    )
    if use_pallas:
        return _flash_custom_vjp(q, k, v, is_causal)
    return _xla_attention(q, k, v, mask=mask, is_causal=is_causal)


# custom VJP: pallas forward, XLA-recompute backward (flash-style backward
# kernel lands with M3 perf work; recompute keeps memory at O(S) not O(S^2)
# only in the forward — backward materialises scores per-head).
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_custom_vjp(q, k, v, is_causal):
    return _pallas_flash_attention(q, k, v, is_causal=is_causal)


def _flash_fwd(q, k, v, is_causal):
    return _pallas_flash_attention(q, k, v, is_causal=is_causal), (q, k, v)


def _flash_bwd(is_causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(q_, k_, v_, is_causal=is_causal), q, k, v)
    return vjp(g)


_flash_custom_vjp.defvjp(_flash_fwd, _flash_bwd)

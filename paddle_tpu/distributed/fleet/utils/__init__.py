from . import hybrid_parallel_util, sequence_parallel_utils
from .hybrid_parallel_util import fused_allreduce_gradients
from .sequence_parallel_utils import (
    AllGatherOp,
    ColumnSequenceParallelLinear,
    GatherOp,
    ReduceScatterOp,
    RowSequenceParallelLinear,
    ScatterOp,
    mark_as_sequence_parallel_parameter,
    register_sequence_parallel_allreduce_hooks,
)

__all__ = ["fused_allreduce_gradients", "ScatterOp", "GatherOp",
           "AllGatherOp", "ReduceScatterOp",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear"]

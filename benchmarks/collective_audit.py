"""Print the per-axis collective inventory of the baseline-ladder steps.

Runs on the 8-device virtual CPU mesh (no TPU needed): compiles the DP
ResNet step and the LLaMA hybrid (dp×sharding×mp) step, audits their
optimized HLO with ``hlo_audit``, and prints the tables SCALING.md embeds.
Usage::

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/collective_audit.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def audit_dp_resnet():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.auto_parallel.api import (
        ProcessMesh, shard_layer)
    from paddle_tpu.distributed.auto_parallel.hlo_audit import (
        collective_inventory, format_inventory)
    from paddle_tpu.vision.models import resnet18
    from jax.sharding import NamedSharding, PartitionSpec as P

    pm = ProcessMesh(np.arange(8), ["dp"])
    model = resnet18(num_classes=10)
    model.train()
    shard_layer(model, pm)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    step = paddle.jit.fused_train_step(lambda x, y: ce(model(x), y), opt,
                                       model=model)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(jax.device_put(
        rng.rand(16, 3, 32, 32).astype(np.float32),
        NamedSharding(pm.mesh, P("dp"))))
    y = paddle.to_tensor(jax.device_put(
        rng.randint(0, 10, (16,)), NamedSharding(pm.mesh, P("dp"))))
    step.compile(x, y)
    entry = next(iter(step._cache.values()))
    inv = collective_inventory(entry._compiled.as_text(), pm.mesh)
    grad_b = sum(4 * int(np.prod(p.shape)) for p in model.parameters()
                 if not p.stop_gradient)
    print("== DP-8 ResNet18 train step (b16, fp32 grads) ==")
    print(format_inventory(inv))
    print(f"trainable grad bytes: {grad_b / 2**20:.2f} MiB; "
          f"all-reduce payload: "
          f"{sum(e['bytes'] for e in inv) / 2**20:.2f} MiB")
    print()


def audit_llama_hybrid():
    from paddle_tpu.distributed.auto_parallel.hlo_audit import (
        collective_inventory, format_inventory)
    from paddle_tpu.models import llama
    from paddle_tpu.parallel import create_hybrid_mesh, set_mesh
    import jax.numpy as jnp

    cfg = llama.LlamaConfig.tiny(sharding_stage=3)
    mesh = create_hybrid_mesh(dp=2, sharding=2, mp=2,
                              devices=jax.devices()[:8])
    try:
        step = llama.make_sharded_train_step(cfg, mesh, lr=1e-3)
        params = llama.init_params(cfg)
        opt = llama.init_opt_state(params)
        toks = jnp.array(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (8, 32)), jnp.int32)
        txt = step.lower(params, opt, toks, toks).compile().as_text()
        inv = collective_inventory(txt, mesh)
        print("== LLaMA-tiny hybrid step (dp=2 x sharding=2 x mp=2, "
              "ZeRO-3 + TP) ==")
        print(format_inventory(inv))
        print()
    finally:
        set_mesh(None)


if __name__ == "__main__":
    if len(jax.devices()) < 8:
        raise SystemExit("run with the 8-device virtual CPU mesh (see "
                         "module docstring)")
    audit_dp_resnet()
    audit_llama_hybrid()

"""ZeRO group_sharded + sequence-parallel tests (reference strategy:
sharding stage2/3 results must equal plain training; SP layers must equal
their dense counterparts — SURVEY.md §4 hybrid-parallel parity rows)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import group_sharded_parallel
from paddle_tpu.distributed.fleet.utils import (
    AllGatherOp,
    ColumnSequenceParallelLinear,
    GatherOp,
    ReduceScatterOp,
    RowSequenceParallelLinear,
    ScatterOp,
    mark_as_sequence_parallel_parameter,
)
from paddle_tpu.parallel import create_hybrid_mesh, set_mesh


@pytest.fixture
def zero_mesh():
    mesh = create_hybrid_mesh(dp=2, sharding=4)
    yield mesh
    set_mesh(None)


@pytest.fixture
def mp4_mesh():
    mesh = create_hybrid_mesh(dp=2, mp=4)
    yield mesh
    set_mesh(None)


def _train_steps(model, opt, steps=3, seed=42):
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        x = paddle.to_tensor(rng.randn(8, 16).astype("float32"))
        y = paddle.to_tensor(rng.randn(8, 4).astype("float32"))
        loss = paddle.mean((model(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestGroupSharded:
    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_stage_matches_unsharded(self, zero_mesh, level):
        paddle.seed(100)
        ref_model = paddle.nn.Linear(16, 4)
        ref_opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=ref_model.parameters())
        w0 = ref_model.weight.numpy().copy()
        set_mesh(None)  # reference run entirely unsharded
        ref_losses = _train_steps(ref_model, ref_opt)

        create_hybrid_mesh(dp=2, sharding=4)
        paddle.seed(100)
        model = paddle.nn.Linear(16, 4)
        np.testing.assert_allclose(model.weight.numpy(), w0)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, level=level)
        losses = _train_steps(model, opt)
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-5, atol=1e-6)

    def test_stage3_param_layout_is_sharded(self, zero_mesh):
        model = paddle.nn.Linear(16, 8)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, level="p_g_os")
        sh = model.weight._value.sharding
        # weight [16, 8]: dim0 divisible by 8 → sharded over ('dp','sharding')
        assert not sh.is_fully_replicated

    def test_offload_states_live_in_host_memory(self, zero_mesh):
        """offload=True: between steps the sharded optimizer states sit in
        pinned_host memory (the reference's CPU offload), and training
        still matches the non-offloaded run numerically."""
        paddle.seed(101)
        ref_model = paddle.nn.Linear(16, 4)
        ref_opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=ref_model.parameters())
        set_mesh(None)
        ref_losses = _train_steps(ref_model, ref_opt)

        create_hybrid_mesh(dp=2, sharding=4)
        paddle.seed(101)
        model = paddle.nn.Linear(16, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, level="os_g",
                                            offload=True)
        losses = _train_steps(model, opt)
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-5, atol=1e-6)
        states = opt._inner_opt._accumulators[id(model.weight)]
        host_kinds = [v.sharding.memory_kind for v in states.values()
                      if hasattr(v, "sharding") and v.ndim > 0]
        # the offload contract is HOST residency; TPUs expose it as
        # pinned_host, this container's CPU backend as unpinned_host
        assert host_kinds and all(k in ("pinned_host", "unpinned_host")
                                  for k in host_kinds), host_kinds

    def test_scaler_wrap(self, zero_mesh):
        model = paddle.nn.Linear(16, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024)
        model, opt, scaler = group_sharded_parallel(model, opt, level="os_g",
                                                    scaler=scaler)
        assert scaler is not None


class TestSequenceParallel:
    def test_scatter_gather_roundtrip(self, mp4_mesh):
        x = paddle.to_tensor(np.random.randn(2, 8, 16).astype("float32"))
        s = ScatterOp.apply(x)
        g = GatherOp.apply(s)
        np.testing.assert_allclose(g.numpy(), x.numpy())
        # scattered layout: seq dim sharded over mp
        assert not s._value.sharding.is_fully_replicated

    def test_sp_linear_pair_matches_dense(self, mp4_mesh):
        paddle.seed(21)
        col = ColumnSequenceParallelLinear(16, 32, gather_output=False)
        row = RowSequenceParallelLinear(32, 16, input_is_parallel=True)
        x = paddle.to_tensor(np.random.randn(2, 8, 16).astype("float32"))
        xs = ScatterOp.apply(x)  # enter SP region: seq-sharded
        y = GatherOp.apply(row(col(xs)))
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(y.numpy(), ref, rtol=2e-5, atol=2e-5)

    def test_sp_backward(self, mp4_mesh):
        col = ColumnSequenceParallelLinear(8, 16, gather_output=False)
        row = RowSequenceParallelLinear(16, 8, input_is_parallel=True)
        x = paddle.to_tensor(np.random.randn(2, 4, 8).astype("float32"),
                             stop_gradient=False)
        loss = paddle.mean(GatherOp.apply(row(col(ScatterOp.apply(x)))))
        loss.backward()
        assert x.grad is not None
        assert col.weight.grad is not None

    def test_mark_parameter(self, mp4_mesh):
        ln = paddle.nn.LayerNorm(16)
        mark_as_sequence_parallel_parameter(ln.weight)
        assert getattr(ln.weight, "sequence_parallel", False)


def test_sequence_parallel_ring_dispatch_lowering():
    """Cheap tier-1 cousin of the full parity test below (r25 suite-time
    claw-back): with sequence_parallel=True on a sep>1 hybrid mesh the
    flagship model's attention DISPATCHES to the ring (context-parallel)
    formulation — pinned on the lowered program text WITHOUT paying the
    8-virtual-device XLA compile. Ring-op numerics (forward + grad
    parity vs full attention) stay tier-1 in test_moe_ring.py; the
    full-model fwd+grad parity runs as `slow` + in the chip lane."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.models import llama
    from paddle_tpu.parallel import create_hybrid_mesh, set_mesh

    cfg_sp = llama.LlamaConfig.tiny(sequence_parallel=True)
    params = llama.init_params(cfg_sp, jax.random.PRNGKey(3))
    toks = jnp.array(
        np.random.RandomState(0).randint(0, cfg_sp.vocab_size, (4, 64)),
        jnp.int32)
    mesh = create_hybrid_mesh(dp=2, mp=2, sep=2, devices=jax.devices()[:8])
    try:
        ps = {k: NamedSharding(mesh, v)
              for k, v in llama.param_specs(cfg_sp).items()}
        params_s = jax.device_put(params, ps)
        toks_s = jax.device_put(
            toks, NamedSharding(mesh, P(("dp", "sharding"), None)))
        fwd = jax.jit(lambda p, t: llama.forward(p, t, cfg_sp))
        hlo = fwd.lower(params_s, toks_s).as_text()
        assert "collective_permute" in hlo, "ring attention not dispatched"
    finally:
        set_mesh(None)


@pytest.mark.slow
def test_sequence_parallel_uses_ring_attention_with_parity():
    """With sequence_parallel=True and a sep>1 mesh, the flagship model's
    attention is the RING (context-parallel) formulation; forward and
    gradients match the single-device reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.models import llama
    from paddle_tpu.parallel import create_hybrid_mesh, set_mesh

    cfg_sp = llama.LlamaConfig.tiny(sequence_parallel=True)
    cfg_ref = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg_sp, jax.random.PRNGKey(3))
    toks = jnp.array(
        np.random.RandomState(0).randint(0, cfg_sp.vocab_size, (4, 64)),
        jnp.int32)

    set_mesh(None)
    ref = llama.forward(params, toks, cfg_ref)
    g_ref = jax.grad(lambda p: llama.loss_fn(p, toks, toks, cfg_ref))(params)

    mesh = create_hybrid_mesh(dp=2, mp=2, sep=2, devices=jax.devices()[:8])
    try:
        ps = {k: NamedSharding(mesh, v)
              for k, v in llama.param_specs(cfg_sp).items()}
        params_s = jax.device_put(params, ps)
        toks_s = jax.device_put(
            toks, NamedSharding(mesh, P(("dp", "sharding"), None)))
        fwd = jax.jit(lambda p, t: llama.forward(p, t, cfg_sp))
        # pin the dispatch: the ring lowers to collective-permute over sep
        hlo = fwd.lower(params_s, toks_s).compile().as_text()
        assert "collective-permute" in hlo, "ring attention not dispatched"
        out = fwd(params_s, toks_s)
        assert float(jnp.max(jnp.abs(
            out.astype(jnp.float32) - ref.astype(jnp.float32)))) < 1e-4
        g_sp = jax.jit(jax.grad(
            lambda p, t: llama.loss_fn(p, t, t, cfg_sp)))(params_s, toks_s)
        for a, b in zip(jax.tree.leaves(g_sp), jax.tree.leaves(g_ref)):
            assert float(jnp.max(jnp.abs(
                a.astype(jnp.float32) - b.astype(jnp.float32)))) < 1e-4
    finally:
        set_mesh(None)

"""``paddle.distributed.utils`` (reference:
``python/paddle/distributed/utils/``): MoE token-exchange primitives
(``global_scatter``/``global_gather``, the python surface of the
reference's ``global_scatter/gather`` collective ops).

TPU-native contract: the ragged token exchange is an all-to-all INSIDE
the MoE layer's shard_map program (see
``paddle_tpu.incubate.distributed.models.moe``) — eager top-level calls
are world-of-one identities, and multi-rank eager use raises the same
launch-runtime error as eager send/recv (the SPMD single controller has
no per-rank eager processes)."""

from __future__ import annotations

from ..enforce import InvalidArgumentError
from .collective import get_default_group

__all__ = ["global_scatter", "global_gather"]


def _world_of_one_or_raise(name, group):
    g = group or get_default_group()
    if g.nranks == 1:
        return True
    raise InvalidArgumentError(
        f"eager {name} across ranks is not supported: the ragged MoE "
        "token exchange is an all-to-all inside the MoE layer's shard_map "
        "program, and cross-process eager exchange needs the launch "
        "runtime (python -m paddle_tpu.distributed.launch)")


def global_scatter(x, local_count, global_count, group=None):
    """Send ``local_count[i*ne+j]`` rows of ``x`` to expert j of rank i
    (reference ``global_scatter``). See the module contract above."""
    _world_of_one_or_raise("global_scatter", group)
    return x


def global_gather(x, local_count, global_count, group=None):
    """Inverse of ``global_scatter`` (reference ``global_gather``)."""
    _world_of_one_or_raise("global_gather", group)
    return x

"""Host-sync detector — the GradScaler bug class, made un-reintroducible.

Every perf round so far found at least one hidden device→host sync in a
hot loop (r8: per-param ``bool()`` in ``GradScaler.unscale_`` cost ~161
blocking round trips per ResNet step; r7: stray ``.item()`` polls in
early scheduler drafts). A sync is invisible in the jaxpr — it happens in
HOST code between dispatches — so the static HLO passes can't see it.
This module instruments the coercion surface instead:

* framework ``Tensor`` coercions (``__bool__``/``item()``/``numpy()``/
  ``__array__``/``__float__``/``__int__``) via the audit hook
  ``core.tensor`` exposes (zero overhead when no audit is active);
* raw ``jax.Array`` coercions and ``jax.device_get`` via context-scoped
  patches (serving fetches its event log through ``device_get``, never
  through a framework Tensor).

``allowed_sync(label)`` marks a region whose syncs are INTENDED — the
per-segment event fetch in ``ServingEngine.run_segment``, the single
fused finite-check in ``GradScaler.unscale_``. The audit separates
allowed from flagged events; budgets pin allowed labels to exact counts
and flagged syncs to zero.
"""

from __future__ import annotations

import contextlib
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["SyncEvent", "SyncAudit", "allowed_sync", "audit_active"]


class _TLS(threading.local):
    def __init__(self):
        self.allowed: List[str] = []   # stack of allowed-sync labels
        self.suppress = False          # one sync = one event (bool -> item)


_tls = _TLS()
_AUDITS: List["SyncAudit"] = []       # active audit stack (outermost first)


def audit_active() -> bool:
    return bool(_AUDITS)


@dataclass
class SyncEvent:
    kind: str                 # 'tensor.bool', 'array.item', 'device_get', ...
    site: str                 # "file.py:123 in fn" — first non-framework frame
    label: Optional[str]      # allowed-sync label, None = flagged
    phase: Optional[str]      # audit phase active when it fired (replay tag)
    nbytes: int = 0           # payload when known (0 when not)
    stack: List[str] = field(default_factory=list)

    @property
    def flagged(self) -> bool:
        return self.label is None


_SKIP_FRAMES = ("paddle_tpu/analysis/", "paddle_tpu/core/tensor.py",
                "contextlib.py", "threading.py")


def _call_site() -> tuple:
    """(site, short-stack) of the user code that forced the sync."""
    frames = traceback.extract_stack()[:-3]  # drop notify/_record/ourselves
    stack = [f"{f.filename}:{f.lineno} in {f.name}" for f in frames[-8:]]
    for f in reversed(frames):
        if not any(s in f.filename for s in _SKIP_FRAMES):
            return f"{f.filename}:{f.lineno} in {f.name}", stack
    return stack[-1] if stack else "<unknown>", stack


def _leaf_bytes(value: Any) -> int:
    try:
        import jax

        return sum(int(l.size) * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(value)
                   if hasattr(l, "dtype"))
    except Exception:
        return 0


def _notify(kind: str, value: Any = None) -> None:
    """Record one device→host sync on every active audit."""
    if not _AUDITS or _tls.suppress:
        return
    site, stack = _call_site()
    label = _tls.allowed[-1] if _tls.allowed else None
    nbytes = _leaf_bytes(value) if value is not None else 0
    for audit in _AUDITS:
        audit._record(SyncEvent(kind=kind, site=site, label=label,
                                phase=audit.phase, nbytes=nbytes,
                                stack=stack))


@contextlib.contextmanager
def _sync_scope(kind: str, value: Any = None):
    """Notify once, then suppress nested notifications for the duration
    (``Tensor.__bool__`` → ``item()`` → ``ArrayImpl.__array__`` is ONE
    sync, not three)."""
    _notify(kind, value)
    saved = _tls.suppress
    _tls.suppress = True
    try:
        yield
    finally:
        _tls.suppress = saved


class allowed_sync:
    """Mark the enclosed region's syncs as intended, under ``label``.

    Used by the framework at its sanctioned hot-loop sync points
    (serving's per-segment event fetch, AMP's fused finite check) and by
    user code to whitelist its own fetches. Cheap enough for hot loops:
    two list ops, audit or no audit."""

    __slots__ = ("label",)

    def __init__(self, label: str):
        self.label = label

    def __enter__(self):
        _tls.allowed.append(self.label)
        return self

    def __exit__(self, *exc):
        _tls.allowed.pop()
        return False


class SyncAudit:
    """Context manager collecting every device→host sync in scope.

    ``phase`` tags let a caller separate warmup from the measured replay::

        with SyncAudit() as audit:
            audit.phase = "warm"
            step(x, y)              # compiles + first syncs — not judged
            audit.phase = "replay"
            step(x, y)
        flagged = audit.flagged("replay")
    """

    def __init__(self):
        self.events: List[SyncEvent] = []
        self.phase: Optional[str] = None

    # -- collection --------------------------------------------------------
    def _record(self, ev: SyncEvent) -> None:
        self.events.append(ev)

    def flagged(self, phase: Optional[str] = None) -> List[SyncEvent]:
        return [e for e in self.events if e.flagged
                and (phase is None or e.phase == phase)]

    def allowed(self, phase: Optional[str] = None) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            if e.label is not None and (phase is None or e.phase == phase):
                out[e.label] = out.get(e.label, 0) + 1
        return out

    # -- scope management --------------------------------------------------
    def __enter__(self):
        _AUDITS.append(self)
        if len(_AUDITS) == 1:
            _install_patches()
        return self

    def __exit__(self, *exc):
        _AUDITS.remove(self)
        if not _AUDITS:
            _remove_patches()
        return False


# ---------------------------------------------------------------------------
# Instrumentation: framework Tensors notify through the hook list in
# core.tensor; raw jax arrays (serving's device_get, host int() reads of
# device scalars) need the array type itself wrapped. Patches live only
# while at least one audit is active and are fully restored after.
# ---------------------------------------------------------------------------

_ORIG: Dict[str, Any] = {}


def _wrap_method(cls, name: str, kind: str):
    orig = getattr(cls, name)

    def wrapped(self, *a, **kw):
        _notify(kind, self)
        saved = _tls.suppress
        _tls.suppress = True
        try:
            return orig(self, *a, **kw)
        finally:
            _tls.suppress = saved

    wrapped.__name__ = name
    _ORIG[f"{cls.__name__}.{name}"] = (cls, name, orig)
    setattr(cls, name, wrapped)


def _install_patches() -> None:
    import jax
    from jax._src import array as _jarray

    from ..core import tensor as _tensor

    _tensor._SYNC_AUDIT_HOOK.append(_sync_scope)

    cls = _jarray.ArrayImpl
    try:
        for name, kind in (("__bool__", "array.bool"),
                           ("__int__", "array.int"),
                           ("__float__", "array.float"),
                           ("__index__", "array.index"),
                           ("item", "array.item"),
                           ("__array__", "array.numpy"),
                           ("tolist", "array.tolist")):
            if hasattr(cls, name):
                _wrap_method(cls, name, kind)
    except (AttributeError, TypeError):  # C-extension type: degrade to
        pass                             # Tensor + device_get coverage

    orig_get = jax.device_get

    def device_get(x):
        with _sync_scope("device_get", x):
            return orig_get(x)

    _ORIG["jax.device_get"] = (jax, "device_get", orig_get)
    jax.device_get = device_get


def _remove_patches() -> None:
    from ..core import tensor as _tensor

    if _sync_scope in _tensor._SYNC_AUDIT_HOOK:
        _tensor._SYNC_AUDIT_HOOK.remove(_sync_scope)
    for cls, name, orig in _ORIG.values():
        setattr(cls, name, orig)
    _ORIG.clear()

"""Pooling layers (reference: ``python/paddle/nn/layer/pooling.py``)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "LPPool1D", "LPPool2D",
    "FractionalMaxPool2D", "FractionalMaxPool3D",
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
    "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D",
]


class _PoolND(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.data_format = data_format
        self.kwargs = kwargs

    def _df(self, default):
        return self.data_format or default

    def extra_repr(self):
        return f"kernel_size={self.kernel_size}, stride={self.stride}, padding={self.padding}"


class MaxPool1D(_PoolND):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self._df("NCL"))


class MaxPool2D(_PoolND):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self._df("NCHW"))


class MaxPool3D(_PoolND):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self._df("NCDHW"))


class AvgPool1D(_PoolND):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self._df("NCL"))


class AvgPool2D(_PoolND):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self._df("NCHW"))


class AvgPool3D(_PoolND):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self._df("NCDHW"))


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     data_format=self.data_format)


class _MaxUnPoolNd(Layer):
    _fn = None
    _fmt = ""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format or self._fmt
        self.output_size = output_size

    def forward(self, x, indices):
        return type(self)._fn(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool1D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool1d)
    _fmt = "NCL"


class MaxUnPool2D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool2d)
    _fmt = "NCHW"


class MaxUnPool3D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool3d)
    _fmt = "NCDHW"


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        return F.lp_pool2d(x, *self.args)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self.args)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool3d(x, *self.args)

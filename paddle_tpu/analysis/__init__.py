"""``paddle_tpu.analysis`` — static analysis of traced programs with
enforced TPU-hazard budgets (ISSUE 4 tentpole).

Five passes over any jit-compiled callable or registered canonical
program:

1. **host-sync detector** (``syncs``) — instruments the ``Tensor`` /
   ``jax.Array`` coercion surface under an audit context; flags any
   device→host sync in a warm hot loop that is not inside an
   ``allowed_sync`` region (the GradScaler per-param ``bool()`` class).
2. **recompile-hazard lint** (``recompile``) — counts real XLA backend
   compilations during warm replay and lints jit cache keys for
   unbucketed dynamic dims (the 2.5 s mid-serve compile class).
3. **relayout accounting** (``hlo.relayout_inventory``) — materialised
   transpose/copy/reshape + pack traffic bytes from optimized HLO (the
   r8 255.5→153.3 MB/step ledger, automated).
4. **donation/aliasing audit** (``hlo.donation_report``) — large entry
   parameters that neither donate nor alias (HBM-peak class).
5. **collective/mesh audit** (``hlo.collective_check``) — every
   collective must attribute to a declared mesh-axis subset (the
   promoted ``benchmarks/collective_audit`` pass).

``budgets`` pins per-program ceilings; ``python -m paddle_tpu.analysis
--gate`` audits the registered canonical programs (``programs`` — six
as of r12, including the mp-sharded ``tp_serving_segment``) and exits
nonzero when any budget regresses — wired into tier-1 so hazards fail
the suite, not the next profiling round.

Quick use::

    from paddle_tpu import analysis

    report = analysis.audit_fn(jitted, x, y)     # any jit callable
    print(report.format())

    report = analysis.audit_program("decode_tick")   # canonical
    violations = analysis.budgets.check(report)
"""

from __future__ import annotations

from . import budgets, hlo, programs, recompile, syncs, tiers
from .auditor import AuditReport, Finding, audit_fn, audit_replay, audit_static
from .recompile import CompileWatch, lint_cache_keys, live_cache_report
from .syncs import SyncAudit, allowed_sync
from .tiers import tier_transfer_audit, tiered_serve_audit

__all__ = [
    "AuditReport", "Finding", "SyncAudit", "allowed_sync", "CompileWatch",
    "lint_cache_keys", "live_cache_report", "audit_fn", "audit_replay",
    "audit_static", "audit_program", "budgets", "hlo", "programs",
    "recompile", "syncs", "tiers", "tier_transfer_audit",
    "tiered_serve_audit",
]


def audit_program(name: str, replays: int = 2) -> AuditReport:
    """Build + audit one canonical program (static + dynamic passes)."""
    handle = programs.build(name)
    rep = audit_static(name, handle.hlo(), mesh=handle.mesh,
                       donation_threshold=handle.donation_threshold,
                       expected_undonated=handle.expected_undonated,
                       allowed_axes=handle.allowed_axes)
    rep.merge(audit_replay(name, handle.replay, replays=replays))
    return rep

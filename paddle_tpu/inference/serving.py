"""Continuous-batching generation engine (serving-shaped decode).

Reference counterpart: Paddle Inference / PaddleNLP's serving stack
(SURVEY.md §2.1 inference row: dynamic batching over the KV cache). The
reference's GPU serving engines (and vLLM-style systems) keep a fixed pool
of decode slots and swap finished requests out for queued ones so the
batch stays full — that scheduling idea, TPU-native:

* **The whole drain is ONE compiled program** (r5, ``run()``'s default;
  see ``_drain_prog``): slot state lives on device and a ``while_loop``
  alternates admit (prefill inside a ``lax.cond`` branch) and decode
  ticks. Admission costs no host round trip, so refill is greedy; the
  host pays one dispatch + one fetch per drain, making throughput AND
  latency independent of dispatch cost (measured 2.6-2.9x fixed
  batching wall-clock even through a ~30 ms/dispatch tunnel).
* **Fixed-shape compiled programs.** Decode is a ragged tick over all
  slots with per-slot positions (every slot attends and writes at its
  own ``pos`` — ``llama.forward_with_cache``'s ragged path) and per-slot
  REMAINING counts: a slot freezes in-program the step its request
  completes. Shapes never depend on request sizes — nothing recompiles
  as requests come and go.
* **Windowed incremental mode** (``run(fused=False)``): for serving on
  top of an already-partial slot state — wave-batched bucketed
  admission, decode chunks chained via async dispatch, host reads
  batched into one ``device_get`` per admission window.
* **Slot-contiguous (ragged) cache, not paged.** Each slot owns rows
  [0, max_len) of the shared [L, slots, max_len, H, D] cache. Paging adds
  an indirection XLA can't fuse well; at serving's typical length spread
  the ragged layout wins on TPU (documented trade-off vs the reference's
  paged pools). r6: the decode tick's attention READS are ragged too —
  the Pallas kernel (`ops/pallas/decode_attention.py`) fetches only KV
  blocks [0, pos] per slot instead of the full max_len window, and the
  tick's between-matmul small-op chains run as fused Pallas epilogue
  ops (`ops/pallas/tick_fusion.py`); both dispatch inside
  ``llama.forward_with_cache`` so every path here (windowed chunks and
  the fused drain's decode branch) picks them up (SCALING.md §3c).

Greedy decoding (temperature 0) — matching ``llama.generate``'s default —
so engine output is bit-comparable to the dense path request-by-request.
``eos_token_id`` freezes a slot in-program the step EOS is emitted.

r15 (ISSUE 10): **speculative + sampled decoding inside the segment
program**. ``ServingEngine(speculative=K)`` drafts K tokens per live
slot from the slot's page-resident token history (in-program n-gram
lookup) and verifies all K+1 positions in ONE tick through the paged
q_len>1 path — accepted-length > 1 tokens per weight stream, the lever
that beats the HBM decode roofline (SCALING §3j). ``sampling=
{"temperature", "top_k", "top_p"}`` samples in-program with per-slot
RNG keys carried in segment state, seeded per request (deterministic
replay); greedy stays the default and bit-identical. Both ride the
``("sseg", n_pad, K, steps)`` program family and keep the audited
one-dispatch/one-fetch contract — acceptance counts travel in the same
event fetch and the host replay recovers per-request accepted lengths.
"""

from __future__ import annotations

import contextlib
import functools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.syncs import allowed_sync
from ..models import llama
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability import metrics as _metrics
from .program_space import PROGRAM_SPACE, WorkloadEnvelope, chunk_for

__all__ = ["Request", "ServingEngine", "SEGMENT_HOOKS", "PROGRAM_SPACE",
           "WorkloadEnvelope"]

# Process-wide segment observers (r14, ISSUE 9): ``fn(steps, new_tokens,
# n_finished)`` called from ``_segment_telemetry`` after every segment's
# host replay — host ints only, so a hook can never add a device sync.
# ``observability.slo.install`` / ``observability.perf.install`` use this
# to attach the SLO monitor and the explained-perf interval accumulator
# to ANY engine (the analysis gate's --ops mode rides it: the canonical
# serving programs replay through run_segment with no scheduler in the
# loop, and the monitors must still see every segment). Empty by
# default — the common case costs one truthiness check per segment.
SEGMENT_HOOKS: List = []


@contextlib.contextmanager
def _mesh_scope(mesh):
    """Make ``mesh`` the global mesh for the duration of a program
    build/call (r12 tensor-parallel serving): the model's sharding
    constraints (``with_sharding_constraint``) read the global mesh at
    TRACE time, so an mp-sharded engine must trace its segment programs
    under its own mesh without leaking it into unrelated callers (tests
    and sibling engines pin ``set_mesh(None)``)."""
    if mesh is None:
        yield
        return
    from ..parallel.mesh import get_mesh, set_mesh

    prev = get_mesh()
    set_mesh(mesh)
    try:
        yield
    finally:
        set_mesh(prev)

_WAVE_WIDTHS = (8, 4, 2, 1)  # compiled prefill sub-batch sizes


# --- in-program sampling primitives (r15, ISSUE 10) -----------------------
# Per-slot RNG state rides the segment as RAW uint32 [slots, 2] key data
# (threefry): raw keys scatter/donate like any int array, so the while-
# body carries stay trivial. All of these run INSIDE compiled programs.

def _split_rows(raw):
    """Advance each row's key: (next_state [n,2], consume [n,2])."""
    nk = jax.vmap(jax.random.split)(raw)
    return nk[:, 0], nk[:, 1]


def _subkeys_rows(raw, n: int):
    """n consumable subkeys per row: [rows, n, 2]."""
    return jax.vmap(lambda k: jax.random.split(k, n))(raw)


def _categorical_rows(filt, keys):
    """One independent categorical draw per row: ``filt`` [..., V]
    filtered logits, ``keys`` [..., 2] raw key per row."""
    V = filt.shape[-1]
    toks = jax.vmap(jax.random.categorical)(
        keys.reshape(-1, 2), filt.reshape(-1, V))
    return toks.reshape(filt.shape[:-1]).astype(jnp.int32)


def _uniform_rows(keys):
    """One uniform [0,1) draw per row key: keys [..., 2] -> [...]."""
    u = jax.vmap(lambda k: jax.random.uniform(k))(keys.reshape(-1, 2))
    return u.reshape(keys.shape[:-1])


@dataclass
class _PendingSegment:
    """A dispatched-but-not-fetched segment (r12): the device futures of
    one fused segment plus the host bookkeeping its replay needs. The
    fleet router dispatches one of these per replica and only then
    fetches them in turn — replica i+1's device work overlaps replica
    i's fetch wait, with the per-segment sync contract intact (each
    finish is still exactly one ``allowed_sync`` event fetch)."""
    paged: bool
    picked: List["Request"]
    n: int
    now: float
    prefix_cache: object
    dev: tuple                     # (out, aq, aslot, step, qidx) futures
    pre_lens: object               # [n] reused-prefix rows per request
    req_pages: Optional[List[List[int]]] = None   # paged reservations
    # r13: admission-time context per request. full_prompts[j] is the
    # tokens the admit actually prefills — prompt + any tokens already
    # generated before a preemption/failover requeue (the RESUME view);
    # the prefix-cache population after the sync must harvest THIS
    # span, not the original prompt. chunk_marker is the aq value the
    # chunked program logs for a non-final prefill-chunk step (the host
    # replay skips those steps — no decode happened on them).
    full_prompts: Optional[List[np.ndarray]] = None
    chunk_marker: Optional[int] = None
    # r15: True when the segment ran the speculative/sampled program —
    # its event log carries [steps, slots, K+1] token matrices plus the
    # per-step accepted counts the host replay distributes
    spec: bool = False
    # r17: True when the segment ran the quality-digest program — its
    # event log additionally carries per-step per-slot logit digests
    # (emitted logit + top-k ids/values) in the same fetch
    digest: bool = False
    # r23: True when the segment ran the sequence-parallel long-context
    # program — its event log additionally carries the pf/pfq/pfo
    # prefill-progress state (a long prefill may span segments; the
    # host keeps its page reservation and resumes it next dispatch)
    sp: bool = False


@dataclass
class Request:
    rid: int
    prompt: np.ndarray            # [S] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    submit_time: float = 0.0      # perf_counter at add_request
    finish_time: float = 0.0      # perf_counter at retirement
    # online-serving measured lifecycle (perf_counter; 0.0 = not yet):
    # these are HOST-OBSERVED times — a token "exists" for a client only
    # once a device->host sync delivered it, so first_token/finish are
    # stamped at the segment sync that surfaced them (r7: the measured
    # replacement for r5's uniform-step latency model)
    arrival_time: float = 0.0     # entered the system (arrival process)
    admit_time: float = 0.0       # packed into a slot (prefill dispatched)
    first_token_time: float = 0.0  # first generated token host-visible
    prefix_hit_len: int = 0       # KV rows reused from the prefix cache
    # r13 SLO-aware serving: smaller priority = more important (class 0
    # outranks class 1); deadline is an ABSOLUTE perf_counter e2e
    # deadline (0.0 = none — the request is never shed). preemptions /
    # requeues count how often this request lost its slot (priority
    # preemption) or its replica (fleet failover); generated tokens
    # survive either — re-admission resumes from prompt + tokens.
    priority: int = 0
    deadline: float = 0.0
    preemptions: int = 0
    requeues: int = 0
    # r15 speculative + sampled decoding (ISSUE 10): per-request sampling
    # seed (only consumed when the engine has a sampling config — the
    # slot's in-program RNG stream is derived from it at every admission,
    # folded with len(tokens) so a resume continues deterministically),
    # and the speculative draft ledger the host replay recovers from the
    # event log: drafts proposed for / accepted by this request (the
    # per-request acceptance rate the benchmark histograms by prompt
    # class).
    seed: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    # r17 quality digests (ISSUE 12): one (emitted_logit, top-k ids,
    # top-k values) triple per emitted token, recovered by the host
    # replay from the same single audited event fetch — None unless the
    # engine runs with quality_digest=True. The shadow-diff monitor
    # compares these across a primary/shadow pair.
    digests: Optional[List[tuple]] = None
    # r18 capacity meter (ISSUE 13): host-stamped resource attribution,
    # always on (a perf_counter read + int arithmetic per event — the
    # stamps are telemetry, never decision inputs, so they stay off the
    # journal clock). pages_reserved = span of the latest reservation
    # (persists after release — the request's §3f page footprint);
    # pages_fresh = the non-shared subset (what admission drew from the
    # free list); page_seconds accumulates held-pages x wall at every
    # release point (retire / requeue / preempt / abort), across
    # resume cycles. meter_ticks counts the weight streams the request
    # was live for (admit prefill + decode/verify ticks);
    # meter_streams its FAIR share (1/live per tick) — summed over a
    # serve the shares tile the segment steps exactly, the identity
    # tests/test_capacity.py pins. capacity.attribute_request joins
    # these with the §3c ledger into bytes/FLOPs.
    pages_reserved: int = 0
    pages_fresh: int = 0
    page_seconds: float = 0.0
    meter_ticks: int = 0
    meter_streams: float = 0.0
    # r19 tiered KV (ISSUE 14): tier traffic billed to THIS request —
    # pages/bytes promoted from the host tier (restore-on-hit) or
    # imported cross-replica for its admission. analysis.tiers enforces
    # tier_bytes <= the request's own KV size (pages_reserved x page
    # bytes): a memory tier must never move more than it saves.
    tier_pages: int = 0
    tier_bytes: int = 0
    _pages_live: int = 0          # currently-held pages (meter internal)
    _pages_t0: float = 0.0        # holding-interval open stamp

    def _meter_reserve(self, pages: int, fresh: int) -> None:
        self.pages_reserved = pages
        self.pages_fresh = fresh
        self._pages_live = pages
        self._pages_t0 = time.perf_counter()

    def _meter_release(self) -> None:
        """Close the open page-holding interval (idempotent)."""
        if self._pages_live:
            self.page_seconds += self._pages_live * (
                time.perf_counter() - self._pages_t0)
            self._pages_live = 0

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens

    def resume_view(self):
        """(tokens to prefill, generations still owed) for admission.
        Fresh requests prefill their prompt; a preempted / failed-over
        request resumes from prompt + everything already generated —
        greedy decode makes the continuation token-identical to an
        uninterrupted run, and the concatenated view lets the prefix
        cache serve the request's own harvested pages back to it (a
        resume is then a page-ref bump + suffix prefill)."""
        if not self.tokens:
            return self.prompt, self.max_new_tokens
        full = np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])
        return full, self.max_new_tokens - len(self.tokens)


# Process-wide compiled-program cache (r12): every program an engine
# builds (admit / decode / drain / segment / paged segment) closes over
# NOTHING but config scalars (cfg, slots, max_len, eos, chunk, mesh) —
# params and caches are arguments — so engines with identical geometry
# can share one jitted callable. A fleet of N identical replicas then
# compiles each segment shape ONCE per process instead of N times
# (compile cost is per binary, not per replica — the ROADMAP item 5
# direction), and the test suite's many tiny engines stop re-compiling
# the same programs per test. Keys hold no arrays; the cache pins only
# XLA executables.
_SHARED_PROGS: Dict[tuple, object] = {}


class ServingEngine:
    def __init__(self, cfg: llama.LlamaConfig, params, slots: int = 8,
                 max_len: Optional[int] = None, chunk: int = 32,
                 prompt_buckets: Sequence[int] = (32, 64, 128, 256),
                 eos_token_id: Optional[int] = None,
                 paged: bool = False, page_size: int = 16,
                 num_pages: Optional[int] = None, mesh=None,
                 chunked_prefill: bool = False,
                 prefill_chunks: Sequence[int] = (8, 16, 32, 64),
                 speculative: int = 0,
                 sampling: Optional[dict] = None,
                 sample_seed: int = 0,
                 quality_digest: bool = False,
                 digest_top_k: int = 4,
                 quant: Optional[str] = None,
                 seq_parallel: int = 0,
                 long_buckets: Sequence[int] = ()):
        self.cfg = cfg
        self.params = params
        self.slots = int(slots)
        # r12 tensor-parallel serving: an 'mp' mesh shards the weights
        # (llama.param_specs — Megatron column/row-parallel) and the KV
        # store on the head dim; a model bigger than one chip's HBM then
        # serves through the SAME one-dispatch/one-fetch segment programs
        # (GSPMD inserts one all-reduce per layer after the row-parallel
        # projections). Serving is segment-only under a mesh (run() and
        # warmup() route accordingly); slot bookkeeping stays host-side
        # and mesh-oblivious.
        self.mesh = mesh
        if mesh is not None:
            mp = int(mesh.shape.get("mp", 1))
            if mp > 1 and (cfg.num_kv_heads % mp or cfg.num_heads % mp):
                raise ValueError(
                    f"num_heads {cfg.num_heads} / num_kv_heads "
                    f"{cfg.num_kv_heads} must divide the mp degree {mp} "
                    f"(the KV cache shards on the head dim)")
            self.params = llama.shard_state(cfg, mesh, params)
        self.max_len = int(max_len or cfg.max_seq_len)
        self.chunk = int(chunk)
        self.buckets = tuple(sorted(int(b) for b in prompt_buckets
                                    if b <= self.max_len))
        if not self.buckets:
            raise ValueError("no prompt bucket fits max_len")
        self.eos = eos_token_id
        self._progs: Dict[tuple, object] = {}  # (bucket, nb) -> admit fn
        self._queue: List[Request] = []
        self._active: List[Optional[Request]] = [None] * self.slots
        self._rem_host = [0] * self.slots  # host mirror of remaining counts
        self._finished: List[Request] = []
        self.last_run_chunks = 0  # decode chunks issued by the last run()
        self.last_run_ticks = 0   # decode TICKS (fused: exact; windowed: chunks*K)
        self.last_latencies = {}  # rid -> submit->finish seconds (last run)
        self._next_rid = 0
        self.paged = bool(paged)
        self.page_backpressure_events = 0  # admissions deferred for pages
        # r13 chunked prefill (ISSUE 8): split each admitted prompt into
        # fixed-width chunks interleaved with decode ticks INSIDE the
        # paged segment program, bounding time-between-tokens for
        # co-resident decodes by one chunk's cost instead of a whole
        # prefill. Chunk widths come from the small DECLARED ladder so
        # program cache keys stay bucketed (a floating chunk width would
        # be the 2.5 s mid-serve XLA-compile class all over again).
        self.chunked = bool(chunked_prefill)
        if self.chunked and not self.paged:
            raise ValueError(
                "chunked_prefill requires paged=True (chunks prefill at "
                "a context offset through the page tables; the "
                "contiguous admit branch stages whole windows)")
        self.prefill_chunks = tuple(sorted(int(c) for c in prefill_chunks))
        if self.chunked and not self.prefill_chunks:
            raise ValueError("chunked_prefill needs a non-empty "
                             "prefill_chunks ladder")
        # r15 speculative + sampled decoding (ISSUE 10; ROADMAP item 3).
        # ``speculative=K``: each decode step drafts K tokens per live
        # slot from the slot's own resident token history (an in-program
        # n-gram/prompt-suffix lookup — no draft model, no host contact)
        # and the target model VERIFIES all K+1 positions in one batched
        # tick through the paged q_len>1 path — accepted-length > 1 per
        # weight stream is the only lever that beats the decode HBM
        # roofline (SCALING §3c/§3j). ``sampling`` = {"temperature",
        # "top_k", "top_p"}: per-slot threaded RNG keys carried in
        # segment state, seeded per request so serves replay
        # deterministically. temperature 0 normalises to None so the
        # default greedy path compiles the EXACT argmax programs
        # (bit-identical, budget-identical).
        self.speculative = int(speculative)
        if self.speculative < 0:
            raise ValueError(f"speculative draft length must be >= 0, "
                             f"got {speculative}")
        samp = None
        if sampling:
            t = float(sampling.get("temperature", 1.0))
            if t < 0.0:
                raise ValueError(f"temperature must be >= 0, got {t}")
            if t > 0.0:
                samp = (t, int(sampling.get("top_k", 0)),
                        float(sampling.get("top_p", 1.0)))
        self.sampling = samp
        self.sample_seed = int(sample_seed)
        if (self.speculative or self.sampling) and not self.paged:
            raise ValueError(
                "speculative/sampled decoding requires paged=True (the "
                "verify tick reuses the page-indirect q_len>1 path and "
                "the RNG/history state rides the paged segment family)")
        # r17 quality digests (ISSUE 12): per-emitted-token logit
        # evidence computed IN-PROGRAM and rolled into the segment event
        # log — the emitted token's logit plus the top-k (ids, values)
        # of the tick's distribution — riding the SAME single audited
        # per-segment fetch. This is the raw material shadow-diff
        # quality monitoring (observability/quality.py) compares across
        # a primary/shadow engine pair: token divergence localises to an
        # exact position, and logit-error budgets (max |Δ|, sampled KL)
        # quantify "how different" below the token-flip threshold.
        # Digests ride the plain paged segment family only — chunked /
        # speculative / sampled variants diff at TOKEN level (their
        # event logs already carry the emitted stream); a digest there
        # would multiply the log width for no extra diff power on the
        # greedy chains they emit.
        self.quality_digest = bool(quality_digest)
        self.digest_top_k = int(digest_top_k)
        if self.quality_digest:
            if not self.paged:
                raise ValueError(
                    "quality_digest requires paged=True (digests extend "
                    "the paged segment event log; the contiguous "
                    "engine's windowed path has no single event fetch "
                    "to ride)")
            if self.chunked or self.speculative or self.sampling:
                raise ValueError(
                    "quality_digest composes with the plain paged "
                    "segment only — chunked/speculative/sampled "
                    "variants are shadow-diffed at token level")
            if self.digest_top_k < 1:
                raise ValueError(f"digest_top_k must be >= 1, got "
                                 f"{digest_top_k}")
        # r21 quantized serving (ISSUE 16): ``quant`` = "int8" | "fp8"
        # shrinks the decode tick's HBM stream — the LAST roofline lever
        # after r15 speculation multiplied tokens per stream. Weights
        # re-quantize at build (per-output-channel scales ride the param
        # tree as ``<name>_scale`` companions; dequant happens in-kernel
        # on the TPU path, adjacent-to-dot on the dense fallback) and
        # the KV pool carries the narrow dtype with per-page scale
        # planes ("ks"/"vs") keyed by physical page id — COW, refcounts
        # and the host tier treat pages dtype-obliviously, so prefix
        # sharing and r19 spill survive unchanged. Paged-only: the
        # quantized programs are a new DTYPE AXIS on the paged segment
        # family ("qpseg"), enumerated and AOT-warmed like every other
        # rung. Composes with quality_digest (the shadow-diff quality
        # bar that certifies the rollout); mesh / chunked / speculative
        # / sampled combos are rejected until they earn their own
        # certification.
        self.quant = str(quant) if quant else None
        if self.quant:
            from ..quantization.serving import (QUANT_MODES,
                                                quantize_llama_params)

            if self.quant not in QUANT_MODES:
                raise ValueError(f"quant must be one of {QUANT_MODES}, "
                                 f"got {quant!r}")
            if not self.paged:
                raise ValueError(
                    "quant requires paged=True (per-page KV scales ride "
                    "the paged pool's fixed tiles; the contiguous cache "
                    "has no page axis to key them on)")
            if mesh is not None:
                raise ValueError(
                    "quant under a mesh is not supported — the scale "
                    "companions would need their own param_specs entry "
                    "before the sharded dequant is certified")
            if self.chunked or self.speculative or self.sampling:
                raise ValueError(
                    "quant composes with the plain paged segment (and "
                    "quality_digest) only — chunked/speculative/sampled "
                    "variants need their own shadow certification")
            self.params = quantize_llama_params(self.params, cfg,
                                                self.quant)
        # r23 long-context serving (ISSUE 18): ``seq_parallel=sp`` adds
        # the sequence-parallel prefill family ("spseg") — prompts past
        # the largest REGULAR bucket admit through sp-wide prefill
        # SLABS (sp chunks of C tokens, the batch axis carrying the
        # shard axis: under an 'sp' mesh each chunk runs on its own
        # devices; without one the slab is a plain batched call with
        # bit-identical math). Every slab row scatters its KV slice
        # straight into the SHARED paged pool through the request's own
        # page-table row, so decode proceeds on the ordinary
        # page-indirect path with zero relayout at the prefill->decode
        # boundary. ``long_buckets`` is the declared LONG prompt rung
        # ladder (all rungs >= the largest regular bucket): intake for
        # long prompts caps at its top, and the spseg key family
        # enumerates over its rungs so the AOT warmup covers every
        # reachable slab width. A long prefill may SPAN segments (the
        # in-program pf/pfq/pfo progress state rides the single event
        # fetch out and back); its page reservation is taken ONCE at
        # first admission and HELD across the spanned segments (the
        # SCALING §3f multi-segment reservation extension — the r19
        # host tier is the pressure valve when one prompt's KV rivals
        # the pool). sp=1 degenerates exactly: regular traffic never
        # engages the family, so program keys and journal streams match
        # the plain paged engine byte for byte.
        self.seq_parallel = int(seq_parallel or 0)
        self.long_buckets = tuple(sorted(int(b) for b in long_buckets))
        if self.seq_parallel < 0:
            raise ValueError(f"seq_parallel must be >= 0, got "
                             f"{seq_parallel}")
        if self.seq_parallel:
            if not self.paged:
                raise ValueError(
                    "seq_parallel requires paged=True (prefill shards "
                    "scatter into the shared paged pool; the contiguous "
                    "cache has no page indirection to land them in)")
            if self.speculative or self.sampling or self.quality_digest \
                    or self.quant:
                raise ValueError(
                    "seq_parallel composes with the plain/chunked paged "
                    "segment only — speculative/sampled/digest/quant "
                    "variants need their own certification")
            if not self.long_buckets:
                raise ValueError("seq_parallel needs a non-empty "
                                 "long_buckets rung ladder")
            if self.long_buckets[0] < max(self.buckets):
                raise ValueError(
                    f"every long bucket must be >= the largest regular "
                    f"bucket {max(self.buckets)} (got "
                    f"{self.long_buckets[0]} — regular traffic rides "
                    f"the ordinary pseg/cseg families)")
            if self.long_buckets[-1] > self.max_len:
                raise ValueError(
                    f"long bucket {self.long_buckets[-1]} exceeds "
                    f"max_len {self.max_len}")
        # rid -> {"pages", "resident"} for long prefills spanning
        # segments: the reservation taken at first admission plus how
        # many KV rows (prefix hit + slabs landed so far) are already
        # resident in the pool — the next dispatch resumes the prefill
        # at that offset with the SAME pages
        self._sp_inflight: Dict[int, dict] = {}
        # acceptance EWMA (emitted tokens per verify tick, >= 1): the
        # SLO scheduler threads this through its deadline and
        # retry_after_s estimates so speculative serves don't over-shed
        # (each tick retires accept_ewma tokens, not one)
        self.spec_accept_ewma = 1.0
        if self.paged:
            # paged mode (r11, inference/paged_kv.py): ONE flat page pool
            # + per-slot page tables replace the [slots, max_len] block.
            # max_len keeps its meaning as the PER-SLOT virtual cap
            # (max_pages * page_size); num_pages sizes the PHYSICAL pool
            # — below slots * max_pages it is the pages-free admission
            # regime the contiguous cache cannot express.
            from .paged_kv import PagedKVCache

            self.page_size = int(page_size)
            if self.max_len % self.page_size:
                raise ValueError(f"max_len {self.max_len} is not a "
                                 f"multiple of page_size {self.page_size}")
            max_pages = self.max_len // self.page_size
            self.pager = PagedKVCache(
                cfg, self.slots, self.page_size,
                num_pages=int(num_pages or self.slots * max_pages + 1),
                max_pages=max_pages, mesh=mesh, quant=self.quant)
            self._cache = None  # no contiguous block exists in paged mode
        else:
            self.pager = None
            self._cache = llama.init_kv_cache(cfg, self.slots, self.max_len)
            if mesh is not None:
                from jax.sharding import NamedSharding

                self._cache = jax.device_put(
                    self._cache,
                    NamedSharding(mesh, llama.kv_cache_spec()))
        self._pos = self._slot_vec()
        self._nxt = self._slot_vec()
        self._rem = self._slot_vec()
        self._init_spec_state()
        self._pending_seg = None  # at most ONE in-flight dispatched segment
        # r14 cold-start metric (ISSUE 9 satellite; ROADMAP item 5's
        # first deliverable): build→first-emitted-token wall time, the
        # number autoscaling/rollout decisions gate on. Stamped ONCE per
        # engine lifetime at the first host-visible token (the fetch
        # that surfaced it), deliberately spanning the first segment's
        # XLA compile — that compile IS the cold-start cost being
        # measured. reset_slots does not clear it (warm resets are not
        # rebuilds).
        self.built_at = time.perf_counter()
        self.cold_start_s: Optional[float] = None
        # r20 (ISSUE 15): AOT bucket-ladder warmup bookkeeping. When
        # ``aot_warmup`` ran, the cold-start gauge splits into the
        # warmup cost (``aot_warmup_s`` — every enumerated program
        # compiled at build) and ``first_token_s`` (cold_start minus
        # warmup: queue/admit/prefill only, no XLA), the pair the
        # autoscaler's scale-up model sums. ``aot_key_seconds`` holds
        # per-key build+compile seconds (the coverage pass attributes
        # dead ladder entries' cost from it); ``prog_key_hits`` counts
        # post-warmup program-cache accesses (the enumerated-vs-used
        # differential's usage side).
        self.aot_warmup_s: Optional[float] = None
        self.first_token_s: Optional[float] = None
        self.aot_key_seconds: Dict[tuple, float] = {}
        self.prog_key_hits: Dict[tuple, int] = {}
        from ..jit import register_compiled_cache

        register_compiled_cache(self)  # analysis.recompile introspection

    def _slot_vec(self):
        """A zeroed [slots] int32 slot-state vector, replicated over the
        engine's mesh when one is set (slot state is tiny and every
        device needs all of it)."""
        v = jnp.zeros((self.slots,), jnp.int32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            v = jax.device_put(v, NamedSharding(self.mesh, P()))
        return v

    def _slot_arr(self, shape, dtype):
        """Zeroed per-slot state array, replicated over the engine's mesh
        (same contract as ``_slot_vec`` for non-vector shapes: the
        speculative token-history mirror and the per-slot RNG keys)."""
        v = jnp.zeros(shape, dtype)
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            v = jax.device_put(v, NamedSharding(self.mesh, P()))
        return v

    def _init_spec_state(self) -> None:
        """(Re)build the speculative / sampling slot state (r15):

        * ``_hist`` [slots, max_len+1] int32 — the slot's TOKEN history
          mirror of its page-resident KV rows (prompt suffix + every
          verified token): the in-program n-gram draft table. One
          overflow column past max_len absorbs clamped writes so a
          near-capacity slot can never corrupt valid history.
        * ``_hstart`` [slots] — first valid history index (= the shared
          prefix length at admission: prefix TOKENS are not re-staged,
          so the draft scan starts where this slot's own tokens do).
        * ``_rng`` [slots, 2] uint32 — raw per-slot PRNG key state,
          re-seeded from the request's seed at every admission.
        """
        if self.paged and (self.speculative or self.sampling):
            self._hist = self._slot_arr((self.slots, self.max_len + 1),
                                        jnp.int32)
            self._hstart = self._slot_vec()
        else:
            self._hist = self._hstart = None
        if self.paged and self.sampling:
            self._rng = self._slot_arr((self.slots, 2), jnp.uint32)
        else:
            self._rng = None

    def cache_info(self) -> dict:
        """Compiled-program cache keys (analysis.recompile lint): admit
        programs key on (bucket, nb), segments on ("seg", n_pad, s_max,
        pre_max, steps), paged segments on ("pseg", n_pad, s_max, steps),
        chunked paged segments on ("cseg", n_pad, s_max_c, C, steps) with
        C drawn from the declared prefill_chunks ladder, speculative/
        sampled segments on ("sseg", n_pad, K, steps) with the admit
        width PINNED to the largest bucket, quality-digest paged
        segments on ("qseg", n_pad, s_max, steps), quantized paged
        segments on ("qpseg", n_pad, s_max, steps, dtype) with dtype
        drawn from the declared QUANT_CODES, sequence-parallel
        long-context segments on ("spseg", n_pad, s_max, C, sp, steps)
        with s_max a slab-rounded long_buckets rung — all bucketed by
        construction, so key-count growth here means a shape leaked
        past the buckets (the 2.5 s mid-serve compile class this
        engine's width pinning fixed). Note the PAGED keys carry no
        pre_max: shared-prefix geometry rides the page tables as DATA,
        so prefix reuse adds zero program shapes."""
        return {"name": f"serving_engine:slots{self.slots}",
                "keys": list(self._progs.keys())}

    def decode_kernel_active(self) -> bool:
        """True when this engine's decode ticks route to the ragged
        Pallas decode-attention kernel (a trace-time dispatch decision —
        the serving lane's smoke gate asserts it so a selection
        regression fails off-chip)."""
        from ..ops.pallas.decode_attention import decode_attention_active

        return decode_attention_active(self.max_len, self.cfg.num_heads,
                                       self.cfg.num_kv_heads,
                                       self.cfg.head_dim)

    def paged_kernel_active(self) -> bool:
        """True when this engine's paged segments route attention to the
        unified page-indirect Pallas kernel (trace-time dispatch — the
        paged serving lane asserts it like ``decode_kernel_active``)."""
        from ..ops.pallas.paged_attention import paged_attention_active

        # a quantized pool takes the dequantizing gather path instead of
        # the page-indirect kernel (its per-page scales need the
        # gather); the weight stream is where the quant bytes win
        return self.paged and not self.quant and paged_attention_active(
            self.page_size, self.cfg.num_heads, self.cfg.num_kv_heads,
            self.cfg.head_dim)

    def quant_kernel_active(self) -> bool:
        """True when this engine's quantized projection matmuls route to
        the in-kernel-dequant Pallas path (trace-time dispatch — the
        quant serving lane asserts it like ``decode_kernel_active``;
        CPU tier-1 exercises the same kernel through FORCE_INTERPRET)."""
        from ..ops.pallas.tick_fusion import quant_matmul_active

        H = self.cfg.hidden_size
        return bool(self.quant) and quant_matmul_active(H, H)

    # --- request intake ---------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int,
                    seed: Optional[int] = None) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        intake_cap = (max(self.long_buckets) if self.seq_parallel
                      else max(self.buckets))
        if len(prompt) > intake_cap:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"{'long ' if self.seq_parallel else ''}bucket "
                f"{intake_cap}")
        if len(prompt) + max_new_tokens - 1 > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds cache max_len {self.max_len}")
        if self.paged:
            need = self.pager.pages_needed(len(prompt) + max_new_tokens - 1)
            if need > self.pager.num_pages - 1:
                raise ValueError(
                    f"request spans {need} pages but the pool holds only "
                    f"{self.pager.num_pages - 1} — it could never admit")
        rid = self._next_rid
        self._next_rid += 1
        # per-request sampling seed: explicit, or derived from the
        # engine's base seed + rid — either way fixed at intake, so one
        # trace replays its sampled streams identically serve to serve
        self._queue.append(Request(rid, prompt, int(max_new_tokens),
                                   submit_time=time.perf_counter(),
                                   seed=(self.sample_seed + rid
                                         if seed is None else int(seed))))
        return rid

    def _retire(self, r: Request) -> None:
        r.finish_time = time.perf_counter()
        self._finished.append(r)

    # --- compiled programs ------------------------------------------------
    def _shared_key(self, key: tuple) -> tuple:
        """Process-wide program-cache key: the engine geometry every
        program closure reads, plus the per-shape key. Engines agreeing
        on all of it trace byte-identical programs."""
        return (self.cfg, self.slots, self.max_len, self.eos, self.chunk,
                self.paged, self.pager.max_pages if self.paged else None,
                self.mesh, self.speculative, self.sampling,
                self.chunked, self.prefill_chunks, self.buckets,
                self.digest_top_k if self.quality_digest else None,
                self.quant,
                ((self.seq_parallel, self.long_buckets)
                 if self.seq_parallel else None),
                key)

    def _memo_prog(self, key: tuple, build):
        """Two-level memo: per-engine ``_progs`` (the recompile lint's
        introspection surface — ``cache_info`` keys stay per engine) in
        front of the process-wide ``_SHARED_PROGS`` store. Every access
        counts into ``prog_key_hits`` (r20: ``aot_warmup`` zeroes the
        counts after compiling the ladder, so what remains is the
        post-warmup usage side of the enumerated-vs-used coverage
        differential)."""
        self.prog_key_hits[key] = self.prog_key_hits.get(key, 0) + 1
        cached = self._progs.get(key)
        if cached is not None:
            return cached
        gkey = self._shared_key(key)
        fn = _SHARED_PROGS.get(gkey)
        if fn is None:
            fn = build()
            _SHARED_PROGS[gkey] = fn
        self._progs[key] = fn
        return fn

    def _admit_prog(self, bucket: int, nb: int):
        """Fused prefill + slot insert: ONE program call per admission
        sub-wave (dispatch latency is the dominant admission cost).
        Memoised per geometry in the process-wide program cache (the
        closure captures config scalars only — never the engine's params
        or KV cache, which would pin them forever)."""
        key = PROGRAM_SPACE.key("admit", bucket=bucket, nb=nb)
        return self._memo_prog(key,
                               lambda: self._build_admit_prog(bucket, nb))

    def _build_admit_prog(self, bucket: int, nb: int):
        cfg, max_len, eos = self.cfg, self.max_len, self.eos

        @functools.partial(jax.jit, donate_argnums=(1,))
        def admit(params, cache, prompts, true_lens, slot_ids,
                  pos, nxt, rem, rems_new):
            # [nb, bucket] padded prompts; logits at each row's true last
            # token; pad rows beyond true_len are dead weight that decode
            # overwrites as generation proceeds
            c = llama.init_kv_cache(cfg, nb, max_len)
            logits, c = llama.forward_with_cache(
                params, prompts, cfg, c, jnp.int32(0),
                logit_pos=true_lens - 1)
            tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            k = cache["k"].at[:, slot_ids].set(c["k"])
            v = cache["v"].at[:, slot_ids].set(c["v"])
            pos = pos.at[slot_ids].set(true_lens)
            nxt = nxt.at[slot_ids].set(tok0)
            if eos is not None:
                # EOS at prefill freezes the slot IN-PROGRAM — the host
                # only learns at the next sync point (r5: host reads are
                # deferred/batched), so the device must not decode on
                rems_new = jnp.where(tok0 == eos, 0, rems_new)
            rem = rem.at[slot_ids].set(rems_new)
            return {"k": k, "v": v}, pos, nxt, rem, tok0

        return admit

    @property
    def _decode_prog(self):
        return self._memo_prog(PROGRAM_SPACE.key("decode", chunk=self.chunk),
                               self._build_decode_prog)

    def _build_decode_prog(self):
        cfg, K, eos = self.cfg, self.chunk, self.eos

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_chunk(params, cache, pos, nxt, rem):
            def body(carry, _):
                cache, pos, nxt, rem = carry
                live = rem > 0
                logits, cache = llama.forward_with_cache(
                    params, nxt[:, None], cfg, cache, pos)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(live, tok, nxt)  # frozen slots idle
                pos = pos + live.astype(jnp.int32)
                rem = rem - live.astype(jnp.int32)
                if eos is not None:
                    rem = jnp.where(live & (tok == eos), 0, rem)
                return (cache, pos, tok, rem), tok

            (cache, pos, nxt, rem), toks = jax.lax.scan(
                body, (cache, pos, nxt, rem), None, length=K)
            return cache, pos, nxt, rem, toks  # toks: [K, slots]

        return decode_chunk

    # --- scheduling -------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket for prompt length {n}")

    def _long_rung(self, n: int) -> int:
        """Smallest declared long bucket covering an ``n``-token
        suffix (r23): the spseg admit-window rung. A continuation's
        shrinking suffix walks DOWN the ladder — every rung at or below
        the first admission's is statically enumerated."""
        for b in self.long_buckets:
            if n <= b:
                return b
        raise ValueError(f"no long bucket for suffix length {n}")

    def _fill_slots(self, admits: List[tuple]) -> None:
        """Admission wave: take as many queued requests as there are free
        slots (longest-remaining-first), group them by prompt bucket, and
        run ONE fused prefill+insert program per sub-group. Hysteresis:
        between windows, refill only once a few slots are free (the
        threshold shrinks with the queue so the tail always drains) —
        wide waves amortise per-program dispatch latency.

        r5: tok0 is NOT fetched here — the device future and its
        (request, slot) mapping append to ``admits`` and the host reads
        them in ONE batched ``jax.device_get`` at the next sync point
        (per-wave blocking fetches were the dominant serving cost on a
        ~30 ms-round-trip dispatch path). Requests with
        ``max_new_tokens == 1`` are retired host-side immediately (a
        host-known condition); their token is delivered at the sync."""
        free = [s for s in range(self.slots) if self._active[s] is None]
        if not free or not self._queue:
            return
        threshold = min(4, self.slots, len(self._queue))
        if len(free) < threshold and len(free) < self.slots:
            return
        self._queue.sort(key=lambda r: -r.max_new_tokens)
        picked = self._queue[:len(free)]
        del self._queue[:len(free)]
        by_bucket: Dict[int, List[Request]] = {}
        for r in picked:
            by_bucket.setdefault(self._bucket_for(len(r.prompt)), []).append(r)
        it = iter(free)
        for bucket, group in sorted(by_bucket.items()):
            i = 0
            while i < len(group):
                nb = next(w for w in _WAVE_WIDTHS if w <= len(group) - i)
                sub = group[i:i + nb]
                i += nb
                slots = [next(it) for _ in sub]
                prompts = np.zeros((nb, bucket), np.int32)
                lens = np.zeros((nb,), np.int32)
                for j, r in enumerate(sub):
                    prompts[j, :len(r.prompt)] = r.prompt
                    lens[j] = len(r.prompt)
                rems = np.array([r.max_new_tokens - 1 for r in sub],
                                np.int32)
                self._cache, self._pos, self._nxt, self._rem, tok0 = \
                    self._admit_prog(bucket, nb)(
                        self.params, self._cache, jnp.asarray(prompts),
                        jnp.asarray(lens), jnp.asarray(slots, jnp.int32),
                        self._pos, self._nxt, self._rem, jnp.asarray(rems))
                admits.append((tok0, list(zip(sub, slots))))
                for r, s in zip(sub, slots):
                    if r.max_new_tokens <= 1:
                        # done at prefill (host-known): free the slot now;
                        # the device-side rem is already 0
                        self._rem_host[s] = 0
                        self._active[s] = None
                    else:
                        self._active[s] = r
                        self._rem_host[s] = r.max_new_tokens - 1
        # recurse: host-known prefill retirements free slots for the rest
        if self._queue and any(a is None for a in self._active):
            self._fill_slots(admits)

    def warmup(self) -> None:
        """Compile the WINDOWED path's program shapes (fused admit per
        bucket x wave width, the decode chunk) so incremental serving
        excludes compiles. The fused drain (``run()``'s default) is
        specialised to the padded workload shape (n_pad, p_max, g_max)
        and compiles on the first ``run()`` that sees that shape — warm
        it by running a representative workload once (the serving
        benchmark does exactly this)."""
        if self.paged or self.mesh is not None:
            # paged and mp-sharded engines serve through segments only;
            # each (n_pad, s_max, steps) shape compiles on its first
            # run_segment and the scheduler's warm pass covers it
            return
        for b in self.buckets:
            for nb in _WAVE_WIDTHS:
                if nb > self.slots:
                    continue
                out = self._admit_prog(b, nb)(
                    self.params, self._cache, jnp.zeros((nb, b), jnp.int32),
                    jnp.ones((nb,), jnp.int32),
                    jnp.arange(nb, dtype=jnp.int32),
                    self._pos, self._nxt, self._rem,
                    jnp.zeros((nb,), jnp.int32))
                self._cache = out[0]
        out = self._decode_prog(self.params, self._cache, self._pos,
                                self._nxt, self._rem)
        self._cache = out[0]
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        self._nxt = jnp.zeros((self.slots,), jnp.int32)
        self._rem = jnp.zeros((self.slots,), jnp.int32)

    # --- program-space coverage + AOT warmup (r20: ISSUE 15) --------------
    def default_envelope(self, seg_steps: Sequence[int] = (),
                         prefix_block: Optional[int] = None,
                         resume: bool = True,
                         offline_batch: Optional[int] = None
                         ) -> WorkloadEnvelope:
        """The widest envelope this engine's INTAKE admits: prompts up
        to the largest bucket, generations filling the cache, segments
        at ``run()``'s drain budget unless the caller declares its
        scheduler's ``seg_steps``. Deployments should declare tighter
        envelopes (every reachable key gets compiled at warmup — a
        loose envelope is dead ladder weight the coverage pass will
        name, not an error)."""
        max_prompt = (self.long_buckets[-1] if self.seq_parallel
                      else self.buckets[-1])
        return WorkloadEnvelope(
            max_prompt=max_prompt,
            max_new_tokens=max(1, self.max_len + 1 - max_prompt),
            seg_steps=tuple(seg_steps) or (4 * self.chunk,),
            resume=resume, prefix_block=prefix_block,
            offline_batch=offline_batch)

    def program_space(self, envelope: Optional[WorkloadEnvelope] = None
                      ) -> Dict[str, frozenset]:
        """Statically enumerate the EXACT finite program-key set this
        config can reach under ``envelope`` (default: the widest intake
        envelope), grouped by registered family. Every jit memo key the
        dispatch paths can construct is in here by construction — the
        keys and the dispatch arithmetic both live in
        ``program_space.PROGRAM_SPACE`` (the coverage pass replays the
        admission arithmetic over the envelope and diffs against this
        set; ``analysis.coverage`` is the enforcement)."""
        env = envelope or self.default_envelope()
        return PROGRAM_SPACE.enumerate_by_family(self, env)

    def aot_warmup(self, envelope: Optional[WorkloadEnvelope] = None,
                   prefix_cache=None) -> Dict[str, dict]:
        """Compile the FULL enumerated program space at build (the
        remaining third of old ROADMAP item 5): every key the envelope
        can reach is built through ``_memo_prog`` (fleet replicas share
        the compile via ``_SHARED_PROGS``; restarts share it via the
        r15 persistent cache) and executed once on empty state —
        ``n_real = 0`` with no live slots makes every segment's
        while_loop exit before its first iteration, so the execution
        costs microseconds and the XLA compile is the whole bill. After
        this, a serve that stays inside the envelope performs ZERO
        backend compiles (``analysis.recompile.enforce_zero_compiles``
        is the budget; ``analysis.coverage`` diffs enumerated vs used).

        Returns {family: {"keys": n, "seconds": s}} and stamps
        ``aot_warmup_s`` (the cold-start split's first half). Requires
        an idle engine (no live slots, queue, or in-flight segment).

        Pass the serve loop's ``prefix_cache`` when one will be
        attached: a tiered cache's D2H-stage/H2D-restore transfer
        programs are shape-keyed on the transferred page count and get
        prewarmed for every count the envelope's prefix lengths can
        reach."""
        assert all(r is None for r in self._active) and not self._queue, \
            "aot_warmup on a non-idle engine"
        assert self._pending_seg is None, \
            "aot_warmup with a dispatched segment in flight"
        env = envelope or self.default_envelope()
        t0 = time.perf_counter()
        by_family = PROGRAM_SPACE.enumerate_by_family(self, env)
        report: Dict[str, dict] = {}
        for fam in sorted(by_family):
            tf = time.perf_counter()
            for key in sorted(by_family[fam]):
                tk = time.perf_counter()
                self._aot_run_key(fam, key)
                self.aot_key_seconds[key] = time.perf_counter() - tk
            report[fam] = {"keys": len(by_family[fam]),
                           "seconds": time.perf_counter() - tf}
        # prewarm the between-segment eager singletons so the first
        # preempt / slot reset after warmup compiles nothing: the
        # preempt freeze scatter (device-operand index — one program
        # for all slots) and the slot-vector fill reset_slots rebuilds
        self._rem = self._rem.at[jnp.asarray(0, jnp.int32)].set(0)
        tier = getattr(prefix_cache, "host_tier", None) \
            if prefix_cache is not None else None
        if tier is not None and self.paged:
            _, hi = env.admit_lengths(self.buckets)
            if self.seq_parallel:
                # long-context harvests can park whole long prompts in
                # the cache, so spill/restore transfer shapes reach the
                # full long-prompt page span
                hi = max(hi, min(env.max_prompt + env.max_new_tokens - 1,
                                 self.max_len))
            tier.prewarm_transfers(hi // self.page_size)
        # windowed-path dummy admits wrote device slot state (pos/nxt);
        # segments and drains ran empty (n_real=0). Either way the
        # engine returns to idle zeros — it was asserted idle at entry,
        # so nothing is lost (the same reset warmup() performs)
        self._pos = self._slot_vec()
        self._nxt = self._slot_vec()
        self._rem = self._slot_vec()
        # post-warmup usage starts clean: what accumulates in
        # prog_key_hits from here on is the serve's ACTUAL key traffic
        # (the coverage differential's used-vs-enumerated side)
        self.prog_key_hits = {}
        self.aot_warmup_s = (self.aot_warmup_s or 0.0) + (
            time.perf_counter() - t0)
        n_keys = sum(r["keys"] for r in report.values())
        _metrics.gauge("serving.aot_warmup_s").set(self.aot_warmup_s)
        _metrics.gauge("serving.program_space_keys").set(n_keys)
        _flight.record("aot_warmup", seconds=round(self.aot_warmup_s, 4),
                       keys=n_keys, families=sorted(report))
        return report

    def _aot_run_key(self, family: str, key: tuple) -> None:
        """Build + compile + once-execute ONE enumerated program key on
        empty dummy state. The dummy calls mirror the dispatch paths'
        real argument shapes exactly (that is what makes the jit cache
        hit later); donated state arrays thread through so the engine
        stays consistent."""
        i32 = jnp.int32
        cfg = self.cfg
        L, Hkv, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
        with _mesh_scope(self.mesh):
            if family == "admit":
                bucket, nb = key
                out = self._admit_prog(bucket, nb)(
                    self.params, self._cache,
                    jnp.zeros((nb, bucket), i32), jnp.ones((nb,), i32),
                    jnp.arange(nb, dtype=i32), self._pos, self._nxt,
                    self._rem, jnp.zeros((nb,), i32))
                self._cache = out[0]
            elif family == "decode":
                out = self._decode_prog(self.params, self._cache,
                                        self._pos, self._nxt, self._rem)
                (self._cache, self._pos, self._nxt, self._rem) = out[:4]
            elif family == "drain":
                _, n_pad, p_max, g_max = key
                out = self._drain_prog(n_pad, p_max, g_max)(
                    self.params, self._cache,
                    jnp.zeros((n_pad, p_max), i32),
                    jnp.ones((n_pad,), i32), jnp.zeros((n_pad,), i32),
                    i32(0))
                self._cache = out[0]
            elif family == "seg":
                _, n_pad, s_max, pre_max, steps = key
                kdt = self._cache["k"].dtype
                out = self._segment_prog(n_pad, s_max, pre_max, steps)(
                    self.params, self._cache, self._pos, self._nxt,
                    self._rem, jnp.zeros((n_pad, s_max), i32),
                    jnp.ones((n_pad,), i32), jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad, L, pre_max, Hkv, D), kdt),
                    jnp.zeros((n_pad, L, pre_max, Hkv, D), kdt),
                    jnp.zeros((n_pad,), i32), i32(0))
                (self._cache, self._pos, self._nxt, self._rem) = out[:4]
            elif family in {"pseg", "qseg", "cseg", "qpseg"}:
                # qpseg keys carry a trailing dtype code; steps sits at
                # a fixed index there, key[-1] everywhere else
                n_pad, s_max = key[1], key[2]
                steps = key[3] if family == "qpseg" else key[-1]
                prog = (self._chunked_segment_prog(n_pad, s_max, key[3],
                                                   steps)
                        if family == "cseg"
                        else self._paged_segment_prog(n_pad, s_max, steps))
                pgr = self.pager
                out = prog(
                    self.params, pgr.pool, pgr.page_table, self._pos,
                    self._nxt, self._rem, jnp.zeros((n_pad, s_max), i32),
                    jnp.ones((n_pad,), i32), jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad, pgr.max_pages), i32), i32(0))
                pgr.pool, pgr.page_table = out[0], out[1]
                (self._pos, self._nxt, self._rem) = out[2:5]
            elif family == "spseg":
                _, n_pad, s_max, C, _sp, steps = key
                pgr = self.pager
                out = self._sp_segment_prog(n_pad, s_max, C, steps)(
                    self.params, pgr.pool, pgr.page_table, self._pos,
                    self._nxt, self._rem, jnp.zeros((n_pad, s_max), i32),
                    jnp.ones((n_pad,), i32), jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad, pgr.max_pages), i32), i32(0))
                pgr.pool, pgr.page_table = out[0], out[1]
                (self._pos, self._nxt, self._rem) = out[2:5]
            elif family == "sseg":
                _, n_pad, _k, steps = key
                pgr = self.pager
                rng = (self._rng if self._rng is not None
                       else jnp.zeros((self.slots, 2), jnp.uint32))
                s_max = self.buckets[-1]
                if self.chunked:
                    C = self._prefill_chunk_for(s_max)
                    s_max = -(-s_max // C) * C
                out = self._spec_segment_prog(n_pad, steps)(
                    self.params, pgr.pool, pgr.page_table, self._pos,
                    self._nxt, self._rem, self._hist, self._hstart, rng,
                    jnp.zeros((n_pad, s_max), i32),
                    jnp.ones((n_pad,), i32), jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad,), i32),
                    jnp.zeros((n_pad, pgr.max_pages), i32),
                    jnp.zeros((n_pad,), i32), i32(0))
                pgr.pool, pgr.page_table = out[0], out[1]
                (self._pos, self._nxt, self._rem) = out[2:5]
                self._hist, self._hstart = out[5], out[6]
                if self._rng is not None:
                    self._rng = out[7]
            else:
                raise KeyError(f"unknown program family {family!r}")

    # --- fused whole-drain program (r5) -----------------------------------
    def _drain_prog(self, n_pad: int, p_max: int, g_max: int):
        """The WHOLE queue drain as ONE compiled program (the decode
        analog of ``llama.generate``'s single-scan design, prescribed by
        r4's verdict): slot state lives on device and a ``while_loop``
        alternates two branches —

          admit:  a free slot exists and requests remain -> prefill the
                  next request (bucket-padded [1, p_max]) inside a
                  ``lax.cond`` branch and scatter its KV/pos/token into
                  the slot arrays;
          decode: one ragged tick for all slots (frozen slots idle).

        Admission costs no host round trip, so refill is GREEDY (every
        free slot refills the moment work is queued — better packing
        than the windowed path's hysteresis). Host round trips for the
        whole drain: ONE dispatch + ONE result fetch, making the engine
        dispatch-latency-robust by construction. Memoised per
        (n_pad, p_max, g_max) padded workload shape."""
        key = PROGRAM_SPACE.key("drain", n_pad=n_pad, p_max=p_max,
                                g_max=g_max)
        return self._memo_prog(key, lambda: self._build_drain_prog(
            n_pad, p_max, g_max))

    def _build_drain_prog(self, n_pad: int, p_max: int, g_max: int):
        cfg, max_len, slots, eos = (self.cfg, self.max_len, self.slots,
                                    self.eos)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def drain(params, cache, prompts, lens, gens, n_real):
            i32 = jnp.int32
            st = dict(
                cache=cache,
                pos=jnp.zeros((slots,), i32),
                nxt=jnp.zeros((slots,), i32),
                rem=jnp.zeros((slots,), i32),
                rid=jnp.full((slots,), n_pad, i32),   # n_pad = trash row
                cnt=jnp.zeros((slots,), i32),
                out=jnp.zeros((n_pad + 1, g_max), i32),
                fin=jnp.zeros((n_pad + 1,), i32),     # finish step / req
                qidx=i32(0), step=i32(0), ndec=i32(0),
            )

            def cond(st):
                return jnp.any(st["rem"] > 0) | (st["qidx"] < n_real)

            def admit(st):
                s = jnp.argmin(st["rem"])  # a rem==0 slot (min is 0)
                q = st["qidx"]
                # every prefill pads to the batch-global p_max (no per-
                # bucket lax.switch): prefill here is HBM-bound — it
                # streams the whole weight set regardless of width — so a
                # 32-token prompt padded to 256 costs ~the same wall time,
                # and one branch keeps the program small
                prow = jax.lax.dynamic_slice(prompts, (q, 0), (1, p_max))
                ln = lens[q]
                c1 = llama.init_kv_cache(cfg, 1, p_max)
                logits, c1 = llama.forward_with_cache(
                    params, prow, cfg, c1, jnp.int32(0), logit_pos=ln - 1)
                t0 = jnp.argmax(logits, axis=-1).astype(i32).reshape(())
                k = jax.lax.dynamic_update_slice(
                    st["cache"]["k"], c1["k"], (0, s, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(
                    st["cache"]["v"], c1["v"], (0, s, 0, 0, 0))
                rem_new = gens[q] - 1
                if eos is not None:
                    rem_new = jnp.where(t0 == eos, 0, rem_new)
                fin = jnp.where(rem_new == 0,
                                st["fin"].at[q].set(st["step"]), st["fin"])
                return dict(
                    cache={"k": k, "v": v},
                    pos=st["pos"].at[s].set(ln),
                    nxt=st["nxt"].at[s].set(t0),
                    rem=st["rem"].at[s].set(rem_new),
                    rid=st["rid"].at[s].set(q),
                    cnt=st["cnt"].at[s].set(1),
                    out=st["out"].at[q, 0].set(t0),
                    fin=fin,
                    qidx=q + 1, step=st["step"], ndec=st["ndec"],
                )

            def decode(st):
                live = st["rem"] > 0
                logits, cache = llama.forward_with_cache(
                    params, st["nxt"][:, None], cfg, st["cache"], st["pos"])
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(live, tok, st["nxt"])
                rows = jnp.where(live, st["rid"], n_pad)
                cols = jnp.minimum(st["cnt"], g_max - 1)
                out = st["out"].at[rows, cols].set(tok)
                rem = st["rem"] - live.astype(jnp.int32)
                if eos is not None:
                    rem = jnp.where(live & (tok == eos), 0, rem)
                finished = live & (rem == 0)
                fin = st["fin"].at[
                    jnp.where(finished, st["rid"], n_pad)].set(st["step"])
                return dict(
                    cache=cache,
                    pos=st["pos"] + live.astype(jnp.int32),
                    nxt=tok,
                    rem=rem,
                    rid=st["rid"], cnt=st["cnt"] + live.astype(jnp.int32),
                    out=out, fin=fin,
                    qidx=st["qidx"], step=st["step"],
                    ndec=st["ndec"] + 1,
                )

            def body(st):
                can_admit = (st["qidx"] < n_real) & jnp.any(st["rem"] == 0)
                st = jax.lax.cond(can_admit, admit, decode, st)
                st["step"] = st["step"] + 1
                return st

            st = jax.lax.while_loop(cond, body, st)
            return (st["cache"], st["out"], st["fin"], st["step"],
                    st["ndec"])

        return drain

    @staticmethod
    def _pow2(n: int, lo: int = 1) -> int:
        p = lo
        while p < n:
            p *= 2
        return p

    def _run_fused(self) -> Dict[int, List[int]]:
        self._queue.sort(key=lambda r: -r.max_new_tokens)
        picked, self._queue = self._queue, []
        n = len(picked)
        n_pad = self._pow2(n)
        p_max = self._bucket_for(max(len(r.prompt) for r in picked))
        g_max = self._pow2(max(r.max_new_tokens for r in picked), lo=16)
        prompts = np.zeros((n_pad, p_max), np.int32)
        lens = np.ones((n_pad,), np.int32)   # pad rows: 1-token dummy
        gens = np.zeros((n_pad,), np.int32)  # gen 0 -> never admitted
        for j, r in enumerate(picked):
            prompts[j, :len(r.prompt)] = r.prompt
            lens[j] = len(r.prompt)
            gens[j] = r.max_new_tokens
        t0 = time.perf_counter()
        self._cache, out, fin, steps, ndec = self._drain_prog(
            n_pad, p_max, g_max)(
                self.params, self._cache, jnp.asarray(prompts),
                jnp.asarray(lens), jnp.asarray(gens), jnp.int32(n))
        out, fin, steps, ndec = jax.device_get([out, fin, steps, ndec])
        wall = time.perf_counter() - t0
        if n and self.cold_start_s is None:
            self._note_cold_start()   # offline drain path's first tokens
        self.last_run_ticks = int(ndec)
        self.last_run_chunks = -(-int(ndec) // self.chunk)
        per_step = wall / max(int(steps), 1)
        for j, r in enumerate(picked):
            toks = [int(t) for t in out[j, :r.max_new_tokens]]
            if self.eos is not None and self.eos in toks:
                toks = toks[:toks.index(self.eos) + 1]
            r.tokens = toks
            # latency estimate: request finished at loop step fin[j] of
            # steps total (single-program drain has no per-request host
            # clock; the step clock scales by measured wall time).
            # Uniform step weighting is deliberate: at this model scale
            # BOTH branch kinds are HBM-bound and stream the full weight
            # set once — an admit (prefill [1, p_max]) and a decode tick
            # ([slots, 1]) cost within ~2x of each other, not the ~p_max x
            # a FLOP-count model would suggest.
            r.finish_time = r.submit_time + (int(fin[j]) + 1) * per_step
            self._finished.append(r)
        done = {r.rid: r.tokens for r in self._finished}
        self.last_latencies = {r.rid: r.finish_time - r.submit_time
                               for r in self._finished if r.finish_time}
        self._finished = []
        return done

    # --- re-entrant fused segments (r7: online continuous batching) -------
    def _segment_prog(self, n_pad: int, s_max: int, pre_max: int,
                      max_steps: int):
        """The fused drain, RE-ENTRANT: one compiled program that starts
        from the engine's *current* slot state (cache/pos/nxt/rem as
        inputs, not zeros), admits up to ``n_pad`` queued requests into
        slots as they free, decodes for at most ``max_steps`` loop
        iterations, and returns the slot state plus an event log the host
        replays. This is ``_drain_prog``'s while_loop with three changes:

        * slot state is an argument — a segment composes with previous
          segments (and with the windowed path) instead of assuming an
          empty engine, so newly arrived requests join slots freed by
          EOS/retirement mid-flight;
        * the loop is step-bounded — the host regains control every
          ``max_steps`` ticks to ingest arrivals and stamp real
          (measured) per-request times at the sync;
        * outputs are an event log indexed by (local step, slot)
          (``out``) plus per-step admit records (``aq``/``aslot``) —
          NOT per-request rows — so requests admitted in *earlier*
          segments keep streaming into the same log and the host replay
          attributes tokens by tracking slot occupancy.

        Shared-prefix admission (``pre_max > 0``): each queue row carries
        ``pre_len`` already-prefilled KV rows (from the prefix cache);
        the admit branch writes those rows into a temp cache and runs
        prefill ONLY on the [1, s_max] suffix at positions
        pre_len..pre_len+s_max-1 — the quadratic attention and the
        per-token matmul work of the shared prefix are not re-done.
        Memoised per (n_pad, s_max, pre_max, max_steps) shape."""
        key = PROGRAM_SPACE.key("seg", n_pad=n_pad, s_max=s_max,
                                pre_max=pre_max, steps=max_steps)
        if pre_max + s_max > self.max_len:
            raise ValueError(
                f"segment admit window {pre_max}+{s_max} exceeds cache "
                f"max_len {self.max_len}")
        return self._memo_prog(key, lambda: self._build_segment_prog(
            n_pad, s_max, pre_max, max_steps))

    def _build_segment_prog(self, n_pad: int, s_max: int, pre_max: int,
                            max_steps: int):
        cfg, slots, eos = self.cfg, self.slots, self.eos

        @functools.partial(jax.jit, donate_argnums=(1,))
        def segment(params, cache, pos, nxt, rem, prompts, lens, gens,
                    pre_k, pre_v, pre_lens, n_real):
            i32 = jnp.int32
            st = dict(
                cache=cache, pos=pos, nxt=nxt, rem=rem,
                out=jnp.zeros((max_steps, slots), i32),
                aq=jnp.full((max_steps,), n_pad, i32),    # n_pad = decode
                aslot=jnp.zeros((max_steps,), i32),
                qidx=i32(0), step=i32(0),
            )

            def cond(st):
                work = jnp.any(st["rem"] > 0) | (st["qidx"] < n_real)
                return work & (st["step"] < max_steps)

            def admit(st):
                s = jnp.argmin(st["rem"])          # a rem==0 slot
                q = st["qidx"]
                prow = jax.lax.dynamic_slice(prompts, (q, 0), (1, s_max))
                ln = lens[q]
                pln = pre_lens[q]
                c1 = llama.init_kv_cache(cfg, 1, pre_max + s_max)
                if pre_max:
                    # reused prefix rows land at absolute rows [0, pre_max)
                    # of the temp cache; rows beyond this request's true
                    # pre_len are zeros and stay masked (suffix tokens
                    # write at absolute positions pre_len+t, and decode
                    # attention never looks past pos)
                    pk = jax.lax.dynamic_slice(
                        pre_k, (q, 0, 0, 0, 0),
                        (1,) + pre_k.shape[1:]).transpose(1, 0, 2, 3, 4)
                    pv = jax.lax.dynamic_slice(
                        pre_v, (q, 0, 0, 0, 0),
                        (1,) + pre_v.shape[1:]).transpose(1, 0, 2, 3, 4)
                    c1 = {
                        "k": jax.lax.dynamic_update_slice(
                            c1["k"], pk.astype(c1["k"].dtype),
                            (0, 0, 0, 0, 0)),
                        "v": jax.lax.dynamic_update_slice(
                            c1["v"], pv.astype(c1["v"].dtype),
                            (0, 0, 0, 0, 0)),
                    }
                logits, c1 = llama.forward_with_cache(
                    params, prow, cfg, c1, pln, logit_pos=ln - 1)
                t0 = jnp.argmax(logits, axis=-1).astype(i32).reshape(())
                k = jax.lax.dynamic_update_slice(
                    st["cache"]["k"], c1["k"], (0, s, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(
                    st["cache"]["v"], c1["v"], (0, s, 0, 0, 0))
                rem_new = gens[q] - 1
                if eos is not None:
                    rem_new = jnp.where(t0 == eos, 0, rem_new)
                return dict(
                    cache={"k": k, "v": v},
                    pos=st["pos"].at[s].set(pln + ln),
                    nxt=st["nxt"].at[s].set(t0),
                    rem=st["rem"].at[s].set(rem_new),
                    out=st["out"].at[st["step"], s].set(t0),
                    aq=st["aq"].at[st["step"]].set(q),
                    aslot=st["aslot"].at[st["step"]].set(s),
                    qidx=q + 1, step=st["step"],
                )

            def decode(st):
                live = st["rem"] > 0
                logits, cache = llama.forward_with_cache(
                    params, st["nxt"][:, None], cfg, st["cache"], st["pos"])
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(live, tok, st["nxt"])
                rem = st["rem"] - live.astype(jnp.int32)
                if eos is not None:
                    rem = jnp.where(live & (tok == eos), 0, rem)
                return dict(
                    cache=cache,
                    pos=st["pos"] + live.astype(jnp.int32),
                    nxt=tok, rem=rem,
                    out=st["out"].at[st["step"]].set(tok),
                    aq=st["aq"], aslot=st["aslot"],
                    qidx=st["qidx"], step=st["step"],
                )

            def body(st):
                can_admit = (st["qidx"] < n_real) & jnp.any(st["rem"] == 0)
                st = jax.lax.cond(can_admit, admit, decode, st)
                st["step"] = st["step"] + 1
                return st

            st = jax.lax.while_loop(cond, body, st)
            return (st["cache"], st["pos"], st["nxt"], st["rem"],
                    st["out"], st["aq"], st["aslot"], st["step"],
                    st["qidx"])

        return segment

    def _replay_segment(self, picked, toks, aq, aslot, steps: int, n: int,
                        on_admit=None, on_retire=None,
                        chunk_marker: Optional[int] = None,
                        acc=None, spec_stats: Optional[dict] = None,
                        dig=None):
        """Host replay of a segment's event log — ONE contract for the
        contiguous and paged engines: walk the log chronologically,
        tracking slot occupancy (admits rebind a slot; decode ticks
        append one token to every slot the HOST knows is live via its
        rem mirror, so frozen-slot repeats and pad rows are dropped
        exactly as the windowed _sync does). ``on_admit(q, slot)`` /
        ``on_retire(req, slot)`` are the paged engine's page-table
        bookkeeping hooks, called in event order so a slot freed and
        re-admitted mid-segment releases the old occupant's pages
        before the new page list installs. ``chunk_marker`` (chunked
        prefill): aq values >= it mark NON-FINAL prefill-chunk steps —
        no decode ran and no token surfaced there, so the replay skips
        the step.

        r15 speculative event logs: ``acc`` ([steps, slots]) makes
        ``toks`` a [steps, slots, K+1] token matrix — a decode step is
        a VERIFY tick that emitted ``acc[st, s]`` tokens for slot ``s``
        (admits carry their one token at column 0). The replay walks
        each slot's accepted prefix, recovers per-request accepted
        lengths into the Request ledger, and accumulates the segment's
        draft accounting into ``spec_stats`` — host arithmetic on the
        SAME single fetched log, zero extra device contact."""
        spec_k = self.speculative
        admitted, first_tokens, finished = [], [], []
        new_tokens = eos_stops = 0
        for st in range(steps):
            q = int(aq[st])
            if chunk_marker is not None and q >= chunk_marker:
                continue                   # mid-prefill chunk: no tokens
            if q < n:                      # admit event
                r = picked[q]
                s = int(aslot[st])
                assert self._active[s] is None, "admit into a live slot"
                if on_admit is not None:
                    on_admit(q, s)
                t = int(toks[st, s, 0] if acc is not None
                        else toks[st, s])
                r.tokens.append(t)
                # r18 meter: the admit's prefill streamed the weight
                # set once, solo (the admit branch runs alone)
                r.meter_ticks += 1
                r.meter_streams += 1.0
                if dig is not None:
                    self._append_digest(r, dig, st, s)
                new_tokens += 1
                admitted.append(r.rid)
                if len(r.tokens) == 1:
                    # a RESUMED request (preempt/failover) already
                    # delivered its first token before losing its slot —
                    # only a fresh admit opens the TTFT clock
                    first_tokens.append(r.rid)
                hit_eos = self.eos is not None and t == self.eos
                eos_stops += hit_eos
                if r.done or hit_eos:
                    self._rem_host[s] = 0
                    self._retire(r)
                    finished.append(r.rid)
                    if on_retire is not None:
                        on_retire(r, s)
                else:
                    self._active[s] = r
                    # remaining = owed minus everything generated so far
                    # (fresh: max_new - 1; resumed: the true tail)
                    self._rem_host[s] = r.max_new_tokens - len(r.tokens)
            elif acc is None:              # decode tick
                live_now = [(s, r) for s, r in enumerate(self._active)
                            if r is not None and self._rem_host[s] > 0]
                share = 1.0 / len(live_now) if live_now else 0.0
                for s, r in live_now:
                    # r18 meter: every live slot consumed this tick's
                    # one weight stream; the share splits it fairly
                    r.meter_ticks += 1
                    r.meter_streams += share
                    t = int(toks[st, s])
                    r.tokens.append(t)
                    if dig is not None:
                        self._append_digest(r, dig, st, s)
                    new_tokens += 1
                    if len(r.tokens) == 1:
                        first_tokens.append(r.rid)
                    self._rem_host[s] -= 1
                    if self.eos is not None and t == self.eos:
                        self._rem_host[s] = 0
                        eos_stops += 1
                    if self._rem_host[s] == 0:
                        self._retire(r)
                        self._active[s] = None
                        finished.append(r.rid)
                        if on_retire is not None:
                            on_retire(r, s)
            else:                          # spec VERIFY tick
                live_now = [(s, r) for s, r in enumerate(self._active)
                            if r is not None and self._rem_host[s] > 0]
                share = 1.0 / len(live_now) if live_now else 0.0
                any_live = False
                for s, r in live_now:
                    any_live = True
                    # r18 meter: a verify tick is still ONE weight
                    # stream however many tokens it retires — the
                    # spec-adjusted effective-ticks denominator
                    r.meter_ticks += 1
                    r.meter_streams += share
                    k_emit = int(acc[st, s])
                    if spec_stats is not None:
                        spec_stats["slot_ticks"] += 1
                    if spec_k:
                        r.spec_proposed += spec_k
                        r.spec_accepted += max(k_emit - 1, 0)
                        if spec_stats is not None:
                            spec_stats["proposed"] += spec_k
                            spec_stats["accepted"] += max(k_emit - 1, 0)
                    for i in range(k_emit):
                        if self._rem_host[s] <= 0:
                            break
                        t = int(toks[st, s, i])
                        r.tokens.append(t)
                        new_tokens += 1
                        if spec_stats is not None:
                            spec_stats["emitted"] += 1
                        if len(r.tokens) == 1:
                            first_tokens.append(r.rid)
                        self._rem_host[s] -= 1
                        if self.eos is not None and t == self.eos:
                            self._rem_host[s] = 0
                            eos_stops += 1
                    if self._rem_host[s] == 0:
                        self._retire(r)
                        self._active[s] = None
                        finished.append(r.rid)
                        if on_retire is not None:
                            on_retire(r, s)
                if any_live and spec_stats is not None:
                    spec_stats["verify_steps"] += 1
        if new_tokens and self.cold_start_s is None:
            self._note_cold_start()
        return admitted, first_tokens, finished, new_tokens, eos_stops

    @staticmethod
    def _append_digest(r: Request, dig, st: int, s: int) -> None:
        """Distribute one event-log digest row to its request (r17):
        host arithmetic on the already-fetched arrays — (emitted-token
        logit, top-k ids, top-k values), index-aligned with
        ``r.tokens``."""
        dlg, dti, dtv = dig
        if r.digests is None:
            r.digests = []
        r.digests.append((float(dlg[st, s]),
                          [int(i) for i in dti[st, s]],
                          [float(v) for v in dtv[st, s]]))

    def _note_cold_start(self) -> None:
        """First host-visible token since build: stamp the cold-start
        and publish it (SERVING metric + flight event). Runs at the
        fetch that surfaced the token, so the stamp includes program
        build + first compile + first prefill — the full client-facing
        cold-start window.

        r20 (ISSUE 15): with ``aot_warmup`` the gauge SPLITS —
        ``aot_warmup_s`` (the whole enumerated ladder compiled at
        build) + ``first_token_s`` (cold_start minus warmup: queue,
        admit, prefill — no XLA left to pay). The split is what makes
        the autoscaler's scale-up latency a measured, bounded number:
        warmup cost amortises across the persistent cache / fleet
        shared programs, first_token_s is the irreducible tail."""
        self.cold_start_s = time.perf_counter() - self.built_at
        self.first_token_s = self.cold_start_s - (self.aot_warmup_s or 0.0)
        _metrics.gauge("serving.cold_start_s").set(self.cold_start_s)
        _metrics.gauge("serving.first_token_s").set(self.first_token_s)
        _flight.record("cold_start",
                       seconds=round(self.cold_start_s, 4),
                       aot_warmup_s=(round(self.aot_warmup_s, 4)
                                     if self.aot_warmup_s is not None
                                     else None),
                       first_token_s=round(self.first_token_s, 4),
                       paged=self.paged, slots=self.slots)

    def _segment_telemetry(self, steps, admitted, finished, eos_stops,
                           new_tokens, requeued) -> None:
        """Post-sync counters/flight for one segment — host arithmetic
        on the already-fetched event log (ISSUE 5 contract: the
        segment's device contact stays the single audited allowed_sync
        in the caller)."""
        _metrics.counter("serving.segments").inc()
        _metrics.counter("serving.ticks").inc(steps)
        _metrics.counter("serving.admissions").inc(len(admitted))
        _metrics.counter("serving.tokens_generated").inc(new_tokens)
        if eos_stops:
            _metrics.counter("serving.eos_stops").inc(eos_stops)
        _metrics.gauge("serving.slots_live").set(
            self.slots - self.free_slot_count())
        _flight.record("segment", steps=steps, admitted=len(admitted),
                       finished=len(finished), eos=eos_stops,
                       tokens=new_tokens, requeued=requeued)
        if SEGMENT_HOOKS:
            # r14 ambient observers (SLO monitor / perf intervals):
            # host ints only, same zero-extra-sync contract
            for hook in SEGMENT_HOOKS:
                hook(steps, new_tokens, len(finished))

    def _spec_telemetry(self, stats: dict) -> None:
        """Per-segment speculative accounting (r15 satellite): counters
        for drafts proposed/accepted/rejected, the live accept-rate and
        effective-tokens-per-tick gauges, a ``spec_accept`` flight
        event, and the acceptance EWMA the SLO scheduler threads into
        its deadline/retry estimates. Host arithmetic on the replayed
        event log — the zero-extra-sync telemetry contract holds."""
        prop, accepted = stats["proposed"], stats["accepted"]
        if prop:
            _metrics.counter("spec.proposed").inc(prop)
            _metrics.counter("spec.accepted").inc(accepted)
            _metrics.counter("spec.rejected").inc(prop - accepted)
            _metrics.gauge("spec.accept_rate").set(accepted / prop)
        if stats["slot_ticks"]:
            # PER-SLOT accepted length: tokens one slot retires per
            # verify tick (not batch tokens/tick — a full batch already
            # amortises the weight stream over slots; this gauge is the
            # roofline-beating factor on TOP of that, SCALING §3j)
            eff = stats["emitted"] / stats["slot_ticks"]
            _metrics.gauge("spec.effective_tok_per_tick").set(eff)
            # EWMA over segments: each slot retires ~eff tokens per
            # tick, the factor the SLO deadline/shed estimates divide by
            self.spec_accept_ewma = 0.5 * self.spec_accept_ewma + 0.5 * eff
            _flight.record("spec_accept", proposed=prop,
                           accepted=accepted,
                           rate=round(accepted / prop, 4) if prop else 0.0,
                           tok_per_tick=round(eff, 4))

    def free_slot_count(self) -> int:
        return sum(1 for r in self._active if r is None)

    def reset_slots(self) -> None:
        """Clear all slot state (cache rows stay allocated — pos masking
        makes stale rows invisible). Used between warmup and a timed run."""
        assert all(r is None for r in self._active), \
            "reset_slots with live requests"
        assert self._pending_seg is None, \
            "reset_slots with a dispatched segment in flight"
        self._pos = self._slot_vec()
        self._nxt = self._slot_vec()
        self._rem = self._slot_vec()
        self._init_spec_state()
        self.spec_accept_ewma = 1.0
        self._rem_host = [0] * self.slots
        for r in self._queue:
            info = self._sp_inflight.pop(r.rid, None)
            if info is not None:
                r._meter_release()
                self.pager.release_pages(info["pages"])
        self._sp_inflight = {}
        self._queue = []
        self._finished = []
        self.last_run_ticks = 0
        self.last_run_chunks = 0
        self.last_latencies = {}
        self.page_backpressure_events = 0
        if self.paged:
            self.pager.reset()

    # --- preemption / teardown (r13: the SLO control plane's hooks) -------
    def can_preempt(self, slot: int) -> bool:
        """Whether ``slot``'s occupant could be preempted AND later
        resumed by this engine: the resume view (prompt + generated
        tokens) must still fit the largest prompt bucket — a request
        whose generation outgrew the admit window cannot re-prefill and
        must be left to finish in place."""
        r = self._active[slot]
        return (r is not None
                and len(r.prompt) + len(r.tokens) <= max(self.buckets))

    def preempt_slot(self, slot: int, prefix_cache=None) -> Request:
        """Evict ``slot``'s request between segments and return it for
        requeueing — the priority-preemption primitive (ISSUE 8b). The
        device sees one tiny scatter (rem[slot] = 0: the slot freezes
        and, paged, its writes route to the trash page) and NO sync;
        everything else is host bookkeeping:

        * paged + ``prefix_cache``: the slot's page-aligned prefix
          (prompt + tokens generated so far) is PARKED in the cache by
          reference before the slot's refs release — harvest-by-
          reference, zero KV row copies — so the resume admission is a
          page-ref bump plus a suffix-only prefill of the unaligned
          tail;
        * paged without a cache: the pages free outright and resume
          re-prefills (still token-identical — greedy);
        * contiguous: the KV rows [0, aligned_len) are harvested into
          the row-copy cache exactly like post-segment population.

        The caller decides where the request re-enters the queue (the
        SLO scheduler reinserts it at the head of its class)."""
        assert self._pending_seg is None, \
            "preempt with a dispatched segment in flight"
        r = self._active[slot]
        assert r is not None, f"preempt of empty slot {slot}"
        # freeze on device: a dispatch, not a sync (the audit contract
        # of the serve loop — one fetch per segment — is untouched).
        # The index rides as a DEVICE operand, not a baked constant, so
        # one compiled scatter covers every slot — aot_warmup prewarms
        # it and the zero-post-warmup-compile budget holds across
        # preemptions of any slot (r20)
        self._rem = self._rem.at[jnp.asarray(slot, jnp.int32)].set(0)
        self._rem_host[slot] = 0
        self._active[slot] = None
        r.preemptions += 1
        fp, _ = r.resume_view()
        if self.paged:
            r._meter_release()
            pgr = self.pager
            if prefix_cache is not None:
                plen_b = prefix_cache.round_down(len(fp))
                if plen_b:
                    prefix_cache.insert(
                        fp[:plen_b],
                        pgr.slot_pages[slot][:plen_b // self.page_size])
            pgr.free_slot(slot)
        elif prefix_cache is not None:
            plen_b = prefix_cache.round_down(len(fp))
            if plen_b:
                prefix_cache.insert(fp[:plen_b],
                                    self._cache["k"][:, slot, :plen_b],
                                    self._cache["v"][:, slot, :plen_b])
        _metrics.counter("serving.preemptions").inc()
        _flight.record("preempt", rid=r.rid, slot=slot,
                       tokens_done=len(r.tokens),
                       remaining=r.max_new_tokens - len(r.tokens),
                       parked=prefix_cache is not None)
        return r

    def abort(self) -> List[Request]:
        """Tear the engine down after a replica failure (fleet failover,
        ISSUE 8c) and return every request it still owed: the queue, the
        live slots, and anything an in-flight (dispatched, never
        fetched) segment had picked — that segment's event log is LOST,
        but its requests' host state never advanced, so each resumes
        elsewhere from its last fetched token (greedy decode keeps the
        stream identical). Slot vectors and the page pool reset so a
        recovered replica re-enters service empty."""
        orphans: List[Request] = []
        p, self._pending_seg = self._pending_seg, None
        released_rids = set()
        if p is not None:
            if p.paged:
                for pages in p.req_pages:
                    self.pager.release_pages(pages)
            for r in p.picked:
                r.admit_time = 0.0
                r._meter_release()
                released_rids.add(r.rid)
            orphans += p.picked
        # r23: held multi-segment prefill reservations die with the
        # replica (their landed KV rows are lost) — the request resumes
        # elsewhere with a fresh full prefill
        for rid, info in self._sp_inflight.items():
            if rid not in released_rids:
                self.pager.release_pages(info["pages"])
        self._sp_inflight = {}
        for r in self._active:
            if r is not None:
                r._meter_release()
        for r in self._queue:
            r._meter_release()   # held sp reservations just released
        orphans += [r for r in self._active if r is not None]
        orphans += self._queue
        self._queue = []
        self._active = [None] * self.slots
        self._rem_host = [0] * self.slots
        self._pos = self._slot_vec()
        self._nxt = self._slot_vec()
        self._rem = self._slot_vec()
        self._init_spec_state()
        if self.paged:
            self.pager.reset()
        return orphans

    def run_segment(self, max_steps: int, prefix_cache=None,
                    n_pad: Optional[int] = None,
                    now: Optional[float] = None) -> dict:
        """One fused continuous-batching segment: admit FCFS from the
        queue into free slots (at most ``n_pad``), decode up to
        ``max_steps`` ticks, ONE dispatch + ONE fetch, then replay the
        event log host-side to distribute tokens and retire requests.

        Returns {"steps", "admitted", "first_tokens", "finished"} — rid
        lists the caller (the online scheduler) stamps with the sync
        wall-clock time; ``now`` defaults to time.perf_counter() and is
        recorded as each admitted request's admit_time.

        r12: dispatch and fetch are separable — ``dispatch_segment``
        launches the program and returns immediately (jax async
        dispatch), ``finish_segment`` blocks on the event fetch and runs
        the host replay. The fleet router uses the split to overlap N
        replicas' device work; this method is the two back to back."""
        return self.finish_segment(
            self.dispatch_segment(max_steps, prefix_cache, n_pad, now))

    def dispatch_segment(self, max_steps: int, prefix_cache=None,
                         n_pad: Optional[int] = None,
                         now: Optional[float] = None) -> _PendingSegment:
        """Launch one fused segment WITHOUT fetching its event log: picks
        requests, (for paged engines) reserves page lists, dispatches the
        program, and records the device futures in a ``_PendingSegment``.
        At most one segment may be in flight per engine — the slot-state
        arrays the next dispatch would consume are this segment's donated
        outputs, and the host queue/slot mirrors only advance at the
        fetch."""
        if self._pending_seg is not None:
            raise RuntimeError(
                "dispatch_segment with a segment already in flight — "
                "finish_segment must run first (one outstanding segment "
                "per engine)")
        if now is None:
            # the admit_time stamp feeds the SLO EWMAs (decision
            # inputs), so it reads the r16 DECISION clock — recorded
            # with a journal attached, fed back during replay
            now = _journal.now()
        n_pad = n_pad or self._pow2(self.slots)
        if self.paged:
            pending = self._dispatch_segment_paged(max_steps, prefix_cache,
                                                   n_pad, now)
        else:
            pending = self._dispatch_segment_dense(max_steps, prefix_cache,
                                                   n_pad, now)
        self._pending_seg = pending
        return pending

    def finish_segment(self, pending: Optional[_PendingSegment] = None
                       ) -> dict:
        """Block on a dispatched segment's event fetch (THE audited
        per-segment sync) and replay it host-side. Returns the
        ``run_segment`` result dict."""
        p = pending if pending is not None else self._pending_seg
        if p is None or p is not self._pending_seg:
            raise RuntimeError("finish_segment without a matching "
                               "dispatched segment")
        self._pending_seg = None
        if p.paged:
            return self._finish_segment_paged(p)
        return self._finish_segment_dense(p)

    def _dispatch_segment_dense(self, max_steps: int, prefix_cache,
                                n_pad: int, now: float) -> _PendingSegment:
        # pick up to n_pad regardless of CURRENT free slots: in-program
        # admission refills slots the moment they retire mid-segment, so
        # over-picking is exactly what keeps the batch full (requests the
        # step budget couldn't admit are re-queued below)
        picked = self._queue[:n_pad]
        del self._queue[:len(picked)]
        n = len(picked)

        # admission view (r13): a fresh request prefills its prompt, a
        # preempted/failed-over one resumes from prompt + generated
        # tokens and owes only the tail
        fulls = [r.resume_view() for r in picked]

        # prefix-cache lookup (admission-time detection): per request the
        # longest cached block-aligned prefix; suffix = the rest
        pre_lens = np.zeros((n_pad,), np.int32)
        pre_entries = [None] * n
        if prefix_cache is not None:
            for j, r in enumerate(picked):
                fp = fulls[j][0]
                ent = prefix_cache.match(fp)
                if ent is not None and ent.length < len(fp):
                    pre_entries[j] = ent
                    pre_lens[j] = ent.length
                    r.prefix_hit_len = ent.length
        pre_max = int(max(pre_lens)) if n else 0
        if pre_max:
            pre_max = prefix_cache.round_up(pre_max)

        # prompt width: WITHOUT prefix reuse, pin to the largest bucket —
        # prefill pads there anyway on the drain path (HBM-bound: it
        # streams the full weight set regardless of width) and ONE
        # program shape means no mid-serve XLA compile when arrival
        # jitter regroups admissions (measured: a stray 64-wide segment
        # compiled 2.5s into an online run, dwarfing the work). WITH
        # prefix reuse the suffix width IS the saving, so bucket it —
        # shared-prefix workloads have uniform tails, so the shape set
        # stays small and the warm pass covers it.
        if prefix_cache is None or pre_max == 0:
            s_max = self.buckets[-1]
        else:
            suf_max = max((len(fulls[j][0]) - int(pre_lens[j])
                           for j in range(n)), default=1)
            s_max = self._bucket_for(suf_max)
        if pre_max and pre_max + s_max > self.max_len:
            # prefix + suffix window must fit the cache; drop the hits
            pre_max = 0
            pre_lens[:] = 0
            pre_entries = [None] * n
            for r in picked:
                r.prefix_hit_len = 0
            s_max = self.buckets[-1]

        prompts = np.zeros((n_pad, s_max), np.int32)
        lens = np.ones((n_pad,), np.int32)
        gens = np.zeros((n_pad,), np.int32)   # gen 0 -> never admitted
        for j, r in enumerate(picked):
            fp, remaining = fulls[j]
            suf = fp[int(pre_lens[j]):]
            prompts[j, :len(suf)] = suf
            lens[j] = len(suf)
            gens[j] = remaining
            r.admit_time = now
        if pre_max:
            L = self.cfg.num_layers
            Hkv, D = self.cfg.num_kv_heads, self.cfg.head_dim
            pk = jnp.zeros((n_pad, L, pre_max, Hkv, D), self._cache["k"].dtype)
            pv = jnp.zeros((n_pad, L, pre_max, Hkv, D), self._cache["v"].dtype)
            for j, ent in enumerate(pre_entries):
                if ent is not None:
                    pk = pk.at[j, :, :ent.length].set(ent.k[:, :ent.length])
                    pv = pv.at[j, :, :ent.length].set(ent.v[:, :ent.length])
        else:
            # zero-width prefix block: the program specialises pre_max=0
            # and skips the prefix writes entirely
            L = self.cfg.num_layers
            Hkv, D = self.cfg.num_kv_heads, self.cfg.head_dim
            pk = jnp.zeros((n_pad, L, 0, Hkv, D), self._cache["k"].dtype)
            pv = jnp.zeros((n_pad, L, 0, Hkv, D), self._cache["v"].dtype)

        with _mesh_scope(self.mesh):
            out = self._segment_prog(n_pad, s_max, pre_max, max_steps)(
                self.params, self._cache, self._pos, self._nxt, self._rem,
                jnp.asarray(prompts), jnp.asarray(lens), jnp.asarray(gens),
                pk, pv, jnp.asarray(pre_lens), jnp.int32(n))
        self._cache, self._pos, self._nxt, self._rem = out[:4]
        return _PendingSegment(paged=False, picked=picked, n=n, now=now,
                               prefix_cache=prefix_cache, dev=out[4:],
                               pre_lens=pre_lens,
                               full_prompts=[f for f, _ in fulls])

    def _finish_segment_dense(self, p: _PendingSegment) -> dict:
        picked, n, prefix_cache, pre_lens = (p.picked, p.n, p.prefix_cache,
                                             p.pre_lens)
        # THE per-segment sync: the one place the online serve loop is
        # allowed to block on the device (audited — see analysis.syncs;
        # the budget pins it to exactly one per segment)
        with allowed_sync("serving.segment_event_fetch"):
            toks, aq, aslot, steps, qadm = jax.device_get(p.dev)
        steps, qadm = int(steps), int(qadm)
        self.last_run_ticks += steps
        self.last_run_chunks += 1

        admitted, first_tokens, finished, new_tokens, eos_stops = \
            self._replay_segment(picked, toks, aq, aslot, steps, n)
        if qadm < n:
            # step budget ran out before every picked request found a
            # slot: back to the queue head, FCFS order preserved
            for r in picked[qadm:]:
                r.admit_time = 0.0
            self._queue[:0] = picked[qadm:]

        # prefix-cache population: insert each admitted request's full
        # prompt KV (block-trimmed device slices of the slot cache —
        # rows [0, plen) hold exactly the prompt's keys until the slot
        # is reused, and insertion right after the sync precedes any
        # donation of this cache buffer)
        if prefix_cache is not None:
            last_admit = {}                # slot -> its latest admit event
            for st in range(steps):
                q = int(aq[st])
                if q < n:
                    last_admit[int(aslot[st])] = q
            for s, q in last_admit.items():
                fp = p.full_prompts[q]     # the span actually prefilled
                plen_b = prefix_cache.round_down(len(fp))
                if plen_b > int(pre_lens[q]):
                    prefix_cache.insert(
                        fp[:plen_b],
                        self._cache["k"][:, s, :plen_b],
                        self._cache["v"][:, s, :plen_b])

        self._segment_telemetry(steps, admitted, finished, eos_stops,
                                new_tokens, max(0, n - qadm))
        return {"steps": steps, "admitted": admitted,
                "first_tokens": first_tokens, "finished": finished,
                "tokens": new_tokens}

    # --- paged segments (r11: page-table KV, inference/paged_kv.py) -------
    def _paged_segment_prog(self, n_pad: int, s_max: int, max_steps: int):
        """``_segment_prog`` over the PAGED pool: same while_loop, same
        event log, same one-dispatch/one-fetch contract — three changes:

        * slot KV state is (pool, page_table) instead of a contiguous
          block; both are donated and updated in place;
        * the admit branch INSTALLS the request's host-reserved page
          list into the slot's table row and prefills the suffix
          directly into those pages (``llama.forward_with_pages``) —
          shared-prefix rows are already resident in the shared pages,
          so a hit contributes ZERO KV row copies to the program (the
          contiguous segment's pre_k/pre_v staging tensors and their
          dynamic_update_slice writes do not exist here);
        * the decode branch passes the live mask so retired slots'
          writes route to the trash page.

        The memo key carries NO prefix width: prefix geometry is page
        DATA (pre_lens + tables), not shape — a shared-prefix workload
        adds zero program shapes (one fewer recompile hazard than the
        contiguous engine's ("seg", ..., pre_max, ...) family).

        r17 (ISSUE 12): with ``quality_digest`` the program family is
        ("qseg", n_pad, s_max, steps) — same loop, same single fetch,
        but the event log additionally carries per-step per-slot logit
        digests (the emitted token's logit + the tick's top-k ids and
        values, fp32) computed in-program from logits the tick already
        produced. Digest arrays are [steps, slots(, k)] — bytes per
        tick are (1 + 2k) * 4 * slots, invisible next to the weight
        stream (SCALING §3l) — and ride the SAME audited fetch, so the
        one-dispatch/one-fetch contract is untouched (the
        quality_serving_segment gate program pins it)."""
        if self.quant:
            # r21: the quantized engine's segments are a DTYPE AXIS on
            # the paged family — the program BODY is identical (the
            # narrow pool dtype + scale planes flow through
            # llama.forward_with_pages from the donated pool operand);
            # the axis exists so the coverage auditor enumerates and
            # warms the quantized rungs separately (their compiled
            # programs differ, so their keys must too). quality_digest
            # composes: the digest columns certify the rollout.
            from ..quantization.serving import QUANT_CODES

            key = PROGRAM_SPACE.key("qpseg", n_pad=n_pad, s_max=s_max,
                                    steps=max_steps,
                                    dtype=QUANT_CODES[self.quant])
            return self._memo_prog(
                key, lambda: self._build_paged_segment_prog(
                    n_pad, s_max, max_steps,
                    digest_k=(self.digest_top_k if self.quality_digest
                              else 0)))
        if self.quality_digest:
            key = PROGRAM_SPACE.key("qseg", n_pad=n_pad, s_max=s_max,
                                    steps=max_steps)
            return self._memo_prog(
                key, lambda: self._build_paged_segment_prog(
                    n_pad, s_max, max_steps,
                    digest_k=self.digest_top_k))
        key = PROGRAM_SPACE.key("pseg", n_pad=n_pad, s_max=s_max,
                                steps=max_steps)
        return self._memo_prog(key, lambda: self._build_paged_segment_prog(
            n_pad, s_max, max_steps))

    def _build_paged_segment_prog(self, n_pad: int, s_max: int,
                                  max_steps: int, digest_k: int = 0):
        cfg, slots, eos = self.cfg, self.slots, self.eos
        max_pages = self.pager.max_pages

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def segment(params, pool, ptab, pos, nxt, rem, prompts, lens,
                    gens, pre_lens, req_tables, n_real):
            i32 = jnp.int32
            st = dict(
                pool=pool, pt=ptab, pos=pos, nxt=nxt, rem=rem,
                out=jnp.zeros((max_steps, slots), i32),
                aq=jnp.full((max_steps,), n_pad, i32),    # n_pad = decode
                aslot=jnp.zeros((max_steps,), i32),
                qidx=i32(0), step=i32(0),
            )
            if digest_k:
                # r17 logit digests: emitted-token logit + top-k
                # (ids, values) per step/slot — fp32 event-log columns
                # the host replay distributes per request
                st.update(
                    dlg=jnp.zeros((max_steps, slots), jnp.float32),
                    dti=jnp.zeros((max_steps, slots, digest_k), i32),
                    dtv=jnp.zeros((max_steps, slots, digest_k),
                                  jnp.float32),
                )

            def cond(st):
                work = jnp.any(st["rem"] > 0) | (st["qidx"] < n_real)
                return work & (st["step"] < max_steps)

            def admit(st):
                s = jnp.argmin(st["rem"])          # a rem==0 slot
                q = st["qidx"]
                row = jax.lax.dynamic_slice(req_tables, (q, 0),
                                            (1, max_pages))
                prow = jax.lax.dynamic_slice(prompts, (q, 0), (1, s_max))
                ln = lens[q]
                pln = pre_lens[q]
                # suffix-only prefill AT context offset pln: queries sit
                # at positions pln..pln+s_max-1 and attend the shared
                # prefix pages in place — the prefix's quadratic
                # attention, its per-token matmuls AND its KV writes are
                # all skipped
                logits, pool = llama.forward_with_pages(
                    params, prow, cfg, st["pool"], row,
                    jnp.reshape(pln, (1,)), logit_pos=ln - 1)
                t0 = jnp.argmax(logits, axis=-1).astype(i32).reshape(())
                rem_new = gens[q] - 1
                if eos is not None:
                    rem_new = jnp.where(t0 == eos, 0, rem_new)
                new = dict(
                    pool=pool,
                    pt=st["pt"].at[s].set(row[0]),
                    pos=st["pos"].at[s].set(pln + ln),
                    nxt=st["nxt"].at[s].set(t0),
                    rem=st["rem"].at[s].set(rem_new),
                    out=st["out"].at[st["step"], s].set(t0),
                    aq=st["aq"].at[st["step"]].set(q),
                    aslot=st["aslot"].at[st["step"]].set(s),
                    qidx=q + 1, step=st["step"],
                )
                if digest_k:
                    lg = logits.astype(jnp.float32)       # [1, V]
                    tv, ti = jax.lax.top_k(lg, digest_k)
                    el = jnp.take_along_axis(
                        lg, t0.reshape(1, 1), axis=-1)[0, 0]
                    new["dlg"] = st["dlg"].at[st["step"], s].set(el)
                    new["dti"] = st["dti"].at[st["step"], s].set(ti[0])
                    new["dtv"] = st["dtv"].at[st["step"], s].set(tv[0])
                return new

            def decode(st):
                live = st["rem"] > 0
                logits, pool = llama.forward_with_pages(
                    params, st["nxt"][:, None], cfg, st["pool"],
                    st["pt"], st["pos"], live=live)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(live, tok, st["nxt"])
                rem = st["rem"] - live.astype(jnp.int32)
                if eos is not None:
                    rem = jnp.where(live & (tok == eos), 0, rem)
                new = dict(
                    pool=pool, pt=st["pt"],
                    pos=st["pos"] + live.astype(jnp.int32),
                    nxt=tok, rem=rem,
                    out=st["out"].at[st["step"]].set(tok),
                    aq=st["aq"], aslot=st["aslot"],
                    qidx=st["qidx"], step=st["step"],
                )
                if digest_k:
                    lg = logits.astype(jnp.float32)       # [slots, V]
                    tv, ti = jax.lax.top_k(lg, digest_k)
                    el = jnp.take_along_axis(lg, tok[:, None],
                                             axis=-1)[:, 0]
                    new["dlg"] = st["dlg"].at[st["step"]].set(el)
                    new["dti"] = st["dti"].at[st["step"]].set(ti)
                    new["dtv"] = st["dtv"].at[st["step"]].set(tv)
                return new

            def body(st):
                can_admit = (st["qidx"] < n_real) & jnp.any(st["rem"] == 0)
                st = jax.lax.cond(can_admit, admit, decode, st)
                st["step"] = st["step"] + 1
                return st

            st = jax.lax.while_loop(cond, body, st)
            outs = (st["pool"], st["pt"], st["pos"], st["nxt"], st["rem"],
                    st["out"], st["aq"], st["aslot"])
            if digest_k:
                outs += (st["dlg"], st["dti"], st["dtv"])
            return outs + (st["step"], st["qidx"])

        return segment

    # --- chunked prefill (r13: bounded time-between-tokens) ----------------

    def _prefill_chunk_for(self, s_max: int) -> int:
        """Chunk width for a segment whose admit window is ``s_max``
        wide: the smallest ladder entry that bounds a full-width prefill
        at ``program_space.MAX_PREFILL_CHUNKS`` chunk steps — short
        windows get tight time-between-tokens, long ones a bounded step
        count, and every width is DECLARED (a finite ("cseg", ..)
        program-key family; a floating chunk width would re-open the
        mid-serve-compile hazard the bucket pinning closed). The cap
        matters for ADMISSION throughput too: a prefill may only start
        while 2 x chunks steps remain in the segment budget, so a finer
        ladder narrows the start window and long prompts begin to
        monopolize segment heads (measured on the overload lane —
        8-chunk prefills throttled admission to one start per segment).

        r20: the arithmetic lives in ``program_space.chunk_for`` — ONE
        copy shared by dispatch and the ``cseg`` family's static
        enumerator, so coverage can never drift from the runtime."""
        return chunk_for(self.prefill_chunks, s_max)

    def _chunked_segment_prog(self, n_pad: int, s_max_c: int, C: int,
                              max_steps: int):
        """``_paged_segment_prog`` with the admit branch split into
        ``C``-token prefill chunks INTERLEAVED with decode ticks: a
        long prompt no longer stalls every co-resident decode for its
        whole prefill — between consecutive chunks the running slots
        each emit a token, so time-between-tokens is bounded by ONE
        chunk's cost (the ISSUE 8 TTFT-p99-spike fix; ROADMAP item 4).
        Same pool/page-table state, same event log, same single fetch:

        * in-program prefill PROGRESS state (``pf``/``pfq``/``pfo``): at
          most one slot is mid-prefill; each chunk step prefills tokens
          [pfo, pfo+C) of its suffix at context offset pre_len+pfo —
          exactly the q_len>1 page-indirect path the unified kernel
          already serves (``llama.forward_with_pages``), so no new
          kernel work exists here, only scheduling;
        * the FINAL chunk samples the first token and emits the admit
          event; non-final chunk steps log ``aq = n_pad + 1`` (the
          chunk marker) and the host replay skips them — the replay
          contract is unchanged;
        * a prefill only STARTS if its 2*ceil(len/C) worst-case step
          cost fits the remaining budget, so a segment never ends with
          a half-prefilled slot (no cross-segment prefill state to
          carry; un-started requests requeue exactly as before);
        * ``phase`` alternates chunk/decode steps while anything is
          live, and chunks run back-to-back when nothing is decoding
          (nobody is waiting on a token, so interleaving would only
          add latency).

        ``s_max_c`` is the admit window rounded up to a chunk multiple
        (slices never clamp); memo key ("cseg", n_pad, s_max_c, C,
        max_steps) with C from the declared ladder."""
        if s_max_c % C:
            raise ValueError(f"admit window {s_max_c} is not a multiple "
                             f"of the prefill chunk {C}")
        key = PROGRAM_SPACE.key("cseg", n_pad=n_pad, s_max=s_max_c, c=C,
                                steps=max_steps)
        return self._memo_prog(key, lambda: self._build_chunked_segment_prog(
            n_pad, s_max_c, C, max_steps))

    def _build_chunked_segment_prog(self, n_pad: int, s_max_c: int, C: int,
                                    max_steps: int):
        cfg, slots, eos = self.cfg, self.slots, self.eos
        max_pages = self.pager.max_pages

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def segment(params, pool, ptab, pos, nxt, rem, prompts, lens,
                    gens, pre_lens, req_tables, n_real):
            i32 = jnp.int32
            st = dict(
                pool=pool, pt=ptab, pos=pos, nxt=nxt, rem=rem,
                out=jnp.zeros((max_steps, slots), i32),
                aq=jnp.full((max_steps,), n_pad, i32),    # n_pad = decode
                aslot=jnp.zeros((max_steps,), i32),
                pf=i32(-1),      # slot mid-prefill (-1 = none)
                pfq=i32(0),      # its queue row
                pfo=i32(0),      # suffix tokens already prefilled
                phase=i32(0),    # 1 = just chunked -> decode next
                qidx=i32(0), step=i32(0),
            )

            def _startable(st):
                # a new prefill may begin only if its worst-case step
                # cost (chunks + interleaved decodes) fits the budget
                ln = lens[jnp.minimum(st["qidx"], n_pad - 1)]
                chunks = (ln + C - 1) // C
                return ((st["qidx"] < n_real)
                        & (st["step"] + 2 * chunks <= max_steps))

            def cond(st):
                work = (jnp.any(st["rem"] > 0) | (st["pf"] >= 0)
                        | _startable(st))
                return work & (st["step"] < max_steps)

            def chunk(st):
                starting = st["pf"] < 0
                s = jnp.where(starting,
                              jnp.argmin(st["rem"]).astype(jnp.int32),
                              st["pf"])
                q = jnp.where(starting, st["qidx"], st["pfq"])
                off = jnp.where(starting, 0, st["pfo"])
                row = jax.lax.dynamic_slice(req_tables, (q, 0),
                                            (1, max_pages))
                # installing the table row is idempotent across chunks
                pt = st["pt"].at[s].set(row[0])
                ln = lens[q]
                pln = pre_lens[q]
                ctok = jax.lax.dynamic_slice(prompts, (q, off), (1, C))
                # one C-token prefill chunk at context offset pln+off —
                # queries attend the shared prefix AND earlier chunks in
                # place through the page table, so chunked == one-shot
                # prefill mathematically (token-parity-tested)
                logits, pool = llama.forward_with_pages(
                    params, ctok, cfg, st["pool"], row,
                    jnp.reshape(pln + off, (1,)),
                    logit_pos=jnp.minimum(ln - 1 - off, C - 1))
                done = off + C >= ln
                t0 = jnp.argmax(logits, axis=-1).astype(i32).reshape(())
                rem_new = gens[q] - 1
                if eos is not None:
                    rem_new = jnp.where(t0 == eos, 0, rem_new)
                return dict(
                    pool=pool, pt=pt,
                    pos=jnp.where(done, st["pos"].at[s].set(pln + ln),
                                  st["pos"]),
                    nxt=jnp.where(done, st["nxt"].at[s].set(t0),
                                  st["nxt"]),
                    rem=jnp.where(done, st["rem"].at[s].set(rem_new),
                                  st["rem"]),
                    out=jnp.where(done,
                                  st["out"].at[st["step"], s].set(t0),
                                  st["out"]),
                    aq=st["aq"].at[st["step"]].set(
                        jnp.where(done, q, i32(n_pad + 1))),
                    aslot=st["aslot"].at[st["step"]].set(s),
                    pf=jnp.where(done, i32(-1), s),
                    pfq=q, pfo=off + C, phase=i32(1),
                    qidx=jnp.where(starting, st["qidx"] + 1, st["qidx"]),
                    step=st["step"],
                )

            def decode(st):
                live = st["rem"] > 0
                logits, pool = llama.forward_with_pages(
                    params, st["nxt"][:, None], cfg, st["pool"],
                    st["pt"], st["pos"], live=live)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(live, tok, st["nxt"])
                rem = st["rem"] - live.astype(jnp.int32)
                if eos is not None:
                    rem = jnp.where(live & (tok == eos), 0, rem)
                return dict(
                    pool=pool, pt=st["pt"],
                    pos=st["pos"] + live.astype(jnp.int32),
                    nxt=tok, rem=rem,
                    out=st["out"].at[st["step"]].set(tok),
                    aq=st["aq"], aslot=st["aslot"],
                    pf=st["pf"], pfq=st["pfq"], pfo=st["pfo"],
                    phase=i32(0),
                    qidx=st["qidx"], step=st["step"],
                )

            def body(st):
                live_any = jnp.any(st["rem"] > 0)
                pf_active = st["pf"] >= 0
                can_start = ((~pf_active) & jnp.any(st["rem"] == 0)
                             & _startable(st))
                do_chunk = ((pf_active | can_start)
                            & ((st["phase"] == 0) | ~live_any))
                st = jax.lax.cond(do_chunk, chunk, decode, st)
                st["step"] = st["step"] + 1
                return st

            st = jax.lax.while_loop(cond, body, st)
            return (st["pool"], st["pt"], st["pos"], st["nxt"], st["rem"],
                    st["out"], st["aq"], st["aslot"], st["step"],
                    st["qidx"])

        return segment

    # --- sequence-parallel long-context prefill (r23: ISSUE 18) -----------

    def _sp_segment_prog(self, n_pad: int, s_max_c: int, C: int,
                         max_steps: int):
        """``_chunked_segment_prog`` with the prefill chunk widened into
        an sp-row SLAB: each chunk step prefills ``sp`` consecutive
        C-token chunks as ``sp`` BATCH rows of one
        ``forward_with_pages`` call, every row writing its KV slice
        straight into the request's pages at its own absolute offset.
        The batch axis IS the sequence-parallel shard axis — under an
        'sp' mesh GSPMD runs each row on its own devices (ring/Ulysses
        attention across shards, ``ops/pallas/ring_attention.py``);
        without one it is a plain batched call. Either way the math is
        BIT-IDENTICAL to the unsharded chunked prefill: all slab rows
        scatter before any row attends (per layer), the paged gather
        window and its absolute-position masks are unchanged, so each
        query reduces over exactly the same values (the page-parity and
        token-parity tests pin this). Decode is untouched — the slab
        lands pool pages the ordinary page-indirect decode path reads,
        zero relayout at the prefill->decode boundary.

        Differences from the cseg program:

        * a prefill may SPAN segments: ``_startable`` drops the
          2*chunks budget gate (a 128k prefill never fits one segment
          by design) and the final ``pf``/``pfq``/``pfo`` progress
          state returns in the SAME single fetch — the host keeps the
          page reservation and re-dispatches the remainder as a
          continuation with ``pre_len`` advanced past the landed rows;
        * slab coverage rounds the suffix up to ``sp*C``; overrun rows
          land in reserved tail pages or the trash page and are never
          read (position-masked), and the emitted first token comes
          from the WINNER row — the one holding the suffix's true last
          token.

        Memo key ("spseg", n_pad, s_max, C, sp, steps): s_max is a
        slab-rounded ``long_buckets`` rung, C the largest declared
        prefill chunk (TBT for co-resident decodes is bounded by ONE
        slab's cost — sp*C tokens through the model, which the 'sp'
        mesh runs as C per shard)."""
        sp = self.seq_parallel
        if s_max_c % (sp * C):
            raise ValueError(f"admit window {s_max_c} is not a multiple "
                             f"of the sp slab {sp}*{C}")
        key = PROGRAM_SPACE.key("spseg", n_pad=n_pad, s_max=s_max_c, c=C,
                                sp=sp, steps=max_steps)
        return self._memo_prog(key, lambda: self._build_sp_segment_prog(
            n_pad, s_max_c, C, sp, max_steps))

    def _build_sp_segment_prog(self, n_pad: int, s_max_c: int, C: int,
                               sp: int, max_steps: int):
        cfg, slots, eos = self.cfg, self.slots, self.eos
        max_pages = self.pager.max_pages
        Cs = sp * C

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def segment(params, pool, ptab, pos, nxt, rem, prompts, lens,
                    gens, pre_lens, req_tables, n_real):
            i32 = jnp.int32
            st = dict(
                pool=pool, pt=ptab, pos=pos, nxt=nxt, rem=rem,
                out=jnp.zeros((max_steps, slots), i32),
                aq=jnp.full((max_steps,), n_pad, i32),    # n_pad = decode
                aslot=jnp.zeros((max_steps,), i32),
                pf=i32(-1),      # slot mid-prefill (-1 = none)
                pfq=i32(0),      # its queue row
                pfo=i32(0),      # suffix tokens already prefilled
                phase=i32(0),    # 1 = just chunked -> decode next
                qidx=i32(0), step=i32(0),
            )

            def _startable(st):
                # unlike cseg there is NO worst-case budget gate: a
                # long prefill is EXPECTED to span segments — progress
                # carries over through pf/pfq/pfo
                return st["qidx"] < n_real

            def cond(st):
                work = (jnp.any(st["rem"] > 0) | (st["pf"] >= 0)
                        | _startable(st))
                return work & (st["step"] < max_steps)

            def chunk(st):
                starting = st["pf"] < 0
                s = jnp.where(starting,
                              jnp.argmin(st["rem"]).astype(jnp.int32),
                              st["pf"])
                q = jnp.where(starting, st["qidx"], st["pfq"])
                off = jnp.where(starting, 0, st["pfo"])
                row = jax.lax.dynamic_slice(req_tables, (q, 0),
                                            (1, max_pages))
                # installing the table row is idempotent across chunks
                pt = st["pt"].at[s].set(row[0])
                ln = lens[q]
                pln = pre_lens[q]
                ar = jnp.arange(sp, dtype=i32)
                # one sp-row slab: row i prefills suffix tokens
                # [off+i*C, off+(i+1)*C) at absolute offset
                # pln+off+i*C through the SAME page-table row — every
                # row scatters before any row attends, so the slab is
                # bit-identical to sp sequential chunks
                slab = jax.lax.dynamic_slice(
                    prompts, (q, off), (1, Cs)).reshape(sp, C)
                logits, pool = llama.forward_with_pages(
                    params, slab, cfg, st["pool"],
                    jnp.broadcast_to(row, (sp, max_pages)),
                    pln + off + ar * C,
                    logit_pos=jnp.clip(ln - 1 - off - ar * C, 0, C - 1))
                done = off + Cs >= ln
                # the winner row holds the suffix's true last token;
                # rows past it see garbage their clamp masks out
                r_star = jnp.clip((ln - 1 - off) // C, 0, sp - 1)
                t0 = jnp.argmax(logits, axis=-1).astype(i32)[r_star]
                rem_new = gens[q] - 1
                if eos is not None:
                    rem_new = jnp.where(t0 == eos, 0, rem_new)
                return dict(
                    pool=pool, pt=pt,
                    pos=jnp.where(done, st["pos"].at[s].set(pln + ln),
                                  st["pos"]),
                    nxt=jnp.where(done, st["nxt"].at[s].set(t0),
                                  st["nxt"]),
                    rem=jnp.where(done, st["rem"].at[s].set(rem_new),
                                  st["rem"]),
                    out=jnp.where(done,
                                  st["out"].at[st["step"], s].set(t0),
                                  st["out"]),
                    aq=st["aq"].at[st["step"]].set(
                        jnp.where(done, q, i32(n_pad + 1))),
                    aslot=st["aslot"].at[st["step"]].set(s),
                    pf=jnp.where(done, i32(-1), s),
                    pfq=q, pfo=off + Cs, phase=i32(1),
                    qidx=jnp.where(starting, st["qidx"] + 1, st["qidx"]),
                    step=st["step"],
                )

            def decode(st):
                live = st["rem"] > 0
                logits, pool = llama.forward_with_pages(
                    params, st["nxt"][:, None], cfg, st["pool"],
                    st["pt"], st["pos"], live=live)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(live, tok, st["nxt"])
                rem = st["rem"] - live.astype(jnp.int32)
                if eos is not None:
                    rem = jnp.where(live & (tok == eos), 0, rem)
                return dict(
                    pool=pool, pt=st["pt"],
                    pos=st["pos"] + live.astype(jnp.int32),
                    nxt=tok, rem=rem,
                    out=st["out"].at[st["step"]].set(tok),
                    aq=st["aq"], aslot=st["aslot"],
                    pf=st["pf"], pfq=st["pfq"], pfo=st["pfo"],
                    phase=i32(0),
                    qidx=st["qidx"], step=st["step"],
                )

            def body(st):
                live_any = jnp.any(st["rem"] > 0)
                pf_active = st["pf"] >= 0
                can_start = ((~pf_active) & jnp.any(st["rem"] == 0)
                             & _startable(st))
                do_chunk = ((pf_active | can_start)
                            & ((st["phase"] == 0) | ~live_any))
                st = jax.lax.cond(do_chunk, chunk, decode, st)
                st["step"] = st["step"] + 1
                return st

            st = jax.lax.while_loop(cond, body, st)
            return (st["pool"], st["pt"], st["pos"], st["nxt"], st["rem"],
                    st["out"], st["aq"], st["aslot"], st["pf"],
                    st["pfq"], st["pfo"], st["step"], st["qidx"])

        return segment

    # --- speculative + sampled segments (r15: ISSUE 10, ROADMAP item 3) ---
    def _spec_segment_prog(self, n_pad: int, max_steps: int):
        """The paged segment with MULTI-TOKEN VERIFIED TICKS: every
        decode step drafts ``K = self.speculative`` tokens per live slot
        from the slot's page-resident token history (an in-program
        n-gram/prompt-suffix lookup — the draft table is built from
        segment state, zero host contact) and scores all K+1 positions
        in ONE forward pass through the unified paged q_len>1 path
        (``llama.forward_with_pages`` at the slot's context offset —
        exactly the chunked-prefill machinery, so verification adds no
        new kernel). Decode is HBM-bound (SCALING §3c: each tick streams
        the full weight set), so emitting accepted-length > 1 tokens per
        weight stream is the one lever that BEATS the roofline instead
        of approaching it (SCALING §3j).

        Acceptance is computed in-program and rolled into the event log
        (``out`` [steps, slots, K+1] + ``acc`` [steps, slots]): the host
        replay recovers per-request accepted lengths from the SAME
        single fetch — the audited one-dispatch/one-fetch contract is
        untouched. Greedy verification emits the target's argmax chain,
        so the speculative greedy stream is token-identical to the
        non-speculative engine by construction (the draft only decides
        how MANY chain tokens emit per tick, never their values). With a
        sampling config, rejection sampling against the deterministic
        (delta) draft keeps the emitted stream distributed exactly as
        non-speculative sampling; per-slot RNG keys ride segment state,
        re-seeded from the request's seed at admission.

        Admission reuses the r13 chunk branch with the chunk width C
        pinned by config (the declared ladder when ``chunked_prefill``,
        else the full admit window = one-step prefill), and the admit
        window itself is PINNED to the largest bucket — the memo key
        ("sseg", n_pad, K, steps) carries no width, so prefix hits and
        arrival jitter add zero program shapes. K = 0 with a sampling
        config is the plain SAMPLED paged segment (a verify tick over
        one position is exactly a sampled decode tick), which keeps the
        canonical paged/chunked greedy programs byte-identical."""
        K = self.speculative
        key = PROGRAM_SPACE.key("sseg", n_pad=n_pad, k=K, steps=max_steps)
        return self._memo_prog(key, lambda: self._build_spec_segment_prog(
            n_pad, K, max_steps))

    def _build_spec_segment_prog(self, n_pad: int, K: int, max_steps: int):
        cfg, slots, eos = self.cfg, self.slots, self.eos
        max_pages = self.pager.max_pages
        max_len = self.max_len
        sampling = self.sampling
        s_max = self.buckets[-1]
        if self.chunked:
            C = self._prefill_chunk_for(s_max)
            s_max = -(-s_max // C) * C
        else:
            C = s_max

        @functools.partial(jax.jit, donate_argnums=(1, 2, 6))
        def segment(params, pool, ptab, pos, nxt, rem, hist, hstart, rng,
                    prompts, lens, gens, pre_lens, req_tables, seeds,
                    n_real):
            i32 = jnp.int32
            sl = jnp.arange(slots)
            st = dict(
                pool=pool, pt=ptab, pos=pos, nxt=nxt, rem=rem,
                hist=hist, hstart=hstart, rng=rng,
                out=jnp.zeros((max_steps, slots, K + 1), i32),
                acc=jnp.zeros((max_steps, slots), i32),
                aq=jnp.full((max_steps,), n_pad, i32),    # n_pad = verify
                aslot=jnp.zeros((max_steps,), i32),
                pf=i32(-1), pfq=i32(0), pfo=i32(0), phase=i32(0),
                qidx=i32(0), step=i32(0),
            )

            def _startable(st):
                ln = lens[jnp.minimum(st["qidx"], n_pad - 1)]
                chunks = (ln + C - 1) // C
                return ((st["qidx"] < n_real)
                        & (st["step"] + 2 * chunks <= max_steps))

            def cond(st):
                work = (jnp.any(st["rem"] > 0) | (st["pf"] >= 0)
                        | _startable(st))
                return work & (st["step"] < max_steps)

            def chunk(st):
                # the admit path — the r13 chunk branch plus the spec
                # state writes: the chunk's tokens land in the slot's
                # history mirror, hstart pins the draft-scan floor at
                # the shared-prefix boundary, and a sampling engine
                # re-seeds the slot's RNG from the request seed
                starting = st["pf"] < 0
                s = jnp.where(starting,
                              jnp.argmin(st["rem"]).astype(jnp.int32),
                              st["pf"])
                q = jnp.where(starting, st["qidx"], st["pfq"])
                off = jnp.where(starting, 0, st["pfo"])
                row = jax.lax.dynamic_slice(req_tables, (q, 0),
                                            (1, max_pages))
                pt = st["pt"].at[s].set(row[0])
                ln = lens[q]
                pln = pre_lens[q]
                ctok = jax.lax.dynamic_slice(prompts, (q, off), (1, C))
                logits, pool = llama.forward_with_pages(
                    params, ctok, cfg, st["pool"], row,
                    jnp.reshape(pln + off, (1,)),
                    logit_pos=jnp.minimum(ln - 1 - off, C - 1))
                done = off + C >= ln
                if sampling is None:
                    t0 = jnp.argmax(logits, axis=-1).astype(i32).reshape(())
                    rng_new = st["rng"]
                else:
                    k0, kuse = jax.random.split(
                        jax.random.PRNGKey(seeds[q]))
                    filt = llama.sample_filter_logits(logits, *sampling)
                    t0 = jax.random.categorical(
                        kuse, filt, axis=-1).astype(i32).reshape(())
                    rng_new = st["rng"].at[s].set(k0)
                rem_new = gens[q] - 1
                if eos is not None:
                    rem_new = jnp.where(t0 == eos, 0, rem_new)
                # token history: the chunk's suffix tokens at absolute
                # positions pln+off.. (clamped into the overflow column
                # so a near-capacity admit cannot corrupt valid rows)
                hidx = jnp.minimum(pln + off + jnp.arange(C), max_len)
                hist_new = st["hist"].at[s, hidx].set(ctok[0])
                return dict(
                    pool=pool, pt=pt,
                    pos=jnp.where(done, st["pos"].at[s].set(pln + ln),
                                  st["pos"]),
                    nxt=jnp.where(done, st["nxt"].at[s].set(t0),
                                  st["nxt"]),
                    rem=jnp.where(done, st["rem"].at[s].set(rem_new),
                                  st["rem"]),
                    hist=hist_new,
                    hstart=st["hstart"].at[s].set(pln),
                    rng=jnp.where(done, rng_new, st["rng"]),
                    out=jnp.where(done,
                                  st["out"].at[st["step"], s, 0].set(t0),
                                  st["out"]),
                    acc=jnp.where(done,
                                  st["acc"].at[st["step"], s].set(1),
                                  st["acc"]),
                    aq=st["aq"].at[st["step"]].set(
                        jnp.where(done, q, i32(n_pad + 1))),
                    aslot=st["aslot"].at[st["step"]].set(s),
                    pf=jnp.where(done, i32(-1), s),
                    pfq=q, pfo=off + C, phase=i32(1),
                    qidx=jnp.where(starting, st["qidx"] + 1, st["qidx"]),
                    step=st["step"],
                )

            def verify(st):
                live = st["rem"] > 0
                pos, nxt = st["pos"], st["nxt"]
                hist, hstart = st["hist"], st["hstart"]
                if K:
                    # n-gram draft (host-free): match the running bigram
                    # (hist[pos-1], nxt) against the slot's own history;
                    # on a hit the K tokens after the LATEST match are
                    # this tick's draft, else repeat-last (acceptance 0
                    # costs nothing but the already-paid tick)
                    hcols = jnp.arange(max_len + 1)
                    prev = jnp.take_along_axis(
                        hist, jnp.maximum(pos - 1, 0)[:, None],
                        axis=1)[:, 0]
                    hprev = jnp.concatenate(
                        [jnp.zeros((slots, 1), i32), hist[:, :-1]],
                        axis=1)
                    match = ((hist == nxt[:, None])
                             & (hprev == prev[:, None])
                             & (hcols[None] >= hstart[:, None] + 1)
                             & (hcols[None] < pos[:, None]))
                    found = jnp.any(match, axis=1)
                    j = jnp.argmax(jnp.where(match, hcols[None], -1),
                                   axis=1)
                    didx = jnp.minimum(
                        j[:, None] + 1 + jnp.arange(K)[None],
                        jnp.maximum(pos - 1, 0)[:, None])
                    drafts = jnp.take_along_axis(hist, didx, axis=1)
                    drafts = jnp.where(found[:, None], drafts,
                                       nxt[:, None])
                else:
                    drafts = jnp.zeros((slots, 0), i32)
                # ONE verify tick over all K+1 positions per slot: the
                # paged q_len>1 path at each slot's context offset —
                # the same single weight stream a 1-token tick pays
                x = jnp.concatenate([nxt[:, None], drafts], axis=1)
                logits, pool = llama.forward_with_pages(
                    params, x, cfg, st["pool"], st["pt"], pos,
                    live=live, logits_all=True)       # [slots, K+1, V]
                if sampling is None:
                    # greedy: the target argmax chain IS the emitted
                    # stream; drafts only gate how much of it lands
                    e = jnp.argmax(logits, axis=-1).astype(i32)
                    ok = drafts == e[:, :K]
                    rng_new = st["rng"]
                else:
                    # rejection sampling for a deterministic (delta)
                    # draft: accept d_i with prob p_i(d_i); at the
                    # first rejection resample from p_i with d_i
                    # removed; full acceptance earns the bonus token
                    # from the K+1-th distribution — emitted tokens
                    # are distributed exactly as one-at-a-time sampling
                    filt = llama.sample_filter_logits(logits, *sampling)
                    probs = jax.nn.softmax(filt, axis=-1)
                    rng_new, kuse = _split_rows(st["rng"])
                    sub = _subkeys_rows(kuse, 2 * K + 1)
                    pad_d = jnp.concatenate(
                        [drafts, nxt[:, None]], axis=1)   # ii<a never
                    if K:                                 # hits col K
                        u = _uniform_rows(sub[:, :K])
                        pd = jnp.take_along_axis(
                            probs[:, :K], drafts[..., None],
                            axis=-1)[..., 0]
                        ok = u < pd
                        onehot = jax.nn.one_hot(
                            drafts, filt.shape[-1], dtype=jnp.bool_)
                        res = _categorical_rows(
                            jnp.where(onehot, -jnp.inf, filt[:, :K]),
                            sub[:, K:2 * K])
                    else:
                        ok = jnp.zeros((slots, 0), jnp.bool_)
                        res = jnp.zeros((slots, 0), i32)
                    bonus = _categorical_rows(filt[:, K], sub[:, 2 * K])
                    a0 = jnp.cumprod(ok.astype(i32), axis=1).sum(axis=1)
                    res_all = jnp.concatenate([res, bonus[:, None]],
                                              axis=1)
                    ii = jnp.arange(K + 1)
                    e = jnp.where(ii[None] < a0[:, None], pad_d, res_all)
                a = jnp.cumprod(ok.astype(i32), axis=1).sum(axis=1)
                m = jnp.minimum(a + 1, st["rem"])  # never emit past owed
                m = jnp.where(live, m, 0)
                if eos is not None:
                    ii2 = jnp.arange(K + 1)
                    eosm = (e == eos) & (ii2[None] < m[:, None])
                    has_eos = jnp.any(eosm, axis=1)
                    m = jnp.where(
                        has_eos,
                        jnp.argmax(eosm, axis=1).astype(i32) + 1, m)
                mi = jnp.maximum(m - 1, 0)
                nxt_new = jnp.where(
                    m > 0,
                    jnp.take_along_axis(e, mi[:, None], axis=1)[:, 0],
                    nxt)
                rem_new = st["rem"] - m
                if eos is not None:
                    rem_new = jnp.where(live & has_eos, 0, rem_new)
                # history: the K+1 INPUT tokens now page-resident at
                # pos..pos+K; entries past pos+m are stale, invisible
                # to the draft scan (< pos) and overwritten before the
                # next tick's attention can see them
                hwidx = jnp.minimum(
                    pos[:, None] + jnp.arange(K + 1)[None], max_len)
                hist_new = hist.at[sl[:, None], hwidx].set(x)
                return dict(
                    pool=pool, pt=st["pt"],
                    pos=pos + m, nxt=nxt_new, rem=rem_new,
                    hist=hist_new, hstart=hstart, rng=rng_new,
                    out=st["out"].at[st["step"]].set(e),
                    acc=st["acc"].at[st["step"]].set(m),
                    aq=st["aq"], aslot=st["aslot"],
                    pf=st["pf"], pfq=st["pfq"], pfo=st["pfo"],
                    phase=i32(0),
                    qidx=st["qidx"], step=st["step"],
                )

            def body(st):
                live_any = jnp.any(st["rem"] > 0)
                pf_active = st["pf"] >= 0
                can_start = ((~pf_active) & jnp.any(st["rem"] == 0)
                             & _startable(st))
                do_chunk = ((pf_active | can_start)
                            & ((st["phase"] == 0) | ~live_any))
                st = jax.lax.cond(do_chunk, chunk, verify, st)
                st["step"] = st["step"] + 1
                return st

            st = jax.lax.while_loop(cond, body, st)
            return (st["pool"], st["pt"], st["pos"], st["nxt"], st["rem"],
                    st["hist"], st["hstart"], st["rng"],
                    st["out"], st["aq"], st["aslot"], st["acc"],
                    st["step"], st["qidx"])

        return segment

    def _dispatch_segment_paged(self, max_steps: int, prefix_cache,
                                n_pad: int, now: float) -> _PendingSegment:
        """The paged ``run_segment``: pick FCFS gated on PAGES FREE
        (admission control is memory admission — the request's page
        span is known exactly at admission since generation length is
        fixed), reserve page lists host-side, launch ONE fused paged
        segment, host-replay the shared event log with page-table
        bookkeeping hooks. Same single audited sync per segment."""
        if prefix_cache is not None and not hasattr(prefix_cache, "pager"):
            raise TypeError("paged engine requires a PagedPrefixCache "
                            "(inference/prefix_cache.py), got "
                            f"{type(prefix_cache).__name__}")
        pgr = self.pager
        psz = self.page_size
        picked: List[Request] = []
        fulls: List[np.ndarray] = []      # admission (resume) views
        req_pages: List[List[int]] = []
        pre_lens_l: List[int] = []
        tables: List[np.ndarray] = []
        deferred = 0
        while self._queue and len(picked) < n_pad:
            r = self._queue[0]
            sp_info = (self._sp_inflight.get(r.rid)
                       if self.seq_parallel else None)
            if sp_info is not None:
                # r23 long-prefill continuation: the pages were
                # reserved at first admission and the first
                # ``resident`` rows already landed in the pool — reuse
                # both (zero allocator / prefix-cache / meter traffic;
                # the reservation is HELD across the spanned segments)
                fp, _ = r.resume_view()
                row = np.zeros((pgr.max_pages,), np.int32)
                row[:len(sp_info["pages"])] = sp_info["pages"]
                self._queue.pop(0)
                if not r.admit_time:
                    r.admit_time = now
                picked.append(r)
                fulls.append(fp)
                req_pages.append(sp_info["pages"])
                pre_lens_l.append(sp_info["resident"])
                tables.append(row)
                continue
            fp, remaining = r.resume_view()
            rows = len(fp) + remaining - 1
            total = pgr.pages_needed(rows)
            hit_pages: List[int] = []
            hit_len = 0
            restored = 0
            if prefix_cache is not None:
                m = prefix_cache.match(fp)
                if m is not None and getattr(m, "tier", "hbm") != "host":
                    hit_pages, hit_len = list(m.pages), m.length
                elif m is not None:
                    # r19 tiered KV (ISSUE 14): host-tier hit —
                    # restore-on-hit is reserve + async staged upload +
                    # the normal ref-bump share. Restoring consumes free
                    # pages itself, so the WHOLE request span must fit;
                    # the pressure valve may spill colder entries first.
                    # A failed restore degrades to a plain miss (full
                    # prefill) — never an error.
                    if total > pgr.pages_free:
                        prefix_cache.evict_until(total)
                    if total <= pgr.pages_free:
                        rp = prefix_cache.restore(m.key, m.length)
                        if rp:
                            hit_pages, hit_len = rp, len(rp) * psz
                            restored = len(rp)
            need_new = total - len(hit_pages)
            if need_new > pgr.pages_free:
                if prefix_cache is not None:
                    # page-pressure valve: cached history yields LRU
                    # pages before live traffic defers; eviction may
                    # have freed the very pages the hit named, so trim
                    # the hit at the first no-longer-referenced page
                    prefix_cache.evict_until(need_new)
                    k = 0
                    while (k < len(hit_pages)
                           and pgr.allocator.ref(hit_pages[k]) > 0):
                        k += 1
                    hit_pages, hit_len = hit_pages[:k], k * psz
                    need_new = total - k
                if need_new > pgr.pages_free:
                    # FCFS: the queue head blocks, everything waits —
                    # pages free as live requests retire
                    deferred = len(self._queue)
                    if (not picked
                            and all(not p for p in pgr.slot_pages)):
                        # nothing live to free pages and nothing being
                        # admitted: the pool is pinned by references
                        # outside this engine's control — fail loudly
                        # rather than spin the serve loop forever
                        raise RuntimeError(
                            f"page pool starved: request needs "
                            f"{need_new} pages, {pgr.pages_free} free, "
                            f"no live slots to retire (pages held by an "
                            f"external prefix cache or fork?)")
                    break
            pages, row = pgr.reserve(rows, hit_pages)
            self._queue.pop(0)
            r.prefix_hit_len = hit_len
            r.admit_time = now
            r._meter_reserve(len(pages), len(pages) - len(hit_pages))
            if restored:
                # r19: bill the promotion to the request it admitted
                r.tier_pages += restored
                r.tier_bytes += (restored
                                 * prefix_cache.host_tier.page_bytes())
            picked.append(r)
            fulls.append(fp)
            req_pages.append(pages)
            pre_lens_l.append(hit_len)
            tables.append(row)
        if deferred:
            self.page_backpressure_events += 1
            _metrics.counter("serving.backpressure_pages").inc()
            _flight.record("backpressure", reason="pages",
                           deferred=deferred, pages_free=pgr.pages_free)
        n = len(picked)

        spec = bool(self.speculative or self.sampling)
        # suffix width: same pinning rule as the contiguous segment —
        # largest bucket when nothing was reused, the suffix bucket when
        # prefix hits shorten the prefill. SPEC segments always pin to
        # the largest bucket: the ("sseg", n_pad, K, steps) key family
        # deliberately carries no width, so prefix hits stay page DATA
        # and add zero program shapes.
        # r23: the segment runs the sequence-parallel slab family when
        # any picked request is a long prefill — a fresh suffix past
        # the largest regular bucket, or a continuation mid-flight.
        # Everything else (sp engines included) rides pseg/cseg
        # unchanged: sp=1 or short-only traffic degenerates exactly.
        sp_engaged = [j for j in range(n) if self.seq_parallel and (
            picked[j].rid in self._sp_inflight
            or len(fulls[j]) - pre_lens_l[j] > self.buckets[-1])]
        sp_mode = bool(sp_engaged)

        chunk_marker = None
        if sp_mode:
            # slab width: the largest declared prefill chunk per shard;
            # admit window: the largest engaged rung, slab-rounded.
            # Rungs shrink as continuations land rows, and every rung
            # at or below the first admission's is enumerated.
            C = self.prefill_chunks[-1]
            Cs = self.seq_parallel * C
            lb = max(self._long_rung(max(1, len(fulls[j]) - pre_lens_l[j]))
                     for j in sp_engaged)
            s_max = -(-lb // Cs) * Cs
            chunk_marker = n_pad + 1
        elif spec or prefix_cache is None or not any(pre_lens_l):
            s_max = self.buckets[-1]
        else:
            suf_max = max((len(fulls[j]) - pre_lens_l[j]
                           for j in range(n)), default=1)
            s_max = self._bucket_for(suf_max)

        if self.chunked and not sp_mode:
            C = self._prefill_chunk_for(s_max)
            s_max = -(-s_max // C) * C        # chunk-aligned admit window
            worst = 2 * (s_max // C)
            if max_steps < worst:
                raise ValueError(
                    f"seg_steps {max_steps} cannot fit one chunked "
                    f"prefill ({s_max // C} chunks x {C} interleaved = "
                    f"{worst} steps) — raise seg_steps or shrink the "
                    f"prompt buckets / chunk ladder")
            chunk_marker = n_pad + 1
        if spec:
            # the spec program admits through the chunk branch (one
            # full-width chunk when unchunked), so non-final chunk
            # steps log the same marker and a start needs 2*chunks of
            # step budget
            chunk_marker = n_pad + 1
            if max_steps < 2:
                raise ValueError("speculative segments need seg_steps "
                                 ">= 2 (a prefill start reserves one "
                                 "chunk + one verify step)")

        prompts = np.zeros((n_pad, s_max), np.int32)
        lens = np.ones((n_pad,), np.int32)
        gens = np.zeros((n_pad,), np.int32)   # gen 0 -> never admitted
        pre_lens = np.zeros((n_pad,), np.int32)
        req_tables = np.zeros((n_pad, pgr.max_pages), np.int32)
        seeds = np.zeros((n_pad,), np.int32)
        for j, r in enumerate(picked):
            suf = fulls[j][pre_lens_l[j]:]
            prompts[j, :len(suf)] = suf
            lens[j] = len(suf)
            gens[j] = r.max_new_tokens - len(r.tokens)
            pre_lens[j] = pre_lens_l[j]
            req_tables[j] = tables[j]
            # the slot's RNG stream derives from (request seed, tokens
            # already delivered): a fresh serve replays identically, a
            # preempt/failover resume continues from a deterministic
            # fold instead of re-playing consumed draws
            seeds[j] = (r.seed + 0x9E3779B1 * len(r.tokens)) & 0x7FFFFFFF

        if spec:
            rng = (self._rng if self._rng is not None
                   else jnp.zeros((self.slots, 2), jnp.uint32))
            with _mesh_scope(self.mesh):
                out = self._spec_segment_prog(n_pad, max_steps)(
                    self.params, pgr.pool, pgr.page_table, self._pos,
                    self._nxt, self._rem, self._hist, self._hstart, rng,
                    jnp.asarray(prompts), jnp.asarray(lens),
                    jnp.asarray(gens), jnp.asarray(pre_lens),
                    jnp.asarray(req_tables), jnp.asarray(seeds),
                    jnp.int32(n))
            pgr.pool, pgr.page_table = out[0], out[1]
            self._pos, self._nxt, self._rem = out[2:5]
            self._hist, self._hstart = out[5], out[6]
            if self._rng is not None:
                self._rng = out[7]
            return _PendingSegment(paged=True, picked=picked, n=n,
                                   now=now, prefix_cache=prefix_cache,
                                   dev=out[8:], pre_lens=pre_lens_l,
                                   req_pages=req_pages,
                                   full_prompts=fulls,
                                   chunk_marker=chunk_marker, spec=True)

        prog = (self._sp_segment_prog(n_pad, s_max, C, max_steps)
                if sp_mode
                else self._chunked_segment_prog(n_pad, s_max, C, max_steps)
                if self.chunked
                else self._paged_segment_prog(n_pad, s_max, max_steps))
        with _mesh_scope(self.mesh):
            out = prog(
                self.params, pgr.pool, pgr.page_table, self._pos, self._nxt,
                self._rem, jnp.asarray(prompts), jnp.asarray(lens),
                jnp.asarray(gens), jnp.asarray(pre_lens),
                jnp.asarray(req_tables), jnp.int32(n))
        pgr.pool, pgr.page_table = out[0], out[1]
        self._pos, self._nxt, self._rem = out[2:5]
        return _PendingSegment(paged=True, picked=picked, n=n, now=now,
                               prefix_cache=prefix_cache, dev=out[5:],
                               pre_lens=pre_lens_l, req_pages=req_pages,
                               full_prompts=fulls,
                               chunk_marker=chunk_marker,
                               digest=self.quality_digest, sp=sp_mode)

    def _finish_segment_paged(self, p: _PendingSegment) -> dict:
        picked, n, prefix_cache = p.picked, p.n, p.prefix_cache
        pre_lens_l, req_pages = p.pre_lens, p.req_pages
        pgr = self.pager
        psz = self.page_size
        # THE per-segment sync (same audited label + budget as the
        # contiguous engine: exactly one device contact per segment —
        # the spec program's acceptance counts ride the same fetch).
        # r19 tiered KV (ISSUE 14): queued host-tier stage gathers fold
        # into the SAME single device_get — the D2H spill staging costs
        # zero additional sync events by construction.
        acc = spec_stats = dig = None
        tier = getattr(prefix_cache, "host_tier", None) \
            if prefix_cache is not None else None
        staged = tier.take_pending() if tier is not None else []
        with allowed_sync("serving.segment_event_fetch"):
            payload = (p.dev if not staged
                       else (p.dev, [s[2:] for s in staged]))
            got = jax.device_get(payload)
            dev = got if not staged else got[0]
            if p.spec:
                toks, aq, aslot, acc, steps, qadm = dev
            elif p.digest:
                # r17: digest columns ride the SAME single fetch — the
                # per-segment sync count is unchanged (audited)
                toks, aq, aslot, dlg, dti, dtv, steps, qadm = dev
                dig = (dlg, dti, dtv)
            elif p.sp:
                # r23: the prefill-progress triple rides the SAME
                # single fetch — a long prefill the step budget cut
                # mid-flight resumes next dispatch at row pfo
                toks, aq, aslot, sp_pf, sp_pfq, sp_pfo, steps, qadm = dev
            else:
                toks, aq, aslot, steps, qadm = dev
        if staged:
            tier.complete(staged, got[1])
        steps, qadm = int(steps), int(qadm)
        self.last_run_ticks += steps
        self.last_run_chunks += 1
        if p.spec:
            spec_stats = {"proposed": 0, "accepted": 0, "emitted": 0,
                          "verify_steps": 0, "slot_ticks": 0}

        # page bookkeeping rides the SHARED replay via hooks; retired
        # slots' releases are DEFERRED past the prefix-cache inserts so
        # harvest-by-reference can still retain a finished request's
        # prompt pages
        pending_frees: List[List[int]] = []

        def on_admit(q, s):
            pgr.install(s, req_pages[q])

        def on_retire(r, s):
            r._meter_release()
            pending_frees.append(pgr.slot_pages[s])
            pgr.slot_pages[s] = []

        admitted, first_tokens, finished, new_tokens, eos_stops = \
            self._replay_segment(picked, toks, aq, aslot, steps, n,
                                 on_admit, on_retire,
                                 chunk_marker=p.chunk_marker,
                                 acc=acc, spec_stats=spec_stats, dig=dig)
        if p.chunk_marker is not None:
            chunk_steps = int(np.sum(np.asarray(aq[:steps])
                                     >= p.chunk_marker))
            if chunk_steps:
                _metrics.counter("serving.prefill_chunks").inc(chunk_steps)
        if p.sp:
            # completed admissions retire their carry-over entries; a
            # prefill the budget cut mid-flight re-registers below
            for r in picked:
                self._sp_inflight.pop(r.rid, None)
        if p.sp and int(sp_pf) >= 0:
            # r23 multi-segment prefill: keep the mid-flight request's
            # reservation AND meter open (its pages hold landed KV
            # rows), record the resident row count, and requeue it at
            # the head so the next dispatch continues the slab stream;
            # everything behind it releases and requeues as usual
            j = int(sp_pfq)
            assert qadm == j + 1, (
                f"sp prefill progress desynced: pf row {j}, qadm {qadm}")
            self._sp_inflight[picked[j].rid] = {
                "pages": req_pages[j],
                "resident": pre_lens_l[j] + int(sp_pfo)}
            for k in range(qadm, n):
                picked[k].admit_time = 0.0
                picked[k]._meter_release()
                pgr.release_pages(req_pages[k])
            _flight.record("sp_carryover", rid=picked[j].rid,
                           resident=pre_lens_l[j] + int(sp_pfo),
                           total=len(p.full_prompts[j]))
            self._queue[:0] = picked[j:]
        elif qadm < n:
            # step budget ran out before every picked request found a
            # slot: release the reservations and requeue FCFS
            for j in range(qadm, n):
                picked[j].admit_time = 0.0
                picked[j]._meter_release()
                pgr.release_pages(req_pages[j])
            self._queue[:0] = picked[qadm:]

        # prefix-cache population: harvest BY REFERENCE — retain the
        # admitted request's prompt-spanning pages (zero row copies; the
        # cache and the slot share physical pages from this moment)
        if prefix_cache is not None:
            last_admit = {}                # slot -> its latest admit event
            for st in range(steps):
                q = int(aq[st])
                if q < n:
                    last_admit[int(aslot[st])] = q
            for s, q in last_admit.items():
                fp = p.full_prompts[q]     # the span actually prefilled
                plen_b = prefix_cache.round_down(len(fp))
                if plen_b > pre_lens_l[q]:
                    prefix_cache.insert(fp[:plen_b],
                                        req_pages[q][:plen_b // psz])
        for pages in pending_frees:
            pgr.release_pages(pages)
        pgr._gauges()

        if spec_stats is not None:
            self._spec_telemetry(spec_stats)
        self._segment_telemetry(steps, admitted, finished, eos_stops,
                                new_tokens, max(0, n - qadm))
        out = {"steps": steps, "admitted": admitted,
               "first_tokens": first_tokens, "finished": finished,
               "tokens": new_tokens}
        if spec_stats is not None:
            out["spec"] = spec_stats
        return out

    def collect_finished(self) -> Dict[int, List[int]]:
        """Drain the finished list (segment mode's result channel),
        truncating at max_new_tokens / first EOS like run()."""
        done = {}
        for r in self._finished:
            toks = r.tokens[:r.max_new_tokens]
            if self.eos is not None and self.eos in toks:
                toks = toks[:toks.index(self.eos) + 1]
            r.tokens = toks
            if r.digests is not None:
                r.digests = r.digests[:len(toks)]  # stay index-aligned
            done[r.rid] = toks
            self.last_latencies[r.rid] = r.finish_time - r.submit_time
        self._finished = []
        return done

    # --- the engine loop --------------------------------------------------
    def _chunks_until_sync(self) -> int:
        """How many decode chunks to issue before the next host sync.

        Retirement times are HOST-KNOWN absent EOS (rem counts are fixed
        at admission), so the host can run the device ahead to exactly
        the point where the refill hysteresis would admit new work — no
        per-chunk fetch needed. With EOS enabled, in-program freezing
        keeps results exact but a frozen slot idles until the host
        notices, so the run-ahead is capped to bound the waste."""
        rems = sorted(self._rem_host[s] for s in range(self.slots)
                      if self._active[s] is not None)
        if not rems:
            return 0
        if self._queue:
            threshold = min(4, self.slots, len(self._queue))
            free_now = self.slots - len(rems)
            need = min(max(threshold - free_now, 1), len(rems))
            target = rems[need - 1]
        else:
            target = rems[-1]  # no queue: drain every active slot
        n = -(-target // self.chunk)
        if self.eos is not None:
            n = min(n, 4)  # EOS can freeze slots the host can't see yet
        return n

    def _sync(self, admits: List[tuple], chunk_toks: List[object]) -> None:
        """ONE batched device->host fetch for a whole window (admit tok0s
        + every decode chunk's [K, slots] tokens), then distribute
        chronologically: a slot admitted this window consumes its tok0
        first, then the chunk ticks. Tokens after a slot's remaining
        count or its first EOS are in-program frozen repeats and are
        dropped."""
        if not admits and not chunk_toks:
            return
        fetched = jax.device_get([[a[0] for a in admits], chunk_toks])
        tok0s, toks = fetched
        for (_, pairs), t0 in zip(admits, tok0s):
            for (r, s), t in zip(pairs, np.asarray(t0).tolist()):
                r.tokens.append(int(t))
                hit_eos = self.eos is not None and int(t) == self.eos
                if r.done or hit_eos:
                    if self._active[s] is r:  # not already freed host-side
                        self._active[s] = None
                    self._rem_host[s] = 0
                    self._retire(r)
        if toks:
            stream = np.concatenate([np.asarray(t) for t in toks], axis=0)
            ticks = stream.shape[0]
            for slot, req in enumerate(self._active):
                if req is None:
                    continue
                take = min(ticks, self._rem_host[slot])
                for k in range(take):
                    t = int(stream[k, slot])
                    req.tokens.append(t)
                    self._rem_host[slot] -= 1
                    if self.eos is not None and t == self.eos:
                        self._rem_host[slot] = 0
                        break
                if self._rem_host[slot] == 0:
                    self._retire(req)
                    self._active[slot] = None

    def run(self, fused: bool = True) -> Dict[int, List[int]]:
        """Drain the queue: continuous batching until every request is
        served. Returns rid -> generated tokens (greedy, incl. the first
        token sampled at prefill).

        ``fused=True`` (default): the whole drain compiles into ONE
        program — in-program admission + slot freeze, one dispatch + one
        fetch total (see ``_drain_prog``). The windowed host loop below
        (``fused=False``) remains for incremental serving on top of an
        already-partial slot state; it batches its host reads per
        admission window: admission programs plus every decode chunk up
        to the next host-known refill point issue without reading
        anything back (chunks chain device-side through jax async
        dispatch) and the window ends in ONE batched fetch."""
        if self.paged or self.mesh is not None:
            # paged and mp-sharded engines drain through the segment path
            # (the online product's loop): same greedy in-program
            # admission, one dispatch + one fetch per segment
            self.last_run_ticks = 0
            self.last_run_chunks = 0
            self.last_latencies = {}
            while self._queue or any(r is not None for r in self._active):
                self.run_segment(4 * self.chunk)
            return self.collect_finished()
        if fused and self._queue and \
                all(r is None for r in self._active):
            return self._run_fused()
        self.last_run_chunks = 0
        admits: List[tuple] = []
        self._fill_slots(admits)
        while any(r is not None for r in self._active):
            chunk_toks: List[object] = []
            for _ in range(self._chunks_until_sync()):
                out = self._decode_prog(self.params, self._cache, self._pos,
                                        self._nxt, self._rem)
                self.last_run_chunks += 1
                self._cache, self._pos, self._nxt, self._rem, toks = out
                chunk_toks.append(toks)
            self._sync(admits, chunk_toks)
            admits = []
            self._fill_slots(admits)
        self._sync(admits, [])  # tail: admits whose requests all retired
        self.last_run_ticks = self.last_run_chunks * self.chunk
        done = {r.rid: r.tokens[:r.max_new_tokens] for r in self._finished}
        # per-request slot latency (continuous batching's OTHER win besides
        # packing: short requests retire early instead of waiting for the
        # batch's longest) — consumed by benchmarks/serving artifacts
        self.last_latencies = {r.rid: r.finish_time - r.submit_time
                               for r in self._finished if r.finish_time}
        self._finished = []
        return done

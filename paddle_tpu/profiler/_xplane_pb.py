"""Minimal XSpace protobuf reader — the ``jax.profiler.ProfileData``
fallback for jax builds that don't ship the binding (this container's
0.4.37 exposes only ``device_memory_profile``).

The xplane file on disk is a plain ``tensorflow.profiler.XSpace`` proto;
the handful of fields the tables need (planes → lines → events with
names and times) decode with a ~60-line wire-format walker — no
tensorflow/protobuf dependency. Field numbers from
``tsl/profiler/protobuf/xplane.proto``::

    XSpace   { repeated XPlane planes = 1; }
    XPlane   { int64 id = 1; string name = 2; repeated XLine lines = 3;
               map<int64, XEventMetadata> event_metadata = 4; }
    XLine    { int64 id = 1; string name = 2; int64 timestamp_ns = 3;
               repeated XEvent events = 4; }
    XEvent   { int64 metadata_id = 1; int64 offset_ps = 2;
               int64 duration_ps = 3; }
    XEventMetadata { int64 id = 1; string name = 2; }

The facade classes mirror the ``ProfileData`` attribute surface the
table builders consume (``planes[].lines[].events[]`` with ``name`` /
``start_ns`` / ``duration_ns``).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

__all__ = ["XSpaceData"]


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """(field_number, wire_type, value) for every top-level field."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:                      # varint
            val, i = _read_varint(buf, i)
        elif wt == 1:                    # fixed64
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:                    # length-delimited
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:                    # fixed32
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


class _Event:
    __slots__ = ("name", "start_ns", "duration_ns")

    def __init__(self, name: str, start_ns: float, duration_ns: float):
        self.name = name
        self.start_ns = start_ns
        self.duration_ns = duration_ns


class _Line:
    __slots__ = ("name", "events")

    def __init__(self, name: str, events: List[_Event]):
        self.name = name
        self.events = events


class _Plane:
    __slots__ = ("name", "lines")

    def __init__(self, name: str, lines: List[_Line]):
        self.name = name
        self.lines = lines


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name = 0, ""
    for field, _wt, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            name = val.decode("utf-8", "replace")
    return mid, name


def _parse_event(buf: bytes) -> Tuple[int, int, int]:
    mid, offset_ps, duration_ps = 0, 0, 0
    for field, _wt, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            offset_ps = val
        elif field == 3:
            duration_ps = val
    return mid, offset_ps, duration_ps


def _parse_line(buf: bytes, meta: Dict[int, str]) -> _Line:
    name = ""
    timestamp_ns = 0
    raw_events: List[Tuple[int, int, int]] = []
    for field, _wt, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            timestamp_ns = val
        elif field == 4:
            raw_events.append(_parse_event(val))
    events = [_Event(meta.get(mid, f"#{mid}"),
                     timestamp_ns + offset_ps / 1e3,
                     duration_ps / 1e3)
              for mid, offset_ps, duration_ps in raw_events]
    return _Line(name, events)


def _parse_plane(buf: bytes) -> _Plane:
    name = ""
    meta: Dict[int, str] = {}
    line_bufs: List[bytes] = []
    for field, _wt, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            line_bufs.append(val)
        elif field == 4:
            # map entry { key = 1 (varint), value = 2 (XEventMetadata) }
            for f2, _w2, v2 in _fields(val):
                if f2 == 2:
                    mid, mname = _parse_event_metadata(v2)
                    meta[mid] = mname
    return _Plane(name, [_parse_line(b, meta) for b in line_bufs])


class XSpaceData:
    """``ProfileData``-shaped facade over one raw xplane.pb file."""

    def __init__(self, planes: List[_Plane]):
        self.planes = planes

    @classmethod
    def from_file(cls, path: str) -> "XSpaceData":
        with open(path, "rb") as f:
            buf = f.read()
        planes = [_parse_plane(val) for field, _wt, val in _fields(buf)
                  if field == 1]
        return cls(planes)

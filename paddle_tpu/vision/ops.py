"""``paddle.vision.ops`` — detection operators.

Reference counterpart: ``python/paddle/vision/ops.py`` over the phi
detection kernels (``nms``, ``roi_align``, ``roi_pool``, ``box_coder``,
``deform_conv2d``; SURVEY.md §2.1). TPU-native formulations: NMS as a
fixed-trip ``fori_loop`` over sorted candidates (no dynamic shapes inside
jit), RoIAlign as bilinear gathers — both compile into the XLA program
instead of the reference's dynamic-output CUDA kernels; the dynamic-size
final filtering happens on host like the reference's CPU post-process.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..ops.dispatch import run_op

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder",
           "box_area", "prior_box", "yolo_box", "distribute_fpn_proposals",
           "psroi_pool", "deform_conv2d", "DeformConv2D",
           "generate_proposals"]


def box_area(boxes, name=None):
    return run_op("box_area",
                  lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                  boxes)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU [N, M] for xyxy boxes."""

    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)

    return run_op("box_iou", f, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS. Returns kept indices sorted by score (host-side dynamic
    filtering of a compiled fixed-size suppression loop)."""
    bv = boxes._value if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = bv.shape[0]
    sv = (scores._value if isinstance(scores, Tensor)
          else (jnp.asarray(scores) if scores is not None
                else jnp.arange(n, 0, -1, dtype=jnp.float32)))
    if category_idxs is not None:
        # category-aware: offset boxes per class so cross-class pairs never
        # overlap (the standard batched-NMS trick)
        cv = (category_idxs._value if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs)).astype(bv.dtype)
        offset = (jnp.max(bv) + 1.0) * cv
        bv = bv + offset[:, None]

    order = jnp.argsort(-sv)
    bs = bv[order]

    def body(i, keep):
        # suppress every later box overlapping box i (if i itself is kept)
        lt = jnp.maximum(bs[i, :2], bs[:, :2])
        rb = jnp.minimum(bs[i, 2:], bs[:, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[:, 0] * wh[:, 1]
        area_i = (bs[i, 2] - bs[i, 0]) * (bs[i, 3] - bs[i, 1])
        areas = (bs[:, 2] - bs[:, 0]) * (bs[:, 3] - bs[:, 1])
        iou = inter / jnp.maximum(area_i + areas - inter, 1e-10)
        suppress = (iou > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~suppress

    keep0 = jnp.ones((n,), bool)
    keep = jax.lax.fori_loop(0, n, body, keep0)
    # keep is indexed by sorted position: order[j] is kept iff keep[j]
    kept_sorted = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    # int32: jax runs with x64 disabled (TPU-native default)
    return to_tensor(jnp.asarray(kept_sorted, jnp.int32))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gathers. x: [N, C, H, W]; boxes: [R, 4]
    (xyxy in input-image coords); boxes_num: rois per image."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    bn = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                    else boxes_num)
    img_ids = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)
    bv0 = boxes._value if isinstance(boxes, Tensor) else np.asarray(boxes)
    if sampling_ratio > 0:
        sr = int(sampling_ratio)
    else:
        # reference adaptive rule: ceil(roi_size / output_size), which must
        # be a trace-time constant — use the LARGEST roi so every bin is
        # sampled at least as densely as the reference would
        sizes = np.asarray(bv0, np.float32)
        max_h = float(np.max(sizes[:, 3] - sizes[:, 1])) * spatial_scale
        max_w = float(np.max(sizes[:, 2] - sizes[:, 0])) * spatial_scale
        sr = max(1, int(np.ceil(max(max_h / oh, max_w / ow))))

    def f(xv, bv):
        H, W = xv.shape[2], xv.shape[3]
        off = 0.5 if aligned else 0.0
        floor_sz = 1e-3 if aligned else 1.0  # reference clamps to 1 px

        def bilinear(img, yy, xx):
            # img: [C, H, W]; yy: [P]; xx: [Q] -> [C, P, Q]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            g = lambda yi, xi: jnp.take(
                jnp.take(img, yi.astype(jnp.int32), axis=1),
                xi.astype(jnp.int32), axis=2)
            return (g(y0, x0) * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + g(y1i, x0) * wy[None, :, None] * (1 - wx)[None, None, :]
                    + g(y0, x1i) * (1 - wy)[None, :, None] * wx[None, None, :]
                    + g(y1i, x1i) * wy[None, :, None] * wx[None, None, :])

        def one_roi(box, img_id):
            x1 = box[0] * spatial_scale - off
            y1 = box[1] * spatial_scale - off
            rw = jnp.maximum(box[2] * spatial_scale - off - x1, floor_sz)
            rh = jnp.maximum(box[3] * spatial_scale - off - y1, floor_sz)
            ys = y1 + rh * (jnp.arange(oh * sr) + 0.5) / (oh * sr)
            xs = x1 + rw * (jnp.arange(ow * sr) + 0.5) / (ow * sr)
            img = jnp.take(xv, img_id, axis=0)
            sampled = bilinear(img, ys, xs)           # [C, oh*sr, ow*sr]
            C = sampled.shape[0]
            return sampled.reshape(C, oh, sr, ow, sr).mean((2, 4))

        return jax.vmap(one_roi)(bv, img_ids)

    return run_op("roi_align", f, x, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (max) — implemented as RoIAlign-style sampling with max
    reduction (adaptive max over the roi grid)."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    bn = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                    else boxes_num)
    img_ids = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def f(xv, bv):
        H, W = xv.shape[2], xv.shape[3]
        sr = 2

        def one_roi(box, img_id):
            x1 = box[0] * spatial_scale
            y1 = box[1] * spatial_scale
            x2 = jnp.maximum(box[2] * spatial_scale, x1 + 1)
            y2 = jnp.maximum(box[3] * spatial_scale, y1 + 1)
            ys = jnp.clip(y1 + (y2 - y1) * (jnp.arange(oh * sr) + 0.5)
                          / (oh * sr), 0, H - 1).astype(jnp.int32)
            xs = jnp.clip(x1 + (x2 - x1) * (jnp.arange(ow * sr) + 0.5)
                          / (ow * sr), 0, W - 1).astype(jnp.int32)
            img = jnp.take(xv, img_id, axis=0)
            sampled = jnp.take(jnp.take(img, ys, axis=1), xs, axis=2)
            C = sampled.shape[0]
            return sampled.reshape(C, oh, sr, ow, sr).max((2, 4))

        return jax.vmap(one_roi)(bv, img_ids)

    return run_op("roi_pool", f, x, boxes)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode detection boxes against priors (reference
    ``paddle.vision.ops.box_coder``, encode/decode_center_size)."""

    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        if tb.ndim == 3:
            # [N, M, 4] targets: priors broadcast along `axis` (reference
            # decode with per-class deltas)
            exp_axis = 1 if axis == 0 else 0
            pb = jnp.expand_dims(pb, exp_axis)
            pbv = jnp.expand_dims(pbv, exp_axis)
            pw = pb[..., 2] - pb[..., 0] + norm
            ph = pb[..., 3] - pb[..., 1] + norm
            pcx = pb[..., 0] + pw / 2
            pcy = pb[..., 1] + ph / 2
            d = tb * pbv
            cx = d[..., 0] * pw + pcx
            cy = d[..., 1] * ph + pcy
            w = jnp.exp(d[..., 2]) * pw
            h = jnp.exp(d[..., 3]) * ph
            return jnp.stack([cx - w / 2, cy - h / 2,
                              cx + w / 2 - norm, cy + h / 2 - norm],
                             axis=-1)
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw / 2
            tcy = tb[:, 1] + th / 2
            out = jnp.stack([
                (tcx - pcx) / pw, (tcy - pcy) / ph,
                jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
            return out / pbv
        # decode
        d = tb * pbv
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w = jnp.exp(d[:, 2]) * pw
        h = jnp.exp(d[:, 3]) * ph
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - norm, cy + h / 2 - norm], axis=1)

    return run_op("box_coder", f, prior_box, prior_box_var, target_box)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes for one feature map (reference phi
    prior_box): per cell, one box per (min_size, aspect_ratio) plus the
    sqrt(min*max) box. Geometry is shape-only — computed host-side once,
    like the reference's CPU kernel, and returned as (boxes [H,W,P,4],
    variances [H,W,P,4]) normalized to the image."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        ms = float(ms)
        ar_boxes = [(ms * np.sqrt(ar), ms / np.sqrt(ar)) for ar in ars]
        max_box = []
        if max_sizes:
            big = np.sqrt(ms * float(max_sizes[ms_i]))
            max_box = [(big, big)]
        if min_max_aspect_ratios_order:
            # reference flag: (min, max, remaining ARs) ordering — SSD
            # checkpoints trained with it pair priors positionally
            boxes += [ar_boxes[0]] + max_box + ar_boxes[1:]
        else:
            boxes += ar_boxes + max_box
    P = len(boxes)
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    out = np.zeros((fh, fw, P, 4), np.float32)
    for p, (bw, bh) in enumerate(boxes):
        out[:, :, p, 0] = (cx[None, :] - bw / 2) / iw
        out[:, :, p, 1] = (cy[:, None] - bh / 2) / ih
        out[:, :, p, 2] = (cx[None, :] + bw / 2) / iw
        out[:, :, p, 3] = (cy[:, None] + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    from ..core.tensor import to_tensor

    return to_tensor(out), to_tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode one YOLOv3 head (reference phi yolo_box): x [N, A*(5+C), H, W]
    -> (boxes [N, A*H*W, 4] xyxy in image pixels, scores [N, A*H*W, C]).
    Low-confidence boxes are zeroed (the reference's conf_thresh gating
    keeps shapes static — exactly XLA's requirement)."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def f(xv, img):
        N, _, H, W = xv.shape
        if iou_aware:
            # reference layout: [N, A + A*(5+C), H, W] — the first A
            # channels are per-anchor IoU logits, then the standard block
            iou = jax.nn.sigmoid(xv[:, :A].reshape(N, A, H, W))
            xv = xv[:, A:]
        v = xv.reshape(N, A, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (sig(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / W
        by = (sig(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / H
        aw = anchors[:, 0][None, :, None, None]
        ah = anchors[:, 1][None, :, None, None]
        bw = jnp.exp(v[:, :, 2]) * aw / (W * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * ah / (H * downsample_ratio)
        conf = sig(v[:, :, 4])
        if iou_aware:
            # PP-YOLO rescore: conf^(1-f) * iou^f
            f_ = jnp.float32(iou_aware_factor)
            conf = jnp.power(conf, 1.0 - f_) * jnp.power(iou, f_)
        cls = sig(v[:, :, 5:]) * conf[:, :, None]
        ih = img[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = img[:, 1].astype(jnp.float32)[:, None, None, None]
        x0 = (bx - bw / 2) * iw
        y0 = (by - bh / 2) * ih
        x1 = (bx + bw / 2) * iw
        y1 = (by + bh / 2) * ih
        if clip_bbox:
            x0 = jnp.clip(x0, 0, iw - 1)
            y0 = jnp.clip(y0, 0, ih - 1)
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
        keep = (conf >= conf_thresh)[..., None]
        # stack(axis=-1) is ALREADY [N, A, H, W, 4]; rows flatten in
        # (A, H, W) order, matching the scores below
        boxes = (jnp.stack([x0, y0, x1, y1], axis=-1) * keep).reshape(
            N, -1, 4)
        scores = (cls * keep.squeeze(-1)[:, :, None]).transpose(
            0, 1, 3, 4, 2).reshape(N, -1, class_num)
        return boxes, scores

    return run_op("yolo_box", f, x, img_size)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels (reference phi distribute_fpn_proposals):
    level = floor(refer_level + log2(sqrt(area)/refer_scale)). Host-side
    (output partition is data-dependent, like the reference's CPU op)."""
    rois = np.asarray(fpn_rois.numpy(), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-12))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    from ..core.tensor import to_tensor

    outs, nums = [], []
    order = []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        order.append(idx)
        outs.append(to_tensor(rois[idx]))
        nums.append(to_tensor(np.array([len(idx)], np.int32)))
    # restore index: position of each original roi in the concatenated outs
    concat_order = np.concatenate(order) if order else np.zeros((0,))
    restore = np.argsort(concat_order).astype(np.int64)
    return outs, to_tensor(restore), nums


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference phi psroi_pool): input
    channels C = out_c * ph * pw; bin (i, j) of an RoI average-pools its
    OWN channel group — the R-FCN head."""
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    bn = [int(v) for v in np.asarray(boxes_num.numpy()).reshape(-1)]
    img_ids = np.concatenate([np.full((n,), i, np.int32)
                              for i, n in enumerate(bn)]) if bn else \
        np.zeros((0,), np.int32)

    def f(xv, bv):
        C = xv.shape[1]
        out_c = C // (ph * pw)
        H, W = xv.shape[2], xv.shape[3]

        def one_roi(box, img_id):
            x0 = box[0] * spatial_scale
            y0 = box[1] * spatial_scale
            x1 = box[2] * spatial_scale
            y1 = box[3] * spatial_scale
            rw = jnp.maximum(x1 - x0, 0.1)
            rh = jnp.maximum(y1 - y0, 0.1)
            img = xv[img_id].reshape(out_c, ph, pw, H, W)
            cols = []
            for i in range(ph):
                for j in range(pw):
                    ys = y0 + rh * i / ph
                    ye = y0 + rh * (i + 1) / ph
                    xs = x0 + rw * j / pw
                    xe = x0 + rw * (j + 1) / pw
                    yy = jnp.arange(H, dtype=jnp.float32)
                    xx = jnp.arange(W, dtype=jnp.float32)
                    my = (yy >= jnp.floor(ys)) & (yy < jnp.ceil(ye))
                    mx = (xx >= jnp.floor(xs)) & (xx < jnp.ceil(xe))
                    m = my[:, None] & mx[None, :]
                    cnt = jnp.maximum(jnp.sum(m), 1)
                    # channel group of THIS bin: [out_c, H, W]
                    grp = img[:, i, j]
                    cols.append(jnp.sum(grp * m[None], axis=(1, 2)) / cnt)
            return jnp.stack(cols, axis=1).reshape(out_c, ph, pw)

        return jax.vmap(one_roi)(bv, jnp.asarray(img_ids))

    return run_op("psroi_pool", f, x, boxes)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference phi deformable_conv):
    bilinear-sample the input at offset-shifted tap positions, then a
    plain dense contraction — the gather-based TPU formulation (the CUDA
    kernel's im2col-with-offsets becomes an explicit sampled patch
    tensor feeding one einsum on the MXU).

    mask=None → v1; mask [N, dg*kh*kw, Ho, Wo] → v2 modulation."""
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph_, pw_ = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def f(xv, ov, wv, *rest):
        N, C, H, W = xv.shape
        Co, Cg, kh, kw = wv.shape
        Ho = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
        K = kh * kw
        dg = deformable_groups
        # base tap positions [Ho, Wo, K]
        oy = jnp.arange(Ho) * sh - ph_
        ox = jnp.arange(Wo) * sw - pw_
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        base_y = jnp.broadcast_to(
            oy[:, None, None, None] + ky[None, None, :, None],
            (Ho, Wo, kh, kw)).reshape(Ho, Wo, K).astype(jnp.float32)
        base_x = jnp.broadcast_to(
            ox[None, :, None, None] + kx[None, None, None, :],
            (Ho, Wo, kh, kw)).reshape(Ho, Wo, K).astype(jnp.float32)
        # offsets [N, dg, K, 2, Ho, Wo] (reference layout: y then x)
        off = ov.reshape(N, dg, K, 2, Ho, Wo)
        sy = base_y[None, None] + off[:, :, :, 0].transpose(0, 1, 3, 4, 2)
        sx = base_x[None, None] + off[:, :, :, 1].transpose(0, 1, 3, 4, 2)
        # bilinear sample each deformable group's channels at (sy, sx):
        # [N, dg, Ho, Wo, K] sampling grid over [N, C, H, W]
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(img, yy, xx):
            # img [N, dg, Cdg, H, W]; yy/xx [N, dg, Ho, Wo, K] int
            valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yc = jnp.clip(yy, 0, H - 1)
            xc = jnp.clip(xx, 0, W - 1)
            flat = img.reshape(N, dg, -1, H * W)
            idx = (yc * W + xc).reshape(N, dg, 1, -1)
            g = jnp.take_along_axis(
                flat, jnp.broadcast_to(idx, flat.shape[:3] + (idx.shape[-1],)),
                axis=-1)
            g = g.reshape(N, dg, -1, Ho, Wo, K)
            return g * valid[:, :, None].astype(g.dtype)

        img = xv.reshape(N, dg, C // dg, H, W)
        samp = 0.0
        for dy, wyy in ((0, 1 - wy), (1, wy)):
            for dx_, wxx in ((0, 1 - wx), (1, wx)):
                g = gather(img, (y0 + dy).astype(jnp.int32),
                           (x0 + dx_).astype(jnp.int32))
                samp = samp + g * (wyy * wxx)[:, :, None]
        # v2 modulation
        if rest and mask is not None:
            mval = rest[-1].reshape(N, dg, K, Ho, Wo).transpose(0, 1, 3, 4, 2)
            samp = samp * mval[:, :, None]
        # samp [N, dg, C/dg, Ho, Wo, K] -> [N, C, K, Ho, Wo]
        samp = samp.reshape(N, C, Ho, Wo, K).transpose(0, 1, 4, 2, 3)
        # grouped contraction: weight [Co, C/groups, kh, kw]
        sampg = samp.reshape(N, groups, C // groups, K, Ho, Wo)
        wg = wv.reshape(groups, Co // groups, Cg, K)
        out = jnp.einsum("ngckhw,gock->ngohw", sampg, wg).reshape(
            N, Co, Ho, Wo)
        if bias is not None:
            # args append bias BEFORE mask: bias is always rest[0]
            out = out + rest[0].reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if bias is not None:
        args.append(bias)
    if mask is not None:
        args.append(mask)
    return run_op("deform_conv2d", f, *args)


from ..nn import initializer as _I               # noqa: E402
from ..nn.layer.layers import Layer as _Layer    # noqa: E402


class DeformConv2D(_Layer):
    """Layer form of ``deform_conv2d`` (reference
    ``paddle.vision.ops.DeformConv2D``; r7 API-residue closure): owns the
    conv weight/bias, takes the offset (and v2 mask) per call —
    ``forward(x, offset, mask=None)``."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, deformable_groups=1,
                 groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        ks = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = (in_channels // groups) * ks[0] * ks[1]
        k = 1.0 / np.sqrt(fan_in) if fan_in else 1.0
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr, default_initializer=_I.Uniform(-k, k))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=_I.Uniform(-k, k))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups,
            groups=self._groups, mask=mask)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference phi generate_proposals_v2):
    per image — decode anchor deltas, clip to the image, drop boxes
    smaller than ``min_size``, keep the pre-NMS top-N by score, NMS, keep
    the post-NMS top-N. HOST-side like the reference's CPU op: every
    stage's survivor count is data-dependent, which has no static-shape
    XLA form; serving pipelines run it between compiled stages.

    scores [N, A, H, W]; bbox_deltas [N, 4*A, H, W]; img_size [N, 2]
    (h, w); anchors [H, W, A, 4] or [H*W*A, 4]; variances same layout.
    Returns (rois [R, 4], roi_probs [R, 1]) concatenated over the batch
    (+ rois_num [N] when ``return_rois_num``).
    """
    from ..core.tensor import to_tensor

    if eta != 1.0:
        raise NotImplementedError(
            "generate_proposals: adaptive-threshold NMS (eta < 1.0) is not "
            "implemented — pass eta=1.0 (fixed nms_thresh)")
    sv = np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores,
                    np.float32)
    dv = np.asarray(bbox_deltas.numpy() if isinstance(bbox_deltas, Tensor)
                    else bbox_deltas, np.float32)
    iszv = np.asarray(img_size.numpy() if isinstance(img_size, Tensor)
                      else img_size, np.float32)
    av = np.asarray(anchors.numpy() if isinstance(anchors, Tensor)
                    else anchors, np.float32).reshape(-1, 4)
    varv = np.asarray(variances.numpy() if isinstance(variances, Tensor)
                      else variances, np.float32).reshape(-1, 4)
    N, A, H, W = sv.shape
    off = 1.0 if pixel_offset else 0.0
    # reference FilterBoxes clamps the size threshold to at least 1 px
    min_size = max(float(min_size), 1.0)

    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        # [A,H,W] -> rows in (H, W, A) order matching the anchor layout
        s = sv[n].transpose(1, 2, 0).reshape(-1)
        d = dv[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        # reference order: pre-NMS top-N by RAW score BEFORE decoding
        order = np.argsort(-s)[:int(pre_nms_top_n)]
        s, d, a_sel, v_sel = s[order], d[order], av[order], varv[order]
        aw = a_sel[:, 2] - a_sel[:, 0] + off
        ah = a_sel[:, 3] - a_sel[:, 1] + off
        acx = a_sel[:, 0] + 0.5 * aw
        acy = a_sel[:, 1] + 0.5 * ah
        dx, dy, dw, dh = (d[:, 0] * v_sel[:, 0], d[:, 1] * v_sel[:, 1],
                          d[:, 2] * v_sel[:, 2], d[:, 3] * v_sel[:, 3])
        cx = dx * aw + acx
        cy = dy * ah + acy
        # the reference clips exp inputs at log(1000/16)
        bw = np.exp(np.minimum(dw, np.log(1000.0 / 16.0))) * aw
        bh = np.exp(np.minimum(dh, np.log(1000.0 / 16.0))) * ah
        boxes = np.stack([cx - 0.5 * bw, cy - 0.5 * bh,
                          cx + 0.5 * bw - off, cy + 0.5 * bh - off], 1)
        h_img, w_img = iszv[n, 0], iszv[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, w_img - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, h_img - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        keep = (ws >= min_size) & (hs >= min_size)
        if pixel_offset:
            # reference: the box CENTER must lie inside the image
            cxs = boxes[:, 0] + 0.5 * ws
            cys = boxes[:, 1] + 0.5 * hs
            keep &= (cxs <= w_img) & (cys <= h_img)
        boxes, s = boxes[keep], s[keep]
        if len(boxes):
            kept = nms(to_tensor(boxes.astype(np.float32)),
                       iou_threshold=nms_thresh,
                       scores=to_tensor(s.astype(np.float32)))
            ki = np.asarray(kept.numpy())[:int(post_nms_top_n)]
            boxes, s = boxes[ki], s[ki]
        all_rois.append(boxes)
        all_probs.append(s[:, None])
        nums.append(len(boxes))
    rois = to_tensor(np.concatenate(all_rois, 0).astype(np.float32)
                     if all_rois else np.zeros((0, 4), np.float32))
    probs = to_tensor(np.concatenate(all_probs, 0).astype(np.float32)
                      if all_probs else np.zeros((0, 1), np.float32))
    if return_rois_num:
        return rois, probs, to_tensor(np.asarray(nums, np.int32))
    return rois, probs

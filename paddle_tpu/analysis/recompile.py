"""Recompile-hazard lint — the mid-serve XLA compile class.

r7's worst latency bug was a single stray program shape: a floating
prompt width let one segment arrive 64-wide instead of bucket-wide and
XLA compiled for 2.5 s in the middle of an online serve (vs ~60 ms of
actual work). The fix was shape pinning; this pass makes the CLASS of
bug visible before it costs a latency cliff:

* ``CompileWatch`` counts real backend compilations (via
  ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
  event) over a region. Budgets pin warm-replay compiles to ZERO — a
  warmed workload that still compiles is re-specialising on something.
* ``lint_cache_keys`` inspects a program cache's keys (the
  introspection hooks ``jit.TracedProgram.cache_info`` /
  ``jit.FusedTrainStep.cache_info`` / ``ServingEngine.cache_info``
  expose them) and flags unbucketed dynamic dims: many distinct shape
  signatures for one structurally-identical program means some input
  dim floats free and every new value will pay a fresh XLA compile.
* ``live_cache_report`` sweeps every registered live program cache
  (``jit.live_program_caches``) in one call.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["CompileWatch", "lint_cache_keys", "live_cache_report",
           "CompileBudgetError", "enforce_zero_compiles"]


class CompileWatch:
    """Count backend compilations inside the context.

    Uses the jax monitoring bus, so it sees EVERY XLA compile in the
    process — jitted framework programs, eager-op singletons, pallas
    kernels — not just the callable under audit. Warm the workload
    first; then a nonzero count during replay IS the hazard (nothing in
    a warmed loop should be compiling)."""

    _EVENT = "/jax/core/compile/backend_compile_duration"

    def __init__(self):
        self.compiles = 0
        self._baseline = 0

    def _listener(self, event: str, duration: float, **kw) -> None:
        if event == self._EVENT:
            self.compiles += 1

    def __enter__(self):
        import jax.monitoring as mon

        mon.register_event_duration_secs_listener(self._listener)
        return self

    def __exit__(self, *exc):
        from jax._src import monitoring as mon

        try:
            mon._unregister_event_duration_listener_by_callback(
                self._listener)
        except Exception:
            pass  # listener API changed: leak one no-op listener
        return False

    def mark(self) -> None:
        """Start a fresh count (end of warmup)."""
        self._baseline = self.compiles

    @property
    def since_mark(self) -> int:
        return self.compiles - self._baseline


class CompileBudgetError(AssertionError):
    """A backend compile happened inside a region pinned to zero."""


@contextlib.contextmanager
def enforce_zero_compiles(label: str = "post-warmup serve"):
    """The hard zero-post-warmup-backend-compiles budget (r20,
    ISSUE 15): after ``ServingEngine.aot_warmup`` has compiled the full
    enumerated program space, a serve that stays inside its declared
    :class:`~paddle_tpu.inference.program_space.WorkloadEnvelope` must
    perform ZERO backend compiles over the whole mixed workload —
    speculation, chunked prefill, preempt/resume, shedding, failover,
    tiers and shadow included. Any compile inside the region raises
    :class:`CompileBudgetError` (it IS the 2.5 s mid-serve latency
    cliff, caught at test time instead of at p99)::

        eng.aot_warmup(envelope)
        with analysis.recompile.enforce_zero_compiles("mixed serve"):
            scheduler.serve(trace)

    Yields the underlying :class:`CompileWatch` so callers can inspect
    the count mid-region."""
    with CompileWatch() as cw:
        yield cw
        if cw.compiles:
            raise CompileBudgetError(
                f"{cw.compiles} backend compile(s) during {label} — the "
                f"zero-post-AOT-warmup budget is 0 (a program shape "
                f"escaped the declared envelope, or warmup missed an "
                f"enumerated key)")


@dataclass
class CacheLint:
    name: str                      # program/cache identity
    n_entries: int
    n_shape_variants: int          # max distinct shape sigs per structure
    hazard: bool
    detail: str = ""
    variants: List[Any] = field(default_factory=list)


def _split_key(key: Any) -> Tuple[Any, Any]:
    """(structure, shape-signature) halves of a cache key.

    The jit caches key on ``(arg_tree, shapes, ..., training, ...)``
    with the shape signature as a tuple of ``((dims...), dtype)`` pairs;
    serving keys are ``(bucket, nb)`` / ``("seg", n_pad, s_max, pre_max,
    steps)`` — already fully bucketed, so each is its own structure."""
    if isinstance(key, tuple):
        shapes = [p for p in key
                  if isinstance(p, tuple) and p and all(
                      isinstance(e, tuple) and len(e) == 2
                      and isinstance(e[0], tuple)
                      and isinstance(e[1], str) for e in p)]
        if shapes:
            rest = tuple(p for p in key if not any(p is s for s in shapes))
            return rest, tuple(shapes)
    return key, None


def lint_cache_keys(name: str, keys: Sequence[Any],
                    max_shape_variants: int = 4) -> CacheLint:
    """Flag a program cache whose keys differ ONLY by input shapes more
    than ``max_shape_variants`` ways — the unbucketed-dynamic-dim
    signature. A cache with many structurally different entries (other
    static args, train/eval) is fine; one structure recompiled per
    arriving shape is the 2.5 s-mid-serve class."""
    by_structure: Dict[Any, set] = {}
    for k in keys:
        structure, shapes = _split_key(k)
        try:
            by_structure.setdefault(structure, set()).add(shapes)
        except TypeError:  # unhashable structure: count it solo
            by_structure.setdefault(repr(structure), set()).add(repr(shapes))
    worst = max((len(v) for v in by_structure.values()), default=0)
    hazard = worst > max_shape_variants
    detail = ""
    variants: List[Any] = []
    if hazard:
        structure = max(by_structure, key=lambda s: len(by_structure[s]))
        variants = sorted(map(repr, by_structure[structure]))
        detail = (f"{worst} shape variants compiled for one program "
                  f"structure (> {max_shape_variants}): likely an "
                  f"unbucketed dynamic dim. Shapes: "
                  + "; ".join(variants[:6])
                  + ("; ..." if len(variants) > 6 else ""))
    return CacheLint(name=name, n_entries=len(list(keys)),
                     n_shape_variants=worst, hazard=hazard, detail=detail,
                     variants=variants)


def live_cache_report(max_shape_variants: int = 4) -> List[CacheLint]:
    """Lint every live registered program cache in the process."""
    from .. import jit

    out = []
    for obj in jit.live_program_caches():
        info = obj.cache_info()
        out.append(lint_cache_keys(info["name"], info["keys"],
                                   max_shape_variants=max_shape_variants))
    return out

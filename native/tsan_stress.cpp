// ThreadSanitizer stress harness for the native runtime (tcp_store +
// data_loader queue).
//
// Reference counterpart: the reference's CI runs its C++ distributed store
// under sanitizers (SURVEY.md §5.2 "race detection"); this binary is the
// equivalent evidence for the TPU-native runtime: N client threads hammer
// one store daemon with concurrent SET/GET/ADD/WAIT/DELETE plus a
// barrier-like ADD/WAIT pattern while producer/consumer threads cycle the
// prefetch queue. Built with -fsanitize=thread (`make -C native tsan`);
// tests/test_native_launch.py runs it and fails on any TSAN report.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* tcp_store_server_start(int port);
int tcp_store_server_port(void* h);
void tcp_store_server_stop(void* h);
void* tcp_store_client_connect(const char* host, int port, int timeout_ms);
void tcp_store_client_close(void* h);
int tcp_store_set(void* h, const char* key, const uint8_t* data, int len);
int tcp_store_get(void* h, const char* key, int timeout_ms, uint8_t* buf,
                  int buflen);
long long tcp_store_add(void* h, const char* key, long long delta);
int tcp_store_wait(void* h, const char* key, int timeout_ms);
int tcp_store_delete(void* h, const char* key);
long long tcp_store_num_keys(void* h);

void* dl_queue_create(int capacity);
int dl_queue_push(void* q, const uint8_t* data, int len, int timeout_ms);
int dl_queue_pop(void* q, uint8_t* buf, int buflen, int timeout_ms);
void dl_queue_close(void* q);
void dl_queue_destroy(void* q);
}

namespace {

std::atomic<int> failures{0};

void check(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FAIL: %s\n", what);
    failures.fetch_add(1);
  }
}

void store_worker(int port, int rank, int n_ranks, int iters) {
  void* c = tcp_store_client_connect("127.0.0.1", port, 5000);
  check(c != nullptr, "client connect");
  if (!c) return;
  char key[64];
  uint8_t buf[256];
  for (int i = 0; i < iters; ++i) {
    // private key churn: set / get / delete
    std::snprintf(key, sizeof key, "k-%d-%d", rank, i % 8);
    std::string val = "v" + std::to_string(rank * 1000 + i);
    check(tcp_store_set(c, key, (const uint8_t*)val.data(),
                        (int)val.size()) == 0, "set");
    int n = tcp_store_get(c, key, 2000, buf, sizeof buf);
    check(n >= 0, "get");
    // shared counter: every rank increments the same key
    tcp_store_add(c, "shared-counter", 1);
    if (i % 16 == 0) tcp_store_delete(c, key);
    // barrier-ish generation sync every 32 iterations
    if (i % 32 == 31) {
      long long gen = i / 32;
      std::string bkey = "bar-" + std::to_string(gen);
      long long arrived = tcp_store_add(c, bkey.c_str(), 1);
      if (arrived == n_ranks) {
        std::string done = "done-" + std::to_string(gen);
        uint8_t one = 1;
        tcp_store_set(c, done.c_str(), &one, 1);
      } else {
        std::string done = "done-" + std::to_string(gen);
        check(tcp_store_wait(c, done.c_str(), 5000) == 0, "barrier wait");
      }
    }
  }
  tcp_store_client_close(c);
}

void queue_producer(void* q, int iters) {
  uint8_t blob[512];
  std::memset(blob, 7, sizeof blob);
  for (int i = 0; i < iters; ++i)
    check(dl_queue_push(q, blob, sizeof blob, 5000) == 0, "queue push");
}

void queue_consumer(void* q, int iters) {
  uint8_t buf[1024];
  for (int i = 0; i < iters; ++i)
    check(dl_queue_pop(q, buf, sizeof buf, 5000) >= 0, "queue pop");
}

}  // namespace

int main() {
  void* srv = tcp_store_server_start(0);
  if (!srv) {
    std::fprintf(stderr, "FAIL: server start\n");
    return 1;
  }
  int port = tcp_store_server_port(srv);

  const int n_ranks = 8, iters = 256;
  std::vector<std::thread> ts;
  for (int r = 0; r < n_ranks; ++r)
    ts.emplace_back(store_worker, port, r, n_ranks, iters);

  void* q = dl_queue_create(4);
  const int qiters = 2000;
  std::thread prod1(queue_producer, q, qiters);
  std::thread prod2(queue_producer, q, qiters);
  std::thread cons1(queue_consumer, q, qiters);
  std::thread cons2(queue_consumer, q, qiters);

  for (auto& t : ts) t.join();
  prod1.join();
  prod2.join();
  cons1.join();
  cons2.join();
  dl_queue_close(q);
  dl_queue_destroy(q);

  // the shared counter must equal exactly ranks x iters (atomic ADDs)
  void* c = tcp_store_client_connect("127.0.0.1", port, 5000);
  check(c != nullptr, "final verify connect");
  long long counter = 0;
  if (c) {
    uint8_t buf[64];
    int n = tcp_store_get(c, "shared-counter", 2000, buf, sizeof buf);
    if (n == 8) std::memcpy(&counter, buf, 8);  // ADD stores LE int64
    tcp_store_client_close(c);
  }
  check(counter == (long long)n_ranks * iters, "shared counter total");
  tcp_store_server_stop(srv);

  if (failures.load()) {
    std::fprintf(stderr, "tsan_stress: %d failures\n", failures.load());
    return 1;
  }
  std::printf("tsan_stress OK: %d ranks x %d iters, counter=%lld\n",
              n_ranks, iters, counter);
  return 0;
}

"""``paddle.audio.functional`` — filterbank / window math (reference:
``python/paddle/audio/functional/`` in the upstream tree; SURVEY.md treats
audio as part of the L8 python surface).

Filterbank construction is static host math (numpy); anything touching
signals goes through ``paddle_tpu.signal`` / tensor ops.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "power_to_db", "create_dct", "get_window",
]


def hz_to_mel(freq, htk: bool = False):
    """Hertz → mel. Slaney formula by default (reference default), HTK
    (2595·log10(1+f/700)) when ``htk``."""
    scalar = np.isscalar(freq)
    f = np.asarray(freq, np.float64)
    if htk:
        m = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        m = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        m = np.where(f >= min_log_hz,
                     min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz)
                     / logstep, m)
    return float(m) if scalar else m


def mel_to_hz(mel, htk: bool = False):
    scalar = np.isscalar(mel)
    m = np.asarray(mel, np.float64)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                     min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar else f


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr: int, n_fft: int):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank (librosa/reference
    convention; 'slaney' area-normalises each filter)."""
    f_max = f_max or sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    weights = np.zeros((n_mels, len(fft_f)))
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif isinstance(norm, (int, float)):
        norms = np.linalg.norm(weights, ord=norm, axis=-1, keepdims=True)
        weights = weights / np.maximum(norms, 1e-10)
    return weights.astype(np.float32)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """10·log10(spect/ref) with an optional dynamic-range floor. Runs as
    one op whose scalar constants live in the closure, so it follows the
    input's committed device (host-resident on the TPU env, where the
    upstream stft chain is host math)."""
    import jax.numpy as jnp

    from ..ops.dispatch import run_op

    x = spect if isinstance(spect, Tensor) else to_tensor(np.asarray(spect))
    offset = 10.0 * math.log10(max(amin, ref_value))

    def f(a):
        log_spec = 10.0 * jnp.log10(jnp.maximum(a, amin)) - offset
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return run_op("power_to_db", f, x)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho"):
    """[n_mels, n_mfcc] DCT-II basis (reference layout: matmul from mel)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    basis = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(2)
        basis *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return basis.astype(np.float32)


def get_window(window: str, win_length: int, fftbins: bool = True):
    """hann/hamming/blackman/bartlett/ones windows (periodic when fftbins)."""
    n = win_length + (0 if fftbins else -1)
    t = np.arange(win_length, dtype=np.float64)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * t / max(n, 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * t / max(n, 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * t / max(n, 1))
             + 0.08 * np.cos(4 * math.pi * t / max(n, 1)))
    elif window == "bartlett":
        w = 1.0 - np.abs(2 * t / max(n, 1) - 1.0)
    elif window in ("ones", "rectangular", "boxcar"):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return w.astype(np.float32)

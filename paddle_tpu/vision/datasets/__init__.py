"""Vision datasets (reference: ``python/paddle/vision/datasets/``).

This environment has **no network access**, so datasets load from a local
path when given one and otherwise fall back to a clearly-labelled
deterministic synthetic sample with the real shapes/dtypes — enough for the
training-pipeline tests and benchmarks that only need data of the right
shape (documented divergence from the reference, which downloads).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Optional

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers", "DatasetFolder", "ImageFolder"]


class MNIST(Dataset):
    """MNIST. With ``image_path``/``label_path`` reads the standard idx-ubyte
    files; otherwise generates a deterministic synthetic set (blobs per class)
    of the same shape ([1, 28, 28] float32 in [0, 1], labels int64)."""

    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform: Optional[Callable] = None, download=True,
                 backend="cv2", synthetic_size=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path or label_path:
            # both-or-neither: one supplied path with the other omitted is
            # the same typo class as a missing file — silently training on
            # synthetic blobs would mask it
            if not (image_path and label_path):
                raise ValueError(
                    f"{type(self).__name__}: image_path and label_path "
                    f"must be given together (got {image_path!r}, "
                    f"{label_path!r}); omit both for the synthetic "
                    f"offline fallback")
            if not (os.path.exists(image_path) and os.path.exists(label_path)):
                raise FileNotFoundError(
                    f"{type(self).__name__}: image_path/label_path "
                    f"({image_path!r}, {label_path!r}) do not both exist "
                    f"(omit them for the synthetic offline fallback)")
            self.images, self.labels = self._load_idx(image_path, label_path)
            self.synthetic = False
        else:
            n = synthetic_size or (6000 if self.mode == "train" else 1000)
            self.images, self.labels = self._synthesize(n)
            self.synthetic = True

    @staticmethod
    def _load_idx(image_path, label_path):
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") else open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") else open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        return images.astype("float32") / 255.0, labels

    def _synthesize(self, n):
        rng = np.random.RandomState(42 if self.mode == "train" else 43)
        labels = rng.randint(0, self.NUM_CLASSES, n).astype("int64")
        images = np.zeros((n, 28, 28), "float32")
        # one blob position per class => linearly separable synthetic digits
        for i, lab in enumerate(labels):
            cx, cy = 4 + 2 * (lab % 5) * 2, 6 + (lab // 5) * 12
            yy, xx = np.mgrid[0:28, 0:28]
            blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / 18.0))
            noise = rng.rand(28, 28) * 0.15
            images[i] = np.clip(blob + noise, 0, 1)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx][None, :, :]  # [1, 28, 28]
        label = np.asarray([self.labels[idx]], dtype="int64")
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    NUM_CLASSES = 10
    SHAPE = (3, 32, 32)
    LABEL_KEYS = (b"labels", b"fine_labels")

    ARCHIVE_SUPPORTED = True  # cifar pickle-tar parsing (Flowers opts out)

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2", synthetic_size=None):
        self.mode = mode.lower()
        self.transform = transform
        if self.ARCHIVE_SUPPORTED and data_file:
            # an EXPLICIT archive path must exist — silently training on
            # synthetic noise because of a typo'd path would look like real
            # training (the synthetic fallback is only for no-path offline
            # use)
            if not os.path.exists(data_file):
                raise FileNotFoundError(
                    f"{type(self).__name__}: data_file {data_file!r} does "
                    f"not exist (omit data_file for the synthetic offline "
                    f"fallback)")
            self.images, self.labels = self._load_archive(data_file)
            self.synthetic = False
            return
        self.synthetic = True
        n = synthetic_size or (5000 if self.mode == "train" else 1000)
        rng = np.random.RandomState(7 if self.mode == "train" else 8)
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype("int64")
        base = rng.rand(self.NUM_CLASSES, *self.SHAPE).astype("float32")
        self.images = np.clip(
            base[self.labels] + rng.rand(n, *self.SHAPE).astype("float32") * 0.3,
            0, 1,
        )

    def _load_archive(self, data_file):
        """Read the standard cifar-python tar.gz: pickled batch dicts with
        ``data`` ([N, 3072] uint8 row-major RGB) and ``labels`` /
        ``fine_labels`` (reference ``Cifar10`` reads the same archive
        member-by-member)."""
        import pickle
        import tarfile

        want_test = self.mode != "train"
        imgs, labs = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in sorted(tf.getmembers(), key=lambda m: m.name):
                base = os.path.basename(member.name)
                is_test = base.startswith("test")
                if not member.isfile() or is_test != want_test or (
                        not base.startswith(("data_batch", "test", "train"))):
                    continue
                d = pickle.load(tf.extractfile(member), encoding="bytes")
                if b"data" not in d:
                    continue
                imgs.append(np.asarray(d[b"data"], dtype=np.uint8))
                for k in self.LABEL_KEYS:
                    if k in d:
                        labs.extend(int(v) for v in d[k])
                        break
        if not imgs:
            raise ValueError(
                f"no {'test' if want_test else 'train'} batches with a "
                f"'data' field found in {data_file}")
        images = np.concatenate(imgs).reshape(-1, *self.SHAPE)
        if len(images) != len(labs):
            raise ValueError(
                f"{data_file}: {len(images)} images but {len(labs)} labels "
                f"— a batch is missing one of the {self.LABEL_KEYS} keys")
        return (images.astype("float32") / 255.0,
                np.asarray(labs, dtype="int64"))

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    NUM_CLASSES = 10


class Cifar100(_CifarBase):
    NUM_CLASSES = 100


class Flowers(_CifarBase):
    """Flowers-102 (reference ``paddle.vision.datasets.Flowers``); synthetic
    fallback in this offline image, same (3, 96, 96)/102-class geometry.
    Its real archive is a tgz of JPEGs + .mat labels — NOT the cifar pickle
    format — so the cifar archive parser is opted out and ``data_file``
    keeps the pre-existing synthetic behavior."""

    ARCHIVE_SUPPORTED = False
    NUM_CLASSES = 102
    SHAPE = (3, 96, 96)

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend="cv2",
                 synthetic_size=None):
        if data_file is not None or label_file is not None \
                or setid_file is not None:
            # loud, not silent: a user pointing at REAL flowers archives
            # must not end up training on synthetic noise (the exact
            # typo'd-path failure mode the MNIST/Cifar file parsers fixed)
            raise NotImplementedError(
                "Flowers archive parsing (tgz of JPEGs + .mat labels) is "
                "not implemented in this offline build — it falls back to "
                "synthetic data ONLY when no files are passed. Drop the "
                "data_file/label_file/setid_file arguments for synthetic "
                "mode, or use DatasetFolder on an extracted image tree.")
        n = synthetic_size or (1020 if mode.lower() == "train" else 102)
        super().__init__(data_file=None, mode=mode, transform=transform,
                         download=download, backend=backend,
                         synthetic_size=n)


class DatasetFolder(Dataset):
    """Image-folder dataset: root/<class>/<img>. Requires numpy-loadable
    images (``.npy``) or pillow if available."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"), dtype="float32") / 255.0
        except ImportError:
            raise RuntimeError(f"No loader for {path} (install pillow or use .npy)")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = [
            os.path.join(root, f) for f in sorted(os.listdir(root))
            if os.path.isfile(os.path.join(root, f))
        ]
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)

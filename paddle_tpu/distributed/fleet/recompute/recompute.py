"""Activation recomputation (gradient checkpointing).

Reference counterpart: ``python/paddle/distributed/fleet/recompute/
recompute.py`` (SURVEY.md §2.2): a PyLayer that stores inputs + RNG state in
forward, and in backward restores the RNG state, replays the forward under
grad mode, and backprops through the replay. ``recompute_sequential`` chunks
a Sequential; used by PP and sharding to bound activation memory.

TPU-native notes: on the whole-graph jit path the same feature is
``jax.checkpoint`` (used by ``paddle_tpu.models.llama`` per layer); this
module provides the eager/Layer-API equivalent with identical semantics,
including the RNG capture the reference implements with its
``get_rng_state_tracker`` save/restore.
"""

from __future__ import annotations

from typing import Any, Sequence

from ....autograd import PyLayer
from ....core.tensor import Tensor
from ....framework import random as frandom

__all__ = ["recompute", "recompute_sequential"]


def _detached(args):
    out = []
    for a in args:
        if isinstance(a, Tensor):
            d = a.detach()
            d.stop_gradient = a.stop_gradient
            out.append(d)
        else:
            out.append(a)
    return out


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` without storing intermediate activations;
    recompute them during backward.

    ``use_reentrant`` and ``preserve_rng_state`` follow the reference's
    defaults (True)."""
    preserve_rng = kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", None)
    if kwargs:
        raise ValueError(f"unsupported kwargs to recompute: {sorted(kwargs)}")

    class _Recompute(PyLayer):
        @staticmethod
        def forward(ctx, *inner_args):
            ctx.fwd_args = inner_args
            if preserve_rng:
                ctx.rng_state = frandom.get_rng_state()
            return function(*inner_args)

        @staticmethod
        def backward(ctx, *grads):
            from ....autograd import backward as autograd_backward
            from ....autograd import enable_grad

            if preserve_rng:
                saved = frandom.get_rng_state()
                frandom.set_rng_state(ctx.rng_state)
            try:
                # replay forward WITH grad tracking on detached inputs; the
                # backward accumulates into ALL leaves — including the
                # parameters ``function`` closes over — exactly like the
                # reference's in-backward paddle.autograd.backward call.
                replay_in = _detached(ctx.fwd_args)
                with enable_grad():  # PyLayer.backward runs under no_grad
                    out = function(*replay_in)
                outs = (out,) if not isinstance(out, (tuple, list)) else tuple(out)
                diff_outs = [o for o in outs if isinstance(o, Tensor)
                             and not o.stop_gradient]
                autograd_backward(diff_outs, [g for o, g in zip(outs, grads)
                                              if isinstance(o, Tensor)
                                              and not o.stop_gradient])
            finally:
                if preserve_rng:
                    frandom.set_rng_state(saved)
            # inputs unreached by the replayed backward (e.g. the function
            # only differentiates its closed-over params) get zero grads —
            # a None here would crash PyLayer's vjp wrapper
            import jax.numpy as jnp

            result = []
            for t in replay_in:
                if isinstance(t, Tensor) and not t.stop_gradient:
                    result.append(t.grad if t.grad is not None
                                  else Tensor(jnp.zeros_like(t._value),
                                              stop_gradient=True))
            return tuple(result) if len(result) != 1 else result[0]

    return _Recompute.apply(*args)


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """Chunked recompute over a Sequential (reference:
    ``recompute_sequential``): split ``functions`` into ``segments`` chunks,
    each recomputed as a unit."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else int(ctx)
    if hasattr(functions, "sublayers") and not isinstance(functions, (list, tuple)):
        layers = list(functions.children()) or [functions]
    else:
        layers = list(functions)
    n = len(layers)
    per = max(n // max(segments, 1), 1)

    def run_chunk(chunk):
        def f(*xs):
            x = xs[0] if len(xs) == 1 else xs
            for l in chunk:
                x = l(x)
            return x

        return f

    x = args
    i = 0
    while i < n:
        chunk = layers[i: i + per]
        x = recompute(run_chunk(chunk), *(x if isinstance(x, tuple) else (x,)),
                      **kwargs)
        i += per
    return x

"""Pallas flash-attention kernel parity tests — REAL TPU ONLY.

The CPU suite (conftest forces the virtual CPU platform) skips these; run
manually on the TPU env: ``python -m pytest tests/test_flash_attention_tpu.py
-q -p no:cacheprovider --noconftest`` or via the verify drive. Parity target:
the XLA reference formulation, bf16 tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.ops.pallas.flash_attention as F

pytestmark = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="pallas kernels run on TPU only")


@pytest.mark.parametrize("causal", [True, False])
# S=512 takes the single-block straight-line kernels (seq == block); S=1024
# exercises the multi-block online-softmax loop and its causal block-skip
# bounds — keep BOTH paths covered.
@pytest.mark.parametrize("S", [512, 1024])
def test_flash_fwd_bwd_parity(causal, S):
    rng = np.random.RandomState(0)
    B, H, D = 2, 4, 64
    q = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
    g = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)

    def f_pallas(q, k, v):
        out = F._flash_custom_vjp(q, k, v, causal)
        return jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32))

    def f_ref(q, k, v):
        out = F._xla_attention(q, k, v, is_causal=causal)
        return jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32))

    out_p = jax.jit(lambda q, k, v: F._flash_custom_vjp(q, k, v, causal))(
        q, k, v).astype(jnp.float32)
    out_r = F._xla_attention(q, k, v, is_causal=causal).astype(jnp.float32)
    assert float(jnp.max(jnp.abs(out_p - out_r))) < 0.03

    gp = jax.jit(jax.grad(f_pallas, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(f_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gp, gr):
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        rel = float(jnp.max(jnp.abs(a - b))) / max(
            1e-6, float(jnp.max(jnp.abs(b))))
        assert rel < 0.02, rel


class TestPackedLayout:
    """Packed flat-layout kernels ([B,S,H*D], 128//D heads per cell) must
    match the blocked [B*H,S,D] kernels they replace on eligible shapes."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("H,D", [(12, 64), (4, 128), (6, 64)])
    def test_packed_vs_blocked_parity(self, causal, H, D):
        rng = np.random.RandomState(1)
        B, S = 2, 512
        q = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
        k = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
        v = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
        g = jnp.array(rng.randn(B, S, H, D), jnp.bfloat16)
        assert F._packed_eligible(q, k)

        out_p, lse_p = jax.jit(
            lambda q, k, v: F._pallas_flash_fwd_packed(q, k, v, causal))(
                q, k, v)
        # blocked path, forced via explicit block sizes
        out_b, lse_b = jax.jit(
            lambda q, k, v: F._pallas_flash_attention(
                q, k, v, is_causal=causal, block_q=min(512, S),
                block_k=min(512, S), with_lse=True))(q, k, v)
        assert float(jnp.max(jnp.abs(out_p.astype(jnp.float32)
                                     - out_b.astype(jnp.float32)))) < 0.03

        dq_p, dk_p, dv_p = jax.jit(
            lambda q, k, v, g: F._pallas_flash_bwd_packed(
                q, k, v, g, out_p, lse_p, causal))(q, k, v, g)
        dq_b, dk_b, dv_b = jax.jit(
            lambda q, k, v, g: F._pallas_flash_bwd(
                q, k, v, g, out_b, lse_b, causal))(q, k, v, g)
        for a, b in zip((dq_p, dk_p, dv_p), (dq_b, dk_b, dv_b)):
            a = a.astype(jnp.float32)
            b = b.astype(jnp.float32)
            rel = float(jnp.max(jnp.abs(a - b))) / max(
                1e-6, float(jnp.max(jnp.abs(b))))
            assert rel < 0.02, rel

    def test_gqa_and_cross_len_stay_off_packed(self):
        rng = np.random.RandomState(2)
        q = jnp.array(rng.randn(2, 512, 8, 64), jnp.bfloat16)
        k_gqa = jnp.array(rng.randn(2, 512, 2, 64), jnp.bfloat16)
        assert F._packed_eligible(q, k_gqa) == 0  # unrepeated GQA kv
        k_short = jnp.array(rng.randn(2, 256, 8, 64), jnp.bfloat16)
        assert F._packed_eligible(q, k_short) == 0  # sq != sk (decode)

"""Sequence parallelism (Megatron-SP) utilities.

Reference counterpart: ``python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py`` (SURVEY.md §2.2 SP row, §5.7): autograd
functions ``ScatterOp``/``GatherOp``/``AllGatherOp``/``ReduceScatterOp``
that move activations between seq-sharded (outside TP matmuls) and
full-seq (inside them) layouts, plus ``mark_as_sequence_parallel_parameter``
and ``register_sequence_parallel_allreduce_hooks`` to sync LayerNorm/bias
params across the TP group.

TPU-native mapping: the four ops are **layout changes on the seq dim** over
the ``mp`` axis, expressed as sharding constraints — the VJP pairs
(scatter↔gather, all_gather↔reduce_scatter) fall out of GSPMD's transpose
rules instead of hand-written backward classes. LN-param sync is unnecessary
(params are single logical arrays; their grads already sum globally), so the
mark/hook APIs are no-op markers kept for source compatibility, and
documented as such.

``ColumnSequenceParallelLinear``/``RowSequenceParallelLinear`` compose the
same matmuls as the mp_layers versions but with seq-sharded input/output —
the layouts the reference achieves with explicit allgather/reduce-scatter.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ....ops.dispatch import run_op
from ....parallel.mesh import mesh_axis_size, named_sharding
from ..meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    _constrain,
    _on_mesh,
)

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "mark_as_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
]

# activations are [B, S, H] by convention (seq dim = 1), matching the
# reference's scatter/gather axis
_SEQ_AXIS = 1


def _seq_spec(ndim: int, axis_name: str = "mp") -> P:
    spec = [None] * ndim
    spec[_SEQ_AXIS] = axis_name
    return P(*spec)


def _full_spec(ndim: int) -> P:
    return P(*([None] * ndim))


class _SpecOp:
    """Callable matching the reference's autograd-function interface:
    ``out = ScatterOp.apply(x)``."""

    forward_spec = None  # fn(ndim) -> P

    @classmethod
    def apply(cls, x, axis_name: str = "mp"):
        return _constrain(x, cls.spec(x.ndim, axis_name))

    def __new__(cls, x, *a, **k):  # allow ScatterOp(x) call style too
        return cls.apply(x, *a, **k)


class ScatterOp(_SpecOp):
    """Full seq → seq-sharded (forward of the reference's ScatterOp; its
    backward, gather, is the GSPMD transpose)."""

    @staticmethod
    def spec(ndim, axis_name="mp"):
        return _seq_spec(ndim, axis_name)


class GatherOp(_SpecOp):
    """Seq-sharded → full seq."""

    @staticmethod
    def spec(ndim, axis_name="mp"):
        return _full_spec(ndim)


class AllGatherOp(GatherOp):
    """Alias semantics: all-gather seq shards before a TP matmul; backward
    is reduce-scatter (GSPMD transpose)."""


class ReduceScatterOp(ScatterOp):
    """Partial-sum full-seq → summed seq-sharded; backward all-gather."""


def mark_as_sequence_parallel_parameter(parameter):
    """No-op marker under GSPMD (grads of shared LN params are already
    global sums); kept so reference model code runs unchanged."""
    parameter.sequence_parallel = True
    return parameter


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               use_main_grad=False):
    """No-op under GSPMD — see mark_as_sequence_parallel_parameter."""
    return model


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel matmul taking seq-sharded input: the implicit
    all-gather on seq happens where the layout changes (the reference's
    explicit AllGatherOp before the matmul)."""

    def forward(self, x):
        x = _on_mesh(x, _seq_spec(x.ndim))
        x = _constrain(x, _full_spec(x.ndim))  # gather seq for the matmul
        y = super().forward(x)
        return y


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel matmul emitting seq-sharded output: the post-matmul
    collective becomes a reduce-scatter instead of an all-reduce (the
    layout-aware optimization SP exists for). Only the output spec differs
    from RowParallelLinear."""

    def _out_spec(self, ndim: int) -> P:
        return _seq_spec(ndim)

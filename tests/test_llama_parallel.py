"""Flagship model + hybrid mesh tests (SURVEY.md §4: hybrid-parallel parity
on N local devices — the reference's test/collective/fleet pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import llama
from paddle_tpu.parallel import create_hybrid_mesh, set_mesh


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    set_mesh(None)


def _data(cfg, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.array(rng.randint(0, cfg.vocab_size, (batch, 64)), jnp.int32)


def test_forward_shapes_single():
    set_mesh(None)
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    tok = _data(cfg, batch=2)
    logits = llama.forward(params, tok, cfg)
    assert logits.shape == (2, 64, cfg.vocab_size)
    loss = llama.loss_fn(params, tok, tok, cfg)
    assert np.isfinite(float(loss))


def test_train_step_learns_single():
    set_mesh(None)
    cfg = llama.LlamaConfig.tiny()
    mesh = create_hybrid_mesh(devices=jax.devices()[:1])
    params = llama.init_params(cfg)
    opt = llama.init_opt_state(params)
    tok = _data(cfg)
    step = llama.make_sharded_train_step(cfg, mesh, lr=1e-3)
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, tok, tok)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses


@pytest.mark.parametrize("stage", [1, 3])
def test_hybrid_parity_vs_single(stage):
    """dp2 x sharding2 x mp2 loss/grads == single-device (the reference's
    hybrid-parallel parity tests, test/collective/fleet). Grads, not
    post-AdamW params: step-1 AdamW normalizes by sqrt(v)+eps which
    amplifies reduction-order float noise unboundedly."""
    cfg = llama.LlamaConfig.tiny(sharding_stage=stage)
    params = llama.init_params(cfg)
    tok = _data(cfg)

    grad_fn = jax.jit(jax.value_and_grad(llama.loss_fn), static_argnums=(3,))

    set_mesh(create_hybrid_mesh(devices=jax.devices()[:1]))
    l1, g1 = grad_fn(params, tok, tok, cfg)
    l1, g1 = float(l1), jax.tree.map(np.asarray, g1)

    mesh8 = create_hybrid_mesh(dp=2, sharding=2, mp=2)
    from jax.sharding import NamedSharding
    ps = {k: NamedSharding(mesh8, v) for k, v in llama.param_specs(cfg).items()}
    params8 = jax.device_put(params, ps)
    l8, g8 = grad_fn(params8, tok, tok, cfg)

    np.testing.assert_allclose(l1, float(l8), rtol=2e-5)
    for k in g1:
        np.testing.assert_allclose(
            g1[k], np.asarray(g8[k]), rtol=1e-4, atol=1e-5, err_msg=k)

    # and the sharded train step itself still runs + learns
    params8, opt = llama.shard_state(cfg, mesh8, params8,
                                     llama.init_opt_state(params8))
    step8 = llama.make_sharded_train_step(cfg, mesh8, lr=1e-3)
    p8, o8, first = step8(params8, opt, tok, tok)
    for _ in range(3):
        p8, o8, last = step8(p8, o8, tok, tok)
    assert float(last) < float(first)


def test_remat_matches_no_remat():
    set_mesh(None)
    cfg_r = llama.LlamaConfig.tiny(remat=True)
    cfg_n = llama.LlamaConfig.tiny(remat=False)
    params = llama.init_params(cfg_r)
    tok = _data(cfg_r, batch=2)
    g_r = jax.grad(llama.loss_fn)(params, tok, tok, cfg_r)
    g_n = jax.grad(llama.loss_fn)(params, tok, tok, cfg_n)
    for k in g_r:
        np.testing.assert_allclose(np.asarray(g_r[k]), np.asarray(g_n[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_gqa_forward():
    set_mesh(None)
    cfg = llama.LlamaConfig.tiny(num_heads=4, num_kv_heads=2)
    params = llama.init_params(cfg)
    tok = _data(cfg, batch=2)
    logits = llama.forward(params, tok, cfg)
    assert logits.shape == (2, 64, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_causality():
    """Changing a future token must not change past logits."""
    set_mesh(None)
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    tok = _data(cfg, batch=1)
    tok2 = tok.at[0, -1].set((tok[0, -1] + 1) % cfg.vocab_size)
    l1 = llama.forward(params, tok, cfg)
    l2 = llama.forward(params, tok2, cfg)
    np.testing.assert_allclose(np.asarray(l1[:, :-1]), np.asarray(l2[:, :-1]),
                               rtol=1e-5, atol=1e-6)


def test_kv_cache_generate_matches_full_recompute():
    """generate() (prefill + ONE lax.scan decode program with donated KV
    cache) must produce exactly the tokens of the naive full-recompute
    greedy loop; temperature/top-k sampling returns the right shape."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(7))
    prompt = jnp.array(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 9)),
        jnp.int32)
    toks = prompt
    ref = []
    for _ in range(6):
        logits = llama.forward(params, toks, cfg)[:, -1].astype(jnp.float32)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        ref.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    ref = jnp.stack(ref, axis=1)

    gen = llama.generate(params, prompt, cfg, max_new_tokens=6)
    assert bool(jnp.all(gen == ref))

    sampled = llama.generate(params, prompt, cfg, max_new_tokens=5,
                             temperature=0.8, top_k=4, seed=3)
    assert sampled.shape == (2, 5)
    assert bool(jnp.all((sampled >= 0) & (sampled < cfg.vocab_size)))

    # nucleus sampling: top_p -> 0 keeps only the argmax token, so the
    # sampled output degenerates to greedy at any temperature
    nucleus = llama.generate(params, prompt, cfg, max_new_tokens=6,
                             temperature=1.0, top_p=1e-6, seed=9)
    assert bool(jnp.all(nucleus == gen))

    # GQA: grouped-einsum cache attention (unrepeated KV cache)
    gcfg = llama.LlamaConfig.tiny(num_heads=4, num_kv_heads=2)
    gparams = llama.init_params(gcfg, jax.random.PRNGKey(3))
    toks = prompt
    ref2 = []
    for _ in range(4):
        logits = llama.forward(gparams, toks, gcfg)[:, -1].astype(jnp.float32)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        ref2.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    gen2 = llama.generate(gparams, prompt, gcfg, max_new_tokens=4)
    assert bool(jnp.all(gen2 == jnp.stack(ref2, axis=1)))


def test_beam_search_generate():
    """Beam search: num_beams=1 is exactly greedy; wider beams find
    sequences with >= total log-likelihood; eos freezing runs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(7))
    prompt = jnp.array(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 9)),
        jnp.int32)

    greedy = llama.generate(params, prompt, cfg, max_new_tokens=6)
    beam1 = llama.beam_search_generate(params, prompt, cfg,
                                       max_new_tokens=6, num_beams=1)
    assert bool(jnp.all(beam1 == greedy))

    def seq_logprob(toks):
        full = jnp.concatenate([prompt, toks], axis=1)
        lp = jax.nn.log_softmax(
            llama.forward(params, full, cfg).astype(jnp.float32), axis=-1)
        total = jnp.zeros((2,))
        for i in range(toks.shape[1]):
            pos = prompt.shape[1] - 1 + i
            total = total + lp[jnp.arange(2), pos, toks[:, i]]
        return total

    beam4 = llama.beam_search_generate(params, prompt, cfg,
                                       max_new_tokens=6, num_beams=4)
    assert bool(jnp.all(seq_logprob(beam4) >= seq_logprob(greedy) - 1e-4))

    eosed = llama.beam_search_generate(params, prompt, cfg,
                                       max_new_tokens=6, num_beams=3,
                                       eos_token_id=5)
    assert eosed.shape == (2, 6)

    # length penalty normalises per-beam (by each hypothesis's OWN length):
    # with an EOS on the beam path, p=0 favours the early-finished beam's
    # raw score while a large p favours the full-length hypothesis
    a = llama.beam_search_generate(params, prompt, cfg, max_new_tokens=6,
                                   num_beams=3, eos_token_id=50,
                                   length_penalty=0.0)
    b = llama.beam_search_generate(params, prompt, cfg, max_new_tokens=6,
                                   num_beams=3, eos_token_id=50,
                                   length_penalty=4.0)
    assert not bool(jnp.all(a == b))


def test_chunked_ce_matches_unchunked():
    """ce_chunks>0 recomputes the head+CE per batch-chunk (logits never
    materialised); loss AND grads must equal the unchunked form."""
    import jax

    from paddle_tpu.parallel import set_mesh

    set_mesh(None)  # single-chip gate for the chunked path
    cfg0 = llama.LlamaConfig.tiny()
    cfg1 = llama.LlamaConfig.tiny(ce_chunks=2)
    params = llama.init_params(cfg0)
    tok = jnp.array(np.random.RandomState(0).randint(
        0, cfg0.vocab_size, (4, 32)), jnp.int32)
    l0, g0 = jax.value_and_grad(lambda p: llama.loss_fn(p, tok, tok, cfg0))(params)
    l1, g1 = jax.value_and_grad(lambda p: llama.loss_fn(p, tok, tok, cfg1))(params)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g0[k]),
                                   rtol=1e-4, atol=1e-6)

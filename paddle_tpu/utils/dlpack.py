"""``paddle.utils.dlpack`` — zero-copy tensor interop via the DLPack
protocol (reference: ``paddle.utils.dlpack.to_dlpack/from_dlpack`` over
DLManagedTensor capsules; SURVEY.md §2.1 tensor API row). ``jax.dlpack``
carries the actual exchange; this module adds the Tensor wrapping and
the reference's capsule-or-producer calling convention."""

from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor/array -> DLPack capsule. Accepts a paddle Tensor or any
    jax array; the capsule is consumable exactly once (DLPack contract)."""
    import jax

    from ..core.tensor import Tensor

    arr = x.value if isinstance(x, Tensor) else jax.numpy.asarray(x)
    return jax.dlpack.to_dlpack(arr)


def from_dlpack(ext):
    """DLPack capsule (or any object with ``__dlpack__``) -> Tensor.
    Matches the reference's from_dlpack, which takes either a capsule
    from ``to_dlpack`` or a producer tensor directly."""
    import jax

    from ..core.tensor import Tensor

    return Tensor(jax.dlpack.from_dlpack(ext))

"""Bare-``dot_general`` microbenchmark at the EXACT headline-step dot shapes.

Purpose (r5): the r4 per-instruction profile says the backward dots run at
81-92% of the bf16 roofline inside the full train step. This script times a
bare ``jnp.dot`` at each of those exact (M, K, N) shapes in isolation,
slope-timed on-device like ``flash_micro.py``, so we can distinguish

  - *intrinsic*: the bare dot ALSO tops out at ~the in-step fraction ->
    that fraction IS the chip's achievable rate for this shape and the
    in-step rate is pinned, vs
  - *scheduling/fusion gap*: the bare dot runs significantly faster ->
    the step is leaving time on the table around that dot.

Variance hardening (r6, VERDICT item 8): the r5 ledger flagged ±35%
run-to-run spread on these micro rates ("head dx ranged 57→97% across
four runs") — a single sample per shape can compare an in-step rate
against a lucky tiling. Every shape is therefore timed ``--repeats``
(>=3) INDEPENDENT slope-timed runs and the table publishes
min/median/max.

THE COMPARISON RULE (what the ledger's residual arithmetic must cite):
an in-step rate is compared against the MEDIAN bare rate — min is noise
floor, max is a lucky run; the median is the reproducible achievable
rate. A shape has a real scheduling gap only when
``median_bare > 1.05 x in_step`` (5% guard band); anything inside the
band is pinned by the chip, not the schedule.

Usage: python benchmarks/dot_micro.py [iters] [repeats]
Writes a per-shape achievable-fraction table to stdout (markdown) for
ARCHITECTURE.md.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

PEAK_TFS = 197e12  # v5e bf16


from microbench import slope_timeit as timeit  # noqa: E402


def bench_shape(rng, M, K, N, out_dtype, iters, repeats):
    """``repeats`` independent slope-timed runs; returns the per-repeat
    seconds list (fresh operands each repeat so allocator/layout luck
    re-rolls too)."""
    f = jax.jit(lambda x, y: jax.lax.dot_general(
        x, y, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(out_dtype))
    times = []
    for _ in range(repeats):
        a = jnp.asarray(rng.randn(M, K), jnp.bfloat16)
        b = jnp.asarray(rng.randn(K, N), jnp.bfloat16)
        times.append(timeit(f, (a, b), iters))
    return times


def _row(tag, m, k, n, name, times):
    fl = 2.0 * m * n * k
    tmin, tmed, tmax = (float(np.min(times)), float(np.median(times)),
                        float(np.max(times)))
    # min TIME = max rate; report rate stats aligned with the rule: the
    # MEDIAN column is the one in-step rates are judged against
    fr = lambda t: fl / t / PEAK_TFS
    print(f"| {tag.strip()} | {m} | {k} | {n} | {name} | "
          f"{tmed*1e3:.3f} | {fl/tmed/1e12:.1f} | "
          f"{fr(tmax):.1%} | {fr(tmed):.1%} | {fr(tmin):.1%} |",
          flush=True)


def main():
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    repeats = max(3, int(sys.argv[2]) if len(sys.argv) > 2 else 3)
    M, H, F, V = 44 * 512, 768, 3072, 32000
    Mv = 44 * 511
    shapes = [
        # tag, M, K, N, in-step output dtype
        ("proj fwd      ", M, H, H, jnp.bfloat16),
        ("proj dW       ", H, M, H, jnp.float32),
        ("mlp gate/up fwd", M, H, F, jnp.bfloat16),
        ("mlp down fwd  ", M, F, H, jnp.bfloat16),
        ("mlp dW gate/up", H, M, F, jnp.float32),
        ("mlp dW down   ", F, M, H, jnp.float32),
        ("mlp dx gate/up", M, F, H, jnp.bfloat16),
        ("mlp dx down   ", M, H, F, jnp.bfloat16),
        ("head fwd      ", Mv, H, V, jnp.bfloat16),
        ("head dW       ", H, Mv, V, jnp.float32),
        ("head dx       ", Mv, V, H, jnp.bfloat16),
    ]
    rng = np.random.RandomState(0)
    print(f"devices: {jax.devices()}", flush=True)
    print(f"{repeats} independent slope-timed repeats/shape; rule: "
          f"in-step vs MEDIAN bare, 5% guard band", flush=True)
    print("| shape | M | K | N | out | med ms | med TF/s | "
          "frac min | frac median | frac max |")
    print("|---|---|---|---|---|---|---|---|---|---|")
    for tag, m, k, n, dt in shapes:
        times = bench_shape(rng, m, k, n, dt, iters, repeats)
        _row(tag, m, k, n, jnp.dtype(dt).name, times)
        # for fp32-output dW shapes, also time the bf16-output variant to
        # split "fp32 HBM write cost" out of any observed deficit
        if dt == jnp.float32:
            times2 = bench_shape(rng, m, k, n, jnp.bfloat16, iters, repeats)
            _row(tag + " (bf16 out)", m, k, n, "bfloat16", times2)


if __name__ == "__main__":
    main()

"""Explained performance — analytic roofline ledgers joined with
runtime counters, plus an EWMA tick-time regression sentinel (ISSUE 9
tentpole, part 2).

The analysis subsystem proves what a program SHOULD cost (budgets.py
pins relayout/pack/sync ledgers per canonical program; SCALING.md §3c
derives the HBM-bound decode ceiling from the live param tree) and the
telemetry registry records what serving DID (ticks, tokens, wall
time). Nothing joined them at runtime: an operator watching
``serving.throughput_tok_s`` had no way to know whether 800 tok/s was
the hardware's roofline or a 10x regression. This module closes that
gap with host arithmetic only:

* :func:`serving_ledger` rebuilds the §3c analytic ledger from the
  LIVE param tree (the same arithmetic ``benchmarks/llama_decode.py``
  publishes): per-tick weight-stream bytes (non-embedding params;
  the lm_head is fully read, the embedding row is a gather), per-tick
  KV bytes at the average position, the HBM tick floor, the tok/s
  ceiling, and matmul FLOPs/token — and attaches the program's pinned
  hazard budget from ``analysis.budgets`` so the static and dynamic
  ledgers travel together.
* :class:`PerfMonitor` accumulates the serving counters the schedulers
  already hold (steps, new tokens, segment wall time — all host
  mirrors of the one audited segment fetch) and, per interval, reports
  **live roofline fraction** (measured tok/s / analytic ceiling) and
  **MFU** (measured FLOP/s / peak) through the gauges
  ``perf.roofline_fraction[<program>]`` / ``perf.mfu[<program>]`` /
  ``perf.tok_s[<program>]``.
* The **regression sentinel** is the runtime sibling of the static
  gate: an EWMA of seconds-per-tick, pinned against a runtime budget
  (explicit ``tick_budget_s``, or self-pinned from the first
  ``pin_after`` segments), emits a ``perf_regression`` flight event +
  ``perf.regressions`` counter when the EWMA crosses
  ``tolerance x budget`` — the 2.5 s-mid-serve class and silent
  10%-slower classes both become operator-visible events instead of a
  vibe in a dashboard.

Roofline constants are the repo's published v5e assumptions (SCALING.md
§2: 819 GB/s HBM, 197 TF/s bf16) regardless of backend — matching
``llama_decode.py``: off-chip lanes report the fraction of the CHIP
ceiling their wall-clock achieves, and the artifact records the
platform so the number is self-describing.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from . import flight as _flight
from . import metrics as _metrics

__all__ = ["serving_ledger", "PerfMonitor", "V5E_HBM_BPS",
           "V5E_PEAK_FLOPS", "install", "uninstall"]

# The repo's pinned roofline constants (SCALING.md §2, public v5e specs)
V5E_HBM_BPS = 819e9
V5E_PEAK_FLOPS = 197e12


def serving_ledger(cfg, params, batch: int, avg_pos: float,
                   program: str = "serving_segment",
                   hbm_bytes_s: float = V5E_HBM_BPS,
                   peak_flops_s: float = V5E_PEAK_FLOPS) -> dict:
    """Analytic byte/op ledger for a decode-bound serving program,
    computed from the LIVE param tree (host shape metadata only — no
    device sync). ``batch`` is the concurrent slot count, ``avg_pos``
    the average KV position a tick attends over.

    The arithmetic is SCALING.md §3c / ``llama_decode.py``'s, verbatim:
    every decode tick streams the non-embedding weights once plus the
    KV rows written so far; the ceiling is ``batch / tick_floor``.
    FLOPs/token = 2 x non-embedding params (matmul MACs x 2) plus the
    attention score/value contractions at ``avg_pos``."""
    import jax

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    embed_rows = cfg.vocab_size * cfg.hidden_size
    itemsize = np.dtype(cfg.dtype).itemsize
    weight_bytes = (n_params - embed_rows) * itemsize
    kv_bytes = (cfg.num_layers * 2 * float(avg_pos) * cfg.num_kv_heads
                * cfg.head_dim * batch * itemsize)
    tick_floor_s = (weight_bytes + kv_bytes) / hbm_bytes_s
    ceiling_tok_s = batch / tick_floor_s
    flops_per_token = (2.0 * (n_params - embed_rows)
                       + 4.0 * float(avg_pos) * cfg.num_heads
                       * cfg.head_dim * cfg.num_layers)
    ledger = {
        "program": program,
        "batch": int(batch),
        "avg_pos": float(avg_pos),
        "n_params": n_params,
        "weight_bytes_per_tick": int(weight_bytes),
        "kv_bytes_per_tick": int(kv_bytes),
        "hbm_bytes_s": hbm_bytes_s,
        "peak_flops_s": peak_flops_s,
        "tick_floor_s": tick_floor_s,
        "ceiling_tok_s": ceiling_tok_s,
        "flops_per_token": flops_per_token,
    }
    # join the STATIC hazard ledger the gate enforces for this program,
    # so /perf serves the analytic bytes next to the pinned budgets
    from ..analysis import budgets as _budgets

    b = _budgets.budget_for(program)
    if b is not None:
        ledger["hazard_budget"] = {
            "relayout_bytes_max": b.relayout_bytes_max,
            "pack_bytes_max": b.pack_bytes_max,
            "warm_compiles": b.warm_compiles,
            "allowed_syncs_per_replay": dict(b.allowed_syncs_per_replay),
            "bytes_platform": b.bytes_platform,
        }
    return ledger


class PerfMonitor:
    """Join one serving program's analytic ledger with its runtime
    counters; report roofline fraction + MFU per interval and watch the
    per-tick EWMA for regressions.

    Feed it per-segment host numbers via :meth:`note_segment` (the
    schedulers pass exact steps/tokens/elapsed from the audited fetch's
    host mirrors) and call :meth:`end_interval` whenever a report
    should be cut (the benchmarks cut one per rated serve; the ops
    endpoint serves the running interval live).

    ``tick_budget_s``: pinned seconds/tick the sentinel guards. When
    ``None`` it self-pins to the EWMA after ``pin_after`` segments —
    the 'no regression vs my own warm baseline' mode the serving lanes
    use. ``tolerance``: multiplier over budget that trips the sentinel.
    """

    def __init__(self, cfg, params, batch: int, avg_pos: float = 64.0,
                 program: str = "serving_segment",
                 hbm_bytes_s: float = V5E_HBM_BPS,
                 peak_flops_s: float = V5E_PEAK_FLOPS,
                 tick_budget_s: Optional[float] = None,
                 pin_after: int = 4, tolerance: float = 1.5,
                 ewma_alpha: float = 0.5):
        self.program = program
        self.ledger = serving_ledger(cfg, params, batch, avg_pos,
                                     program=program,
                                     hbm_bytes_s=hbm_bytes_s,
                                     peak_flops_s=peak_flops_s)
        self.tick_budget_s = tick_budget_s
        self._explicit_budget = tick_budget_s is not None
        self.pin_after = int(pin_after)
        self.tolerance = float(tolerance)
        self.ewma_alpha = float(ewma_alpha)
        self.tick_ewma_s: Optional[float] = None
        self.regressions = 0
        self.segments = 0             # lifetime (the self-pin clock)
        # interval accumulators (host ints/floats only)
        self._iv_segments = 0
        self._iv_steps = 0
        self._iv_tokens = 0
        self._iv_busy_s = 0.0
        self._iv_t0: Optional[float] = None
        self.last_report: Optional[dict] = None

    # --- per-segment intake ----------------------------------------------
    def note_segment(self, steps: int, new_tokens: int,
                     elapsed_s: Optional[float] = None) -> None:
        """One segment's host mirrors: device ticks run, tokens
        surfaced, and (when the caller timed the dispatch→fetch span)
        its wall time. ``elapsed_s=None`` skips the sentinel (ambient
        attachments that cannot time the segment still feed the
        throughput interval)."""
        if self._iv_t0 is None:
            self._iv_t0 = time.perf_counter()
        self.segments += 1
        self._iv_segments += 1
        self._iv_steps += int(steps)
        self._iv_tokens += int(new_tokens)
        if elapsed_s is None or steps <= 0:
            return
        self._iv_busy_s += float(elapsed_s)
        per_tick = float(elapsed_s) / int(steps)
        self.tick_ewma_s = (per_tick if self.tick_ewma_s is None
                            else (1 - self.ewma_alpha) * self.tick_ewma_s
                            + self.ewma_alpha * per_tick)
        _metrics.gauge(
            f"perf.tick_time_ewma_s[{self.program}]").set(self.tick_ewma_s)
        if not self._explicit_budget:
            if self.segments == self.pin_after:
                # self-pin: the warm baseline becomes the budget
                self.tick_budget_s = self.tick_ewma_s
            elif self.segments < self.pin_after:
                return
        if (self.tick_budget_s is not None
                and self.tick_ewma_s > self.tolerance * self.tick_budget_s):
            self.regressions += 1
            _metrics.counter("perf.regressions").inc()
            _flight.record(
                "perf_regression", program=self.program,
                tick_ewma_s=round(self.tick_ewma_s, 6),
                budget_s=round(self.tick_budget_s, 6),
                tolerance=self.tolerance, segment=self.segments)

    # --- interval reporting ----------------------------------------------
    def interval_report(self, now: Optional[float] = None) -> dict:
        """The running interval's explained numbers (without closing
        it): measured tok/s, roofline fraction, MFU, busy fraction."""
        now = time.perf_counter() if now is None else now
        elapsed = (now - self._iv_t0) if self._iv_t0 is not None else 0.0
        tok_s = self._iv_tokens / elapsed if elapsed > 0 else 0.0
        led = self.ledger
        return {
            "program": self.program,
            "interval_s": round(elapsed, 4),
            "segments": self._iv_segments,
            "steps": self._iv_steps,
            "tokens": self._iv_tokens,
            "tok_s": round(tok_s, 2),
            "ceiling_tok_s": round(led["ceiling_tok_s"], 2),
            # NOT rounded: on an off-chip lane the fraction of the chip
            # ceiling is ~1e-6 and rounding would zero the signal
            "roofline_fraction": (tok_s / led["ceiling_tok_s"]
                                  if led["ceiling_tok_s"] else 0.0),
            "mfu": (tok_s * led["flops_per_token"] / led["peak_flops_s"]
                    if led["peak_flops_s"] else 0.0),
            "busy_fraction": (round(self._iv_busy_s / elapsed, 4)
                              if elapsed > 0 else 0.0),
            "tick_ewma_s": self.tick_ewma_s,
            "tick_budget_s": self.tick_budget_s,
            "regressions": self.regressions,
        }

    def end_interval(self) -> dict:
        """Close the interval: publish the gauges, reset accumulators,
        return (and retain) the report."""
        rep = self.interval_report()
        p = self.program
        _metrics.gauge(f"perf.tok_s[{p}]").set(rep["tok_s"])
        _metrics.gauge(f"perf.roofline_fraction[{p}]").set(
            rep["roofline_fraction"])
        _metrics.gauge(f"perf.mfu[{p}]").set(rep["mfu"])
        self._iv_segments = 0
        self._iv_steps = 0
        self._iv_tokens = 0
        self._iv_busy_s = 0.0
        self._iv_t0 = None
        self.last_report = rep
        return rep

    def report(self) -> dict:
        """The ``/perf`` endpoint payload: the analytic ledger plus the
        running interval and the last closed one."""
        return {"ledger": dict(self.ledger),
                "interval": self.interval_report(),
                "last_interval": self.last_report}


# ---------------------------------------------------------------------------
# Ambient attachment (the gate's --ops mode): every engine segment feeds
# the interval accumulators through serving.SEGMENT_HOOKS. No elapsed
# time is available at that hook (the engine doesn't time its own
# dispatch→fetch span), so the sentinel stays quiet — the attachment
# proves hazard-neutrality, the schedulers provide the timed feed.
# ---------------------------------------------------------------------------

_INSTALLED: list = []


def install(monitor: PerfMonitor) -> None:
    from ..inference import serving as _serving

    for m, _ in _INSTALLED:
        if m is monitor:
            return

    def hook(steps: int, new_tokens: int, finished: int) -> None:
        monitor.note_segment(steps, new_tokens, elapsed_s=None)

    _serving.SEGMENT_HOOKS.append(hook)
    _INSTALLED.append((monitor, hook))


def uninstall(monitor: Optional[PerfMonitor] = None) -> None:
    from ..inference import serving as _serving

    keep = []
    for m, hook in _INSTALLED:
        if monitor is None or m is monitor:
            if hook in _serving.SEGMENT_HOOKS:
                _serving.SEGMENT_HOOKS.remove(hook)
        else:
            keep.append((m, hook))
    _INSTALLED[:] = keep

"""Compiled SPMD 1F1B pipeline schedule (meta_parallel/pp_1f1b.py).

Reference test pattern (SURVEY.md §4 hybrid-parallel correctness): the
pipeline schedule must match the non-pipelined execution numerically — 1F1B
reorders micro-batch work, it does not change the math. We assert loss AND
per-parameter gradient parity against the eager grad-accumulation path, and
pin the dispatch: the compiled program must move activations between stages
with collective-permute (the ICI analog of the reference's P2P send/recv).
"""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
)
from paddle_tpu.parallel import create_hybrid_mesh, set_mesh


def _mse(out, y):
    return paddle.mean((out - y) ** 2)


def _build_pp(num_stages, n_layers, virtual=1, width=8, seed=7):
    paddle.seed(seed)
    descs = []
    for _ in range(n_layers):
        descs.append(LayerDesc(paddle.nn.Linear, width, width))
        descs.append(paddle.nn.functional.tanh)
    pl = PipelineLayer(layers=descs, num_stages=num_stages, loss_fn=_mse,
                       num_virtual_pipeline_stages=virtual)
    strategy = DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 4}
    return PipelineParallel(pl, None, strategy), pl


def _grads(pl):
    return [None if p.grad is None else np.asarray(p.grad.numpy()).copy()
            for p in pl.parameters() if not p.stop_gradient]


@pytest.fixture
def pp4_mesh():
    mesh = create_hybrid_mesh(dp=2, pp=4)
    yield mesh
    set_mesh(None)


@pytest.fixture
def pp2v2_mesh():
    mesh = create_hybrid_mesh(dp=2, pp=2, devices=jax.devices()[:4])
    yield mesh
    set_mesh(None)


class Test1F1BParity:
    def test_loss_and_grad_parity_vs_grad_accum(self, pp4_mesh):
        pp, pl = _build_pp(num_stages=4, n_layers=8)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(8, 8).astype("float32"))

        loss_ref = pp.train_batch((x, y))
        g_ref = _grads(pl)
        for p in pl.parameters():
            p.clear_grad()

        loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
        g_new = _grads(pl)

        np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                   rtol=2e-5, atol=1e-7)
        assert len(g_ref) == len(g_new) and len(g_ref) > 0
        for a, b in zip(g_ref, g_new):
            assert (a is None) == (b is None)
            if a is not None:
                np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-6)

    def test_interleaved_virtual_stages_parity(self, pp2v2_mesh):
        # virtual_pp_degree=2 on pp=2: 4 chunks ride 2 devices — the
        # reference's interleaved 1F1B (virtual_pp_degree) on a ring
        pp, pl = _build_pp(num_stages=2, n_layers=8, virtual=2)
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(8, 8).astype("float32"))

        loss_ref = pp.train_batch((x, y))
        g_ref = _grads(pl)
        for p in pl.parameters():
            p.clear_grad()

        loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
        g_new = _grads(pl)

        np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                   rtol=2e-5, atol=1e-7)
        for a, b in zip(g_ref, g_new):
            if a is not None:
                np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-6)

    def test_optimizer_step_applies(self, pp4_mesh):
        pp, pl = _build_pp(num_stages=4, n_layers=8, seed=9)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=pl.parameters())
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
        w0 = pl.run_functions[0].weight.numpy().copy()
        loss = pp.train_batch((x, y), optimizer=opt, schedule="1f1b")
        assert np.isfinite(float(loss.numpy()))
        assert not np.allclose(pl.run_functions[0].weight.numpy(), w0)

    def test_hlo_pins_collective_permute(self, pp4_mesh):
        pp, pl = _build_pp(num_stages=4, n_layers=8, seed=5)
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
        pp.train_batch((x, y), schedule="1f1b")
        eng = pp._1f1b_engine
        (key, fn), = eng._cache.items()
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(eng._mesh, PartitionSpec())
        pvals = [p._value for p in eng._params]
        bvals = [b._value for b in eng._buffers]
        kd = jax.device_put(
            jax.random.key_data(jax.random.PRNGKey(0)), rep)
        hlo = fn.lower(pvals, bvals, jax.device_put(x._value, rep),
                       jax.device_put(y._value, rep), kd).compile().as_text()
        assert "collective-permute" in hlo, (
            "1F1B activation transfer must compile to collective-permute")

    def test_llama_pipe_parity_pp_mp_dp(self):
        """Flagship-shaped 1F1B (VERDICT r2 item 3): LLaMA as a
        PipelineLayer with tied embeddings, TP decoder blocks, and the
        causal-LM loss — pp=2 x mp=2 x dp=2 in ONE mesh. The compiled
        schedule runs manual Megatron TP (local-shard matmuls + f/g
        collectives) inside the pp ring; parity vs the eager
        grad-accumulation path covers loss AND every parameter gradient,
        including the shared embedding (grad contributions from both the
        embed and the LM-head use)."""
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.models.llama_pipe import build_llama_pipe

        mesh = create_hybrid_mesh(pp=2, mp=2, dp=2)
        try:
            paddle.seed(0)
            cfg = LlamaConfig.tiny(num_layers=4)
            pl = build_llama_pipe(cfg, num_stages=2)
            strategy = DistributedStrategy()
            strategy.pipeline_configs = {"accumulate_steps": 4}
            pp = PipelineParallel(pl, None, strategy)

            rng = np.random.RandomState(0)
            x = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64"))
            y = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64"))

            loss_ref = pp.train_batch((x, y))
            g_ref = _grads(pl)
            for p in pl.parameters():
                p.clear_grad()

            loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
            g_new = _grads(pl)

            np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                       rtol=2e-5, atol=1e-6)
            assert len(g_ref) == len(g_new) and len(g_ref) > 10
            for a, b in zip(g_ref, g_new):
                assert (a is None) == (b is None)
                if a is not None:
                    np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-5)

            # the mp-sharded weights keep their TP layout on the grads
            from jax.sharding import NamedSharding

            qw = pl.run_functions[1].wq.weight
            assert isinstance(qw.grad._value.sharding, NamedSharding)
            assert "mp" in str(qw.grad._value.sharding.spec)
        finally:
            set_mesh(None)

    def test_llama_pipe_parity_pp_mp_sharding(self):
        """ZeRO composition (VERDICT r3 item 2): the flagship PipelineLayer
        on pp=2 x mp=2 x sharding=2 in ONE compiled 1F1B program — params
        cross the shard_map boundary ZeRO-sharded, are all-gathered at
        program entry, grads reduce-scatter back to the shard layout, and
        the sharding ranks carry their own batch rows. Parity vs the eager
        grad-accumulation path covers loss and every parameter gradient."""
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.models.llama_pipe import build_llama_pipe

        mesh = create_hybrid_mesh(pp=2, mp=2, sharding=2)
        try:
            paddle.seed(11)
            cfg = LlamaConfig.tiny(num_layers=4)
            pl = build_llama_pipe(cfg, num_stages=2)
            strategy = DistributedStrategy()
            strategy.pipeline_configs = {"accumulate_steps": 4}
            pp = PipelineParallel(pl, None, strategy)

            rng = np.random.RandomState(2)
            x = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64"))
            y = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64"))

            loss_ref = pp.train_batch((x, y))
            g_ref = _grads(pl)
            for p in pl.parameters():
                p.clear_grad()

            loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
            g_new = _grads(pl)

            np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                       rtol=2e-5, atol=1e-6)
            assert len(g_ref) == len(g_new) and len(g_ref) > 10
            for a, b in zip(g_ref, g_new):
                assert (a is None) == (b is None)
                if a is not None:
                    np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-5)

            # the compiled program must carry the ZeRO pair: an entry
            # all-gather and an exit reduce-scatter over 'sharding', on
            # top of the pp collective-permute ring
            eng = pp._1f1b_engine
            fn = next(iter(eng._cache.values()))
            pvals = [p._value for p in eng._params]
            bvals = [b._value for b in eng._buffers]
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep = NamedSharding(mesh, P())
            kd = jax.device_put(
                jax.random.key_data(jax.random.PRNGKey(0)), rep)
            hlo = fn.lower(pvals, bvals,
                           jax.device_put(x._value, rep),
                           jax.device_put(y._value, rep),
                           kd).compile().as_text()
            assert "all-gather" in hlo
            assert "reduce-scatter" in hlo
            assert "collective-permute" in hlo

            # grads keep the ZeRO shard layout at rest
            qw = pl.run_functions[1].wq.weight
            assert "sharding" in str(qw.grad._value.sharding.spec)
        finally:
            set_mesh(None)

    def test_llama_pipe_parity_virtual_stages(self):
        """Interleaved virtual stages on the transformer: 4 chunks over
        pp=2 (virtual_pp_degree=2), tied embeddings crossing the ring
        wrap."""
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.models.llama_pipe import build_llama_pipe

        mesh = create_hybrid_mesh(pp=2, mp=2, dp=2)
        try:
            paddle.seed(3)
            cfg = LlamaConfig.tiny(num_layers=4)
            pl = build_llama_pipe(cfg, num_stages=2,
                                  num_virtual_pipeline_stages=2)
            strategy = DistributedStrategy()
            strategy.pipeline_configs = {"accumulate_steps": 4}
            pp = PipelineParallel(pl, None, strategy)

            rng = np.random.RandomState(5)
            x = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64"))
            y = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64"))

            loss_ref = pp.train_batch((x, y))
            g_ref = _grads(pl)
            for p in pl.parameters():
                p.clear_grad()
            loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
            g_new = _grads(pl)

            np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                       rtol=2e-5, atol=1e-6)
            for a, b in zip(g_ref, g_new):
                if a is not None:
                    np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-5)
        finally:
            set_mesh(None)

    def test_parity_pp_dp_sharding_combined(self):
        """dp AND sharding together (pp=2 x dp=2 x sharding=2): the batch
        splits over BOTH data axes, unshardable grads pmean over each,
        shardable grads reduce-scatter over 'sharding' then pmean over dp.
        Parity against the grad-accumulation path on the small pipeline."""
        mesh = create_hybrid_mesh(pp=2, dp=2, sharding=2)
        try:
            pp, pl = _build_pp(num_stages=2, n_layers=4, seed=21)
            rng = np.random.RandomState(4)
            x = paddle.to_tensor(rng.randn(16, 8).astype("float32"))
            y = paddle.to_tensor(rng.randn(16, 8).astype("float32"))

            loss_ref = pp.train_batch((x, y))
            g_ref = _grads(pl)
            for p in pl.parameters():
                p.clear_grad()
            loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
            g_new = _grads(pl)

            np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                       rtol=2e-5, atol=1e-7)
            assert len(g_ref) == len(g_new) and len(g_ref) > 0
            for a, b in zip(g_ref, g_new):
                assert (a is None) == (b is None)
                if a is not None:
                    np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-6)
        finally:
            set_mesh(None)

    def test_llama_pipe_parity_4axis_16dev(self):
        """The FULL 4-axis hybrid (VERDICT r4 item 7): dp2 x pp2 x mp2 x
        sharding2 — compiled 1F1B with manual TP, in-program ZeRO (entry
        all-gather / exit reduce-scatter over 'sharding') AND dp
        grad-averaging, in ONE program on a 16-device mesh. The suite's
        conftest pins 8 virtual devices, so this runs in a subprocess
        with 16 (same recipe, SURVEY §7.3.5); parity covers loss and
        every parameter gradient, and the HLO must carry all three
        collective families (all-gather, reduce-scatter,
        collective-permute)."""
        import os
        import subprocess
        import sys
        import textwrap

        code = textwrap.dedent("""
            import numpy as np
            import jax
            import paddle_tpu as paddle
            from paddle_tpu.distributed.fleet import DistributedStrategy
            from paddle_tpu.distributed.fleet.meta_parallel import (
                PipelineParallel,
            )
            from paddle_tpu.models.llama import LlamaConfig
            from paddle_tpu.models.llama_pipe import build_llama_pipe
            from paddle_tpu.parallel import create_hybrid_mesh, set_mesh

            mesh = create_hybrid_mesh(dp=2, pp=2, mp=2, sharding=2)
            paddle.seed(0)
            cfg = LlamaConfig.tiny(num_layers=4)
            pl = build_llama_pipe(cfg, num_stages=2)
            strategy = DistributedStrategy()
            strategy.pipeline_configs = {"accumulate_steps": 4}
            pp = PipelineParallel(pl, None, strategy)
            rng = np.random.RandomState(0)
            x = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (16, 16)).astype("int64"))
            y = paddle.to_tensor(
                rng.randint(0, cfg.vocab_size, (16, 16)).astype("int64"))

            loss_ref = pp.train_batch((x, y))
            g_ref = [None if p.grad is None
                     else np.asarray(p.grad.numpy()).copy()
                     for p in pl.parameters() if not p.stop_gradient]
            for p in pl.parameters():
                p.clear_grad()
            loss_1f1b = pp.train_batch((x, y), schedule="1f1b")
            g_new = [None if p.grad is None
                     else np.asarray(p.grad.numpy()).copy()
                     for p in pl.parameters() if not p.stop_gradient]

            np.testing.assert_allclose(loss_1f1b.numpy(), loss_ref.numpy(),
                                       rtol=2e-5, atol=1e-6)
            assert len(g_ref) == len(g_new) and len(g_ref) > 10
            for a, b in zip(g_ref, g_new):
                assert (a is None) == (b is None)
                if a is not None:
                    np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-5)

            # the one compiled program must carry the ZeRO pair AND the
            # pp ring on top of the dp/mp reductions
            eng = pp._1f1b_engine
            fn = next(iter(eng._cache.values()))
            pvals = [p._value for p in eng._params]
            bvals = [b._value for b in eng._buffers]
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep = NamedSharding(mesh, P())
            kd = jax.device_put(
                jax.random.key_data(jax.random.PRNGKey(0)), rep)
            hlo = fn.lower(pvals, bvals,
                           jax.device_put(x._value, rep),
                           jax.device_put(y._value, rep),
                           kd).compile().as_text()
            assert "all-gather" in hlo
            assert "reduce-scatter" in hlo
            assert "collective-permute" in hlo

            qw = pl.run_functions[1].wq.weight
            # strict: the grad must be at REST in the ZeRO shard layout
            # (the 'mp' placement alone comes from TP and would mask a
            # dropped reduce-scatter exit)
            assert "sharding" in str(qw.grad._value.sharding.spec)
            set_mesh(None)
            print("4AXIS-PARITY-OK", float(loss_1f1b.numpy()))
        """)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
        proc = subprocess.run([sys.executable, "-c", code],
                              cwd="/root/repo", env=env, timeout=900,
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "4AXIS-PARITY-OK" in proc.stdout

    def test_gspmd_layer_in_chunk_raises_at_trace(self):
        """The manual-TP footgun guard (VERDICT r3 item 3): a layer that
        stages a GSPMD sharding constraint inside a 1F1B stage chunk must
        fail AT TRACE TIME with the layer's name — not deadlock on a real
        mesh. Also pins that the guard is scoped: the same layer works on
        the eager grad-accumulation path."""
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (
            mp_layers as _mpl,
        )

        class GspmdOnlyLayer(paddle.nn.Layer):
            def __init__(self, width):
                super().__init__()
                self.lin = paddle.nn.Linear(width, width)

            def forward(self, x):
                return _mpl._constrain(self.lin(x), P(None, "mp"))

        mesh = create_hybrid_mesh(pp=2, mp=2, devices=jax.devices()[:4])
        try:
            paddle.seed(13)
            descs = [LayerDesc(paddle.nn.Linear, 8, 8),
                     LayerDesc(GspmdOnlyLayer, 8),
                     LayerDesc(paddle.nn.Linear, 8, 8),
                     LayerDesc(paddle.nn.Linear, 8, 8)]
            pl = PipelineLayer(layers=descs, num_stages=2, loss_fn=_mse)
            strategy = DistributedStrategy()
            strategy.pipeline_configs = {"accumulate_steps": 4}
            pp = PipelineParallel(pl, None, strategy)
            rng = np.random.RandomState(7)
            x = paddle.to_tensor(rng.randn(8, 8).astype("float32"))
            y = paddle.to_tensor(rng.randn(8, 8).astype("float32"))

            # eager grad-accumulation path: GSPMD constraints are fine
            loss_ref = pp.train_batch((x, y))
            assert np.isfinite(float(loss_ref.numpy()))

            with pytest.raises(ValueError, match="GspmdOnlyLayer"):
                pp.train_batch((x, y), schedule="1f1b")
        finally:
            set_mesh(None)

    def test_manual_mp_is_context_local(self):
        """contextvars semantics: nested scopes restore, and a fresh
        context (another task/thread) does not observe the engine's
        manual mode."""
        import contextvars

        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (
            mp_layers as _mpl,
        )

        assert _mpl.manual_axis() is None
        with _mpl.manual_mp("mp", program=True):
            assert _mpl.manual_axis() == "mp"
            assert _mpl.in_manual_program()
            with _mpl.manual_mp(None):
                assert _mpl.manual_axis() is None
                assert _mpl.in_manual_program()  # program flag survives
            assert _mpl.manual_axis() == "mp"
            # a FRESH context (what another thread starts from) sees no
            # manual mode even while this one is inside it
            ctx = contextvars.Context()
            assert ctx.run(_mpl.manual_axis) is None
            assert ctx.run(_mpl.in_manual_program) is False
        assert _mpl.manual_axis() is None
        assert not _mpl.in_manual_program()

    def test_uneven_batch_rejected(self, pp4_mesh):
        pp, pl = _build_pp(num_stages=4, n_layers=8, seed=4)
        x = paddle.to_tensor(np.random.randn(6, 8).astype("float32"))
        y = paddle.to_tensor(np.random.randn(6, 8).astype("float32"))
        with pytest.raises(ValueError, match="divisible"):
            pp.train_batch((x, y), schedule="1f1b")

"""``paddle.geometric`` — graph learning primitives.

Reference counterpart: ``python/paddle/geometric/`` (segment reductions and
the ``send_u_recv``/``send_ue_recv`` message-passing ops used by PGL;
SURVEY.md §2.1 PHI kernel corpus). All reductions lower to XLA segment ops
(one-hot matmul or sort-based — the compiler picks), which is the TPU-native
replacement for the reference's atomic-scatter CUDA kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import run_op

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv"]


def _nseg(segment_ids, num_segments):
    if num_segments is not None:
        return int(num_segments)
    ids = segment_ids._value if isinstance(segment_ids, Tensor) else segment_ids
    return int(jnp.max(ids)) + 1 if ids.size else 0


def _segment(kind):
    fns = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}

    def op(data, segment_ids, num_segments=None, name=None):
        n = _nseg(segment_ids, num_segments)
        ids = (segment_ids._value if isinstance(segment_ids, Tensor)
               else jnp.asarray(segment_ids)).astype(jnp.int32)

        def f(a):
            if kind == "mean":
                s = jax.ops.segment_sum(a, ids, num_segments=n)
                # counts accumulate in fp32: low-precision data dtypes
                # (bf16) lose integer exactness above ~256
                cnt = jax.ops.segment_sum(
                    jnp.ones((a.shape[0],), jnp.float32), ids,
                    num_segments=n).astype(a.dtype)
                return s / jnp.maximum(cnt, 1.0).reshape(
                    (-1,) + (1,) * (a.ndim - 1))
            out = fns[kind](a, ids, num_segments=n)
            if kind in ("max", "min"):
                # empty segments: paddle fills 0, jax fills +-inf
                cnt = jax.ops.segment_sum(jnp.ones((a.shape[0],)), ids,
                                          num_segments=n)
                mask = (cnt > 0).reshape((-1,) + (1,) * (a.ndim - 1))
                out = jnp.where(mask, out, 0.0)
            return out

        return run_op(f"segment_{kind}", f, data)

    op.__name__ = f"segment_{kind}"
    return op


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")


_REDUCERS = {}  # filled below once the public segment ops exist


def _reducer(reduce_op):
    try:
        return _REDUCERS[reduce_op]
    except KeyError:
        raise ValueError(
            f"reduce_op must be one of {sorted(_REDUCERS)}, got "
            f"{reduce_op!r}") from None


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations
    (reference ``paddle.geometric.send_u_recv``)."""
    si = (src_index._value if isinstance(src_index, Tensor)
          else jnp.asarray(src_index)).astype(jnp.int32)
    seg = _reducer(reduce_op)
    gathered = run_op("gather_u", lambda a: jnp.take(a, si, axis=0), x)
    n = out_size if out_size is not None else (
        x._value.shape[0] if isinstance(x, Tensor) else None)
    return seg(gathered, dst_index, num_segments=n)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Like send_u_recv but combines node features with EDGE features
    first (reference ``send_ue_recv``)."""
    si = (src_index._value if isinstance(src_index, Tensor)
          else jnp.asarray(src_index)).astype(jnp.int32)
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]
    msg = run_op("message_ue",
                 lambda a, e: combine(jnp.take(a, si, axis=0), e), x, y)
    seg = _reducer(reduce_op)
    n = out_size if out_size is not None else (
        x._value.shape[0] if isinstance(x, Tensor) else None)
    return seg(msg, dst_index, num_segments=n)


_REDUCERS.update({"sum": segment_sum, "mean": segment_mean,
                  "max": segment_max, "min": segment_min})

"""Per-instruction xplane profile of the ResNet-50 fused train step —
where do the ~19 ms between the measured step and the 40.8 ms
tiling-aware roofline (SCALING.md §3b) go?

Usage: python benchmarks/resnet_profile.py [batch] [top_n]
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision import models

    model = models.resnet50(num_classes=1000, data_format="NHWC")
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=1e-4)
    model, opt = paddle.amp.decorate(models=model, optimizers=opt,
                                     level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            return ce(model(x), y)

    step_fn = paddle.jit.fused_train_step(loss_fn, opt, model=model)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(batch, 224, 224, 3).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)))
    float(step_fn(x, y))
    float(step_fn(x, y))

    tmp = tempfile.mkdtemp(prefix="xplane_rn_")
    n_steps = 6
    with jax.profiler.trace(tmp):
        for _ in range(n_steps):
            loss = step_fn(x, y)
        float(loss)

    from paddle_tpu.profiler import _xplane
    _xplane.print_instr_profile(tmp, n_steps, top_n,
                                header=f"batch {batch}: ")


if __name__ == "__main__":
    main()

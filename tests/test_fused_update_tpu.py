"""On-chip certification of the Pallas fused multi-tensor optimizer
update — REAL TPU ONLY (ISSUE 3 satellite: the chip-lane entry asserting
fused-update vs reference trajectory parity on TPU).

The CPU suite (tests/test_multi_tensor_update.py) proves the kernels
through the pallas interpreter; these tests prove the REAL Mosaic
lowering — SMEM hyper scalars, input/output aliasing, the [rows, 128]
grid — agrees with the XLA reference trajectories on the chip, for the
two configurations the benchmarks run: Momentum+wd over bf16 params (the
ResNet-50 profile config) and AdamW with fp32 master weights (the bench
config).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="on-chip certification runs on TPU only")

SHAPES = [(3, 3, 16, 16)] * 3 + [(1, 1, 32, 16), (7, 7, 3, 16),
                                 (256, 10), (10,)] + [(16,)] * 5 + [(32,)]


def _run(opt_factory, dtype, use_kernel, steps=4):
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.set_flags({"use_pallas_fused_update": use_kernel})
    try:
        rng = np.random.RandomState(0)
        params = [nn.Parameter(
            jnp.asarray(rng.randn(*s) * 0.1).astype(dtype))
            for s in SHAPES]
        opt = opt_factory(params)
        for s in range(steps):
            g_rng = np.random.RandomState(100 + s)
            for p in params:
                p.grad = paddle.to_tensor(
                    jnp.asarray(g_rng.randn(*p.shape) * 0.01)
                    .astype(dtype))
            opt.step()
            opt.clear_grad()
        return [p.numpy().astype(np.float32) for p in params], opt
    finally:
        paddle.set_flags({"use_pallas_fused_update": True})


def test_momentum_bf16_kernel_matches_reference_on_chip():
    import paddle_tpu as paddle
    from paddle_tpu.ops.pallas import multi_tensor_update as mtu

    mtu.reset_selection_count()
    fused, opt = _run(
        lambda ps: paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9, parameters=ps,
            weight_decay=1e-4),
        "bfloat16", use_kernel=True)
    assert mtu.selection_count() >= 1, \
        "fused update not selected on the chip"
    for st in opt._accumulators.values():
        for v in st.values():
            assert v.ndim == 2 and v.shape[1] == 128
    ref, _ = _run(
        lambda ps: paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9, parameters=ps,
            weight_decay=1e-4),
        "bfloat16", use_kernel=False)
    for a, b in zip(fused, ref):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-3)


def test_adamw_master_kernel_matches_reference_on_chip():
    import paddle_tpu as paddle

    fused, _ = _run(
        lambda ps: paddle.optimizer.AdamW(
            learning_rate=0.01, weight_decay=0.1, parameters=ps,
            multi_precision=True),
        "bfloat16", use_kernel=True)
    ref, _ = _run(
        lambda ps: paddle.optimizer.AdamW(
            learning_rate=0.01, weight_decay=0.1, parameters=ps,
            multi_precision=True),
        "bfloat16", use_kernel=False)
    for a, b in zip(fused, ref):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-3)

"""Quantized serving (r21 tentpole, ISSUE 16): int8/fp8 weight
streaming + per-page KV quantization behind the shadow/canary quality
bar.

Pins the subsystem's contracts:

* numeric recipe — per-out-channel weight quantization round-trips
  within the absmax step bound; re-quantizing a quantized tree is a
  loud ValueError;
* in-kernel dequant parity (FORCE_INTERPRET on CPU) — the Pallas
  ``quant_matmul`` and the scale-fed ``ragged_decode_attention`` match
  the dense dequantize-then-compute formulation that stays the
  CPU/mesh fallback;
* the quantized paged engine — mode validation, token determinism
  within one dtype, matched-prefix token agreement vs bf16 above the
  floor (bit-identity across dtypes is NOT the bar — SCALING §3p);
* per-page scale planes ride the page machinery — COW/prefix-hit and
  host-tier spill→restore serve token-identically to an uncached
  quantized serve, and ``page_bytes`` bills the true narrow bytes;
* SyncAudit over the quantized loop — one event fetch per segment,
  zero flagged;
* program space — the ``qpseg`` dtype rung enumerates, AOT-warms, and
  serves with zero post-warmup compiles;
* a journaled quantized serve replays bit-exactly (the header carries
  ``quant``; replay re-quantizes the same fp tree).

Suite-time contract: rides the session ``tiny_llama`` fixture and the
test_kv_tiers engine geometries; serves are short (gen <= 12) and the
heavier spill serve is module-scoped.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.ops.pallas.decode_attention as da
import paddle_tpu.ops.pallas.tick_fusion as tf
from paddle_tpu.inference.kv_tiers import HostTier, page_bytes
from paddle_tpu.inference.prefix_cache import PagedPrefixCache
from paddle_tpu.inference.scheduler import Arrival, OnlineScheduler
from paddle_tpu.inference.serving import ServingEngine, WorkloadEnvelope
from paddle_tpu.parallel import set_mesh
from paddle_tpu.quantization.serving import (
    QUANT_CODES, dequantize_weight, quant_dtype, quantize_kv_rows,
    quantize_llama_params, quantize_weight, quantized_weight_keys)


@pytest.fixture(scope="module")
def tiny(tiny_llama):
    set_mesh(None)
    return tiny_llama


def _mk(cfg, params, quant="int8", num_pages=24, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("prompt_buckets", (8, 16, 32, 64))
    return ServingEngine(cfg, params, paged=True, page_size=16,
                         num_pages=num_pages, quant=quant, **kw)


def _trace(cfg, seed=3, n=4, plen=16, gen=8):
    rng = np.random.RandomState(seed)
    return [Arrival(0.0, rng.randint(0, cfg.vocab_size, (plen,))
                    .astype(np.int32), gen) for _ in range(n)]


def _serve(eng, arr, seg_steps=8, pc=None):
    sch = OnlineScheduler(eng, seg_steps=seg_steps, prefix_cache=pc)
    rep = sch.serve(arr)
    out = sch.results()
    return rep, [out[k] for k in sorted(out)]


# ---------------------------------------------------------------------------
# numeric recipe
# ---------------------------------------------------------------------------


class TestRecipe:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_weight_roundtrip_error_bound(self, mode):
        """Dequantized weights sit within the per-channel step size of
        the fp32 original (int8: half a step after rounding; fp8 keeps
        a relative-error bound from e4m3's 3 mantissa bits)."""
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 48),
                              jnp.float32)
        q, s = quantize_weight(w, mode)
        assert q.dtype == quant_dtype(mode) and s.shape == (48,)
        err = np.abs(np.asarray(dequantize_weight(q, s)) - np.asarray(w))
        step = np.asarray(s)[None, :]
        bound = 0.51 * step if mode == "int8" else 32.0 * step
        assert (err <= bound).all(), float(err.max())

    def test_kv_rows_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 2, 8),
                              jnp.float32)
        q, s = quantize_kv_rows(x, jnp.int8)
        assert q.shape == x.shape and s.shape == (2, 5)
        back = np.asarray(q, np.float32) * np.asarray(s)[..., None, None]
        assert np.abs(back - np.asarray(x)).max() <= \
            0.51 * float(np.asarray(s).max())

    def test_double_quantize_refused(self, tiny):
        cfg, params = tiny
        qp = quantize_llama_params(params, cfg, "int8")
        for name in quantized_weight_keys(cfg):
            assert qp[name].dtype == jnp.int8
            assert name + "_scale" in qp
        with pytest.raises(ValueError, match="double-quantize"):
            quantize_llama_params(qp, cfg, "int8")


# ---------------------------------------------------------------------------
# in-kernel dequant parity (interpret mode = the exact kernel path)
# ---------------------------------------------------------------------------


class TestKernelParity:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_quant_matmul_matches_dense(self, mode):
        w = jax.random.normal(jax.random.PRNGKey(3), (64, 256),
                              jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(4), (4, 64),
                              jnp.float32)
        q, s = quantize_weight(w, mode)
        got = tf.quant_matmul(x, q, s, interpret=True)
        ref = x @ dequantize_weight(q, s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    def test_quant_matmul_active_gate(self, monkeypatch):
        set_mesh(None)
        assert not tf.quant_matmul_active(64, 256)   # CPU, no force
        monkeypatch.setattr(tf, "FORCE_INTERPRET", True)
        assert tf.quant_matmul_active(64, 256)
        assert not tf.quant_matmul_active(63, 256)   # contraction align
        assert not tf.quant_matmul_active(64, 100)   # no lane block

    def test_decode_attention_scales_match_predequantized(self):
        B, S, H, Hkv, D = 2, 128, 4, 2, 128
        kc = jax.random.normal(jax.random.PRNGKey(5), (B, S, Hkv, D),
                               jnp.float32)
        vc = jax.random.normal(jax.random.PRNGKey(6), (B, S, Hkv, D),
                               jnp.float32)
        q = jax.random.normal(jax.random.PRNGKey(7), (B, H, D),
                              jnp.float32)
        pos = jnp.array([5, 97], jnp.int32)
        kq, ks = quantize_kv_rows(kc, jnp.int8)
        vq, vs = quantize_kv_rows(vc, jnp.int8)
        got = da.ragged_decode_attention(q, kq, vq, pos, interpret=True,
                                         k_scale=ks, v_scale=vs)
        kd = kq.astype(jnp.float32) * ks[..., None, None]
        vd = vq.astype(jnp.float32) * vs[..., None, None]
        ref = da.ragged_decode_attention(q, kd, vd, pos, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# the quantized paged engine
# ---------------------------------------------------------------------------


class TestQuantEngine:
    def test_mode_and_combo_validation(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="quant"):
            _mk(cfg, params, quant="int4")
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(cfg, params, slots=2, max_len=96,
                          prompt_buckets=(8, 16), quant="int8")
        with pytest.raises(ValueError, match="quant"):
            _mk(cfg, params, speculative=2)

    def test_pool_planes_and_true_page_bytes(self, tiny):
        """The quantized pool carries int8 K/V planes plus fp32
        per-page-row scale planes, and page_bytes bills the TRUE
        narrow bytes (the tier budgets + §3n arithmetic read this)."""
        cfg, params = tiny
        eng_q = _mk(cfg, params)
        eng_b = _mk(cfg, params, quant=None)
        assert set(eng_q.pager.pool) == {"k", "v", "ks", "vs"}
        assert eng_q.pager.pool["k"].dtype == jnp.int8
        assert eng_q.pager.pool["ks"].dtype == jnp.float32
        bq, bb = page_bytes(eng_q.pager), page_bytes(eng_b.pager)
        L = cfg.num_layers
        elems = L * 16 * cfg.num_kv_heads * cfg.head_dim
        assert bq == 2 * (elems + L * 16 * 4)   # int8 k/v + fp32 ks/vs
        assert bq < bb

    def test_deterministic_and_matches_bf16_above_floor(self, tiny):
        """Same dtype -> bit-identical serves; across dtypes the
        matched-prefix rate clears the floor (the §3p bar — random-init
        weights are the pessimistic case, so the floor is loose)."""
        from paddle_tpu.observability.quality import compare_pair

        cfg, params = tiny
        arr = _trace(cfg)
        _, out1 = _serve(_mk(cfg, params), arr)
        _, out2 = _serve(_mk(cfg, params), arr)
        assert out1 == out2
        _, outb = _serve(_mk(cfg, params, quant=None), arr)
        matched = compared = 0
        for b, q in zip(outb, out1):
            r = compare_pair(b, q)
            matched += r["tokens_matched"]
            compared += r["compared"]
        assert compared > 0 and matched / compared >= 0.5, \
            (matched, compared)

    def test_fp8_serves_deterministically(self, tiny):
        cfg, params = tiny
        arr = _trace(cfg, n=2)
        _, out1 = _serve(_mk(cfg, params, quant="fp8"), arr)
        _, out2 = _serve(_mk(cfg, params, quant="fp8"), arr)
        assert out1 == out2
        assert all(len(t) for t in out1)


# ---------------------------------------------------------------------------
# scale planes ride the page machinery: COW / prefix hits / host spill
# ---------------------------------------------------------------------------


class TestQuantPages:
    def test_prefix_hit_and_cow_token_identity(self, tiny):
        """Shared-prefix quantized serve through the paged prefix cache
        (hits + COW on the shared pages) is token-identical to the
        uncached quantized serve."""
        cfg, params = tiny
        rng = np.random.RandomState(11)
        prefix = rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
        arr = [Arrival(0.0, np.concatenate(
            [prefix, rng.randint(0, cfg.vocab_size, (8,))
             .astype(np.int32)]), 8) for _ in range(4)]
        _, cold = _serve(_mk(cfg, params), arr)
        eng = _mk(cfg, params)
        pc = PagedPrefixCache(eng.pager, capacity_pages=8)
        _, hit = _serve(eng, arr, pc=pc)
        assert pc.stats()["hits"] > 0
        assert hit == cold

    def test_host_spill_restore_token_identity(self, tiny):
        """Spill-heavy quantized serve through the host tier: the scale
        planes spill/restore with the page bytes and tokens match the
        uncached quantized serve; spilled host bytes are the narrow
        page size."""
        cfg, params = tiny
        rng = np.random.RandomState(12)
        prefs = [rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
                 for _ in range(4)]
        arr = [Arrival(0.0, np.concatenate(
            [prefs[i % 4], rng.randint(0, cfg.vocab_size, (8,))
             .astype(np.int32)]), 8) for i in range(8)]
        _, ref = _serve(_mk(cfg, params, num_pages=40), arr)
        eng = _mk(cfg, params, num_pages=11)
        tier = HostTier(eng.pager, capacity_pages=64)
        pc = PagedPrefixCache(eng.pager, capacity_pages=8,
                              host_tier=tier)
        _, out = _serve(eng, arr, pc=pc)
        assert out == ref
        assert pc.spills > 0 and pc.restores > 0
        for ent in tier._host.values():
            assert set(ent) >= {"k", "v", "ks", "vs"}
            assert ent["k"].dtype == np.int8


# ---------------------------------------------------------------------------
# sync audit over the quantized loop
# ---------------------------------------------------------------------------


class TestQuantSyncAudit:
    def test_one_fetch_per_segment_zero_flagged(self, tiny):
        from paddle_tpu.analysis import SyncAudit

        cfg, params = tiny
        arr = _trace(cfg, n=4)
        eng = _mk(cfg, params)
        sch = OnlineScheduler(eng, seg_steps=8)
        sch.serve(arr)                  # warm (compiles outside audit)
        sch.results()
        eng.reset_slots()
        sch._reqs.clear()
        with SyncAudit() as audit:
            audit.phase = "serve"
            rep = sch.serve(arr)
        assert audit.flagged("serve") == [], audit.flagged("serve")
        assert audit.allowed("serve") == {
            "serving.segment_event_fetch": rep.segments}


# ---------------------------------------------------------------------------
# program space: the qpseg dtype rung
# ---------------------------------------------------------------------------


class TestQuantProgramSpace:
    def test_qpseg_enumerates_and_zero_compile_serve(self, tiny):
        """The quantized engine's reachable ladder is the qpseg family
        (dtype axis = the quant code); aot_warmup compiles it and the
        serve afterwards triggers ZERO backend compiles."""
        from paddle_tpu.analysis import coverage, recompile
        from paddle_tpu.inference import serving as _serving
        from paddle_tpu.inference.program_space import PROGRAM_SPACE

        cfg, params = tiny
        arr = _trace(cfg, n=3)
        env = WorkloadEnvelope(max_prompt=16, max_new_tokens=8,
                               seg_steps=(8,), prefix_block=16)
        saved = dict(_serving._SHARED_PROGS)
        try:
            _serving._SHARED_PROGS.clear()
            eng = _mk(cfg, params)
            keys = PROGRAM_SPACE.enumerate(eng, env)
            fams = {k[0] for k in keys}
            assert "qpseg" in fams and "pseg" not in fams
            assert all(k[-1] == QUANT_CODES["int8"] for k in keys
                       if k[0] == "qpseg")
            eng.aot_warmup(env)
            sch = OnlineScheduler(eng, seg_steps=8)
            with recompile.enforce_zero_compiles(
                    "warmed quant serve") as cw:
                sch.serve(arr)
            assert cw.compiles == 0
            assert coverage.coverage_report(eng, env).ok
        finally:
            _serving._SHARED_PROGS.clear()
            _serving._SHARED_PROGS.update(saved)

    def test_dtype_axis_separates_modes(self, tiny):
        """int8 and fp8 engines enumerate DIFFERENT qpseg keys — the
        dtype axis is real, so the AOT ladder can't serve one mode's
        programs to the other."""
        from paddle_tpu.inference.program_space import PROGRAM_SPACE

        cfg, params = tiny
        env = WorkloadEnvelope(max_prompt=16, max_new_tokens=8,
                               seg_steps=(8,), prefix_block=16)
        k8 = PROGRAM_SPACE.enumerate(_mk(cfg, params), env)
        kf = PROGRAM_SPACE.enumerate(_mk(cfg, params, quant="fp8"), env)
        assert k8 and kf and not (set(k8) & set(kf))


# ---------------------------------------------------------------------------
# journaled quantized serve replays bit-exactly
# ---------------------------------------------------------------------------


class TestQuantReplay:
    def test_journal_replay_identical(self, tiny, tmp_path):
        from paddle_tpu.observability import journal as jmod
        from paddle_tpu.observability import replay_serve

        cfg, params = tiny
        arr = _trace(cfg, n=3)
        eng = _mk(cfg, params)
        sch = OnlineScheduler(eng, seg_steps=8)
        jq = jmod.Journal(str(tmp_path))
        jq.params_info = {"prng_seed": 0}
        with jmod.attach(jq):
            sch.serve(arr)
        jq.close()
        res = replay_serve(str(tmp_path), params=params)
        assert res.identical, res.divergence
        assert res.n_decisions > 0

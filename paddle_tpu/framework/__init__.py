from . import random
from .random import (get_cuda_rng_state, get_rng_state, seed,
                     set_cuda_rng_state, set_rng_state)

"""``paddle.incubate.nn.functional`` — fused-op entry points.

Reference counterpart: ``python/paddle/incubate/nn/functional/`` exposing
the fused CUDA kernels (``fused_attention``, ``fused_feedforward``,
``fused_rotary_position_embedding``, ``fused_rms_norm``,
``fused_linear``; SURVEY.md §2.1 "Fused kernels"). TPU-native: the fusions
the reference hand-writes are XLA's job — these wrappers express the math
in fusion-friendly form (and route attention to the Pallas flash kernel);
the API names exist so reference model code ports unchanged.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, to_tensor
from ...nn import functional as F
from ...ops.dispatch import run_op
from ...ops.pallas.flash_attention import dot_product_attention

__all__ = ["fused_linear", "fused_rms_norm", "fused_layer_norm",
           "fused_rotary_position_embedding", "fused_feedforward",
           "flash_attention", "fused_multi_head_attention"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """GEMM+bias epilogue (reference: ``fused_gemm_epilogue``); XLA fuses
    the bias add into the matmul epilogue on its own."""
    if transpose_weight:
        from ...ops.manipulation import transpose

        weight = transpose(weight, [1, 0])
    return F.linear(x, weight, bias)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-6,
                   begin_norm_axis: int = -1, name=None):
    return F.rms_norm(x, norm_weight, epsilon=epsilon) if norm_bias is None \
        else F.rms_norm(x, norm_weight, epsilon=epsilon) + norm_bias


def fused_layer_norm(x, norm_weight, norm_bias, epsilon: float = 1e-5,
                     begin_norm_axis: int = -1, name=None):
    return F.layer_norm(x, x.shape[begin_norm_axis:], weight=norm_weight,
                        bias=norm_bias, epsilon=epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    name=None):
    """RoPE applied to q (and k) — reference: ``fused_rope`` kernel.

    q/k: [B, S, H, D]. When sin/cos are None they are computed with the
    standard 10000^(-2i/D) frequencies."""

    pos_ids = None
    if position_ids is not None:
        pos_ids = position_ids._value if isinstance(position_ids, Tensor) \
            else jnp.asarray(position_ids)

    def rope_one(t, sin_, cos_):
        B, S, H, D = t.shape
        tf = t.astype(jnp.float32)
        if use_neox_rotary_style:
            half = tf.reshape(B, S, H, 2, D // 2)
            x1, x2 = half[..., 0, :], half[..., 1, :]
            rx1 = x1 * cos_ - x2 * sin_
            rx2 = x2 * cos_ + x1 * sin_
            out = jnp.stack([rx1, rx2], axis=-2).reshape(B, S, H, D)
        else:
            x1 = tf[..., 0::2]
            x2 = tf[..., 1::2]
            rx1 = x1 * cos_ - x2 * sin_
            rx2 = x2 * cos_ + x1 * sin_
            out = jnp.stack([rx1, rx2], axis=-1).reshape(B, S, H, D)
        return out.astype(t.dtype)

    def make_sin_cos(S, D, dtype):
        inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        if pos_ids is not None:
            # KV-cache decode: absolute positions supplied by the caller
            pos = pos_ids.astype(jnp.float32)  # [S] or [B, S]
            ang = pos[..., None] * inv  # [..., S, D/2]
            if ang.ndim == 2:  # [S, D/2]
                return (jnp.sin(ang)[None, :, None, :],
                        jnp.cos(ang)[None, :, None, :])
            return (jnp.sin(ang)[:, :, None, :],  # [B, S, 1, D/2]
                    jnp.cos(ang)[:, :, None, :])
        pos = jnp.arange(S, dtype=jnp.float32)
        ang = jnp.outer(pos, inv)  # [S, D/2]
        return jnp.sin(ang)[None, :, None, :], jnp.cos(ang)[None, :, None, :]

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        S, D = t.shape[1], t.shape[-1]
        if sin is None or cos is None:
            s_, c_ = make_sin_cos(S, D, t.dtype)
        else:
            s_ = sin._value if isinstance(sin, Tensor) else jnp.asarray(sin)
            c_ = cos._value if isinstance(cos, Tensor) else jnp.asarray(cos)
            s_ = s_.reshape(-1, s_.shape[-1])  # [S_max, D/2]
            c_ = c_.reshape(-1, c_.shape[-1])
            if pos_ids is not None:
                # gather the caller's table rows at the absolute positions
                s_, c_ = jnp.take(s_, pos_ids, 0), jnp.take(c_, pos_ids, 0)
                if s_.ndim == 3:  # [B, S, D/2]
                    s_, c_ = s_[:, :, None, :], c_[:, :, None, :]
                else:
                    s_, c_ = s_[None, :, None, :], c_[None, :, None, :]
            else:
                s_, c_ = s_[None, :, None, :], c_[None, :, None, :]
        outs.append(run_op("fused_rope", lambda a, s=s_, c=c_: rope_one(a, s, c), t))
    return tuple(outs)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      name=None):
    """Transformer FFN block (reference: ``fused_feedforward`` kernel):
    residual + LN + linear-act-dropout-linear-dropout, pre- or post-LN."""
    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = F.layer_norm(x, x.shape[-1:], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    y = F.linear(x, linear1_weight, linear1_bias)
    y = getattr(F, activation)(y)
    y = F.dropout(y, p=dropout1_rate, training=training, mode=mode)
    y = F.linear(y, linear2_weight, linear2_bias)
    y = F.dropout(y, p=dropout2_rate, training=training, mode=mode)
    out = residual + y
    if not pre_layer_norm and ln2_scale is not None:
        out = F.layer_norm(out, out.shape[-1:], weight=ln2_scale,
                           bias=ln2_bias, epsilon=ln2_epsilon)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention signature over the Pallas
    kernel ([B, S, H, D] layout, like the reference's flash_attn)."""
    out = run_op(
        "flash_attention",
        lambda q, k, v: dot_product_attention(q, k, v, is_causal=causal),
        query, key, value,
    )
    return out, None  # (out, softmax) — softmax never materialised (flash)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """Reference ``fused_attention``: LN→QKV→MHA→proj→dropout→residual."""
    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = F.layer_norm(x, x.shape[-1:], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    B, S, H = x.shape
    # qkv_weight: [3, num_heads, head_dim, H] (reference layout)
    n_heads = qkv_weight.shape[1]
    head_dim = qkv_weight.shape[2]
    mask_val = None
    if attn_mask is not None:
        mask_val = attn_mask._value if isinstance(attn_mask, Tensor) \
            else jnp.asarray(attn_mask)
        while mask_val.ndim < 4:  # broadcast to [B, n_heads, S, S]
            mask_val = mask_val[None]

    def qkv_proj(xa, wa, *rest):
        bias = rest[0] if len(rest) else None
        w = wa.reshape(3 * n_heads * head_dim, H).T  # [H, 3*Hd]
        qkv = xa @ w
        if bias is not None:
            qkv = qkv + bias.reshape(-1)
        qkv = qkv.reshape(B, S, 3, n_heads, head_dim)
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    args = [x, qkv_weight] + ([qkv_bias] if qkv_bias is not None else [])
    q, k, v = run_op("fused_attention_qkv", qkv_proj, *args)
    # attention through the shared SDPA dispatch so attn_dropout_rate gets
    # the reference's PROBS-level dropout semantics
    mask_t = to_tensor(mask_val) if mask_val is not None else None
    o = F.scaled_dot_product_attention(q, k, v, attn_mask=mask_t,
                                       dropout_p=attn_dropout_rate,
                                       training=training)
    o = o.reshape([B, S, n_heads * head_dim])
    o = F.linear(o, linear_weight, linear_bias)
    o = F.dropout(o, p=dropout_rate, training=training, mode=mode)
    out = o + residual if add_residual else o
    if not pre_layer_norm and ln_scale is not None:
        out = F.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def swiglu(x, y=None, name=None):
    """SwiGLU activation (reference ``incubate.nn.functional.swiglu``):
    silu(x) * y; with ``y=None``, x splits into two halves on the last
    axis (the llama MLP convention)."""
    if y is None:
        if x.shape[-1] % 2:
            raise ValueError(
                f"swiglu with y=None needs an even last dim to split, got "
                f"{x.shape[-1]}")
        d = x.shape[-1] // 2
        x, y = x[..., :d], x[..., d:]
    return F.silu(x) * y


__all__ += ["swiglu"]

"""TPU test lane: run the TPU-only pallas-kernel tests on the real chip and
record the result as a per-round artifact next to BENCH (VERDICT r2 weak #5:
the kernel tests are invisible to the CPU-forced default suite, so a silent
flash-kernel regression would only surface as a bench drop).

Writes ``TPU_TESTS_r<N>.json`` at the repo root:
  {"passed": n, "failed": n, "skipped": n, "duration_s": s,
   "tests": [{"id": ..., "outcome": ..., "duration_s": ...}, ...]}

Usage: python benchmarks/tpu_test_lane.py [round_number]
(no args: derives the round from the highest existing BENCH_r*.json).
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TPU_TEST_FILES = [
    "tests/test_flash_attention_tpu.py",
    "tests/test_flash_packed_gating.py",
    "tests/test_resnet_fusion_tpu.py",
    # r4: on-chip END-TO-END certification — full bf16 train steps
    # (framework numerics + fused optimizer), not just kernels
    "tests/test_train_step_tpu.py",
    # r7 (VERDICT r5 item 6): the INFERENCE surface — generate() chip-vs-
    # CPU greedy parity, fused-drain mixed-lengths+EOS, re-entrant
    # segments, unrolled-KV vs scan-layers cache parity, prefix-cache hit
    # (tests/test_decode_attention.py stays OUT of this lane: its
    # cpu-defaults-stay-dense assertion is false on a chip by design)
    "tests/test_inference_tpu.py",
    # r8 (ISSUE 3): the Pallas fused multi-tensor optimizer update —
    # real-Mosaic (SMEM scalars, in-place aliasing) trajectory parity
    "tests/test_fused_update_tpu.py",
    # r9 (ISSUE 4): the program auditor — sync/recompile/relayout/
    # donation passes on the REAL backend (the 8-device collective
    # fixtures skip on a single chip; the budget gate below certifies
    # the canonical programs' budgets on hardware)
    "tests/test_analysis.py",
    # r11 (ISSUE 6): the paged KV subsystem — on chip the engine/kernel
    # parity tests route attention through the REAL unified
    # page-indirect Mosaic kernel (scalar-prefetched page tables), so a
    # paging regression the CPU gather fallback hides fails here
    "tests/test_paged_kv.py",
    # r12 (ISSUE 7): the fleet serving subsystem — router determinism /
    # affinity / backpressure smoke on the real backend, plus the mp=2
    # tensor-parallel segment parity tests (these skip on a single-chip
    # host and run when the lane sees a multi-device TPU)
    "tests/test_fleet_serving.py",
    # r13 (ISSUE 8): the SLO robustness subsystem — chunked-prefill
    # parity through the REAL unified kernel, priority preemption /
    # resume identity, deadline shedding, fleet kill/recover
    "tests/test_slo_serving.py",
    # r14 (ISSUE 9): the SLO monitor & live ops surface — burn-rate
    # alert rules, exporter round-trips on loopback, explained-perf
    # ledger parity, the regression sentinel, cold-start stamping, and
    # the monitored-serve sync audit, all against the real backend
    "tests/test_slo_monitor.py",
    # r15 (ISSUE 10): speculative + sampled decoding — the multi-token
    # verified tick's greedy token identity, in-program sampling seed
    # isolation/replay, the speculative serve-loop sync audit and the
    # acceptance-aware SLO estimates, all against the real backend
    # (the verify path reuses the unified paged kernel's q_len>1 rows)
    "tests/test_spec_sampling.py",
    # r16 (ISSUE 11): the deterministic serving journal — replay
    # identity of journaled overload + fleet-failover serves on the
    # real backend (the fed decision clock makes replay timing-immune,
    # so chip compiles must not perturb a single decision), journey
    # joins, and the journaled-serve sync audit
    "tests/test_journal.py",
    # r17 (ISSUE 12): shadow & canary quality observability — the
    # in-program logit-digest segment on the real backend (digests
    # ride the real kernel's logits through the single fetch),
    # shadow-diff control identity, perturbation detection with exact
    # first-divergence positions, canary verdicts + auto-hold, and the
    # shadowed-fleet-loop sync audit
    "tests/test_quality.py",
    # r18 (ISSUE 13): capacity & memory observability — the page-level
    # metering identities, exhaustion-alert-leads-backpressure ordering
    # on a tight pool, the §3f×§3g planner validation, the /capacity
    # (+audit) endpoint and the monitored-serve sync audit, all against
    # the real backend's paged allocator traffic
    "tests/test_capacity.py",
    # r19 (ISSUE 14): tiered KV memory — spill->restore token identity
    # (host staging riding the real backend's single segment fetch),
    # the one-fetch audit over the tiered loop, directory steering +
    # migration-on-miss, the tier-transfer budget pass, and journal
    # replay of a spill-heavy serve, all against real D2H/H2D copies
    "tests/test_kv_tiers.py",
    # r20 (ISSUE 15): program-space coverage — registry-only key
    # construction, the envelope reachability proof, AOT warmup with
    # the zero-post-warmup-compile budget over the mixed workload, and
    # the persistent-cache warm-restart interplay, against REAL XLA:TPU
    # compiles (the 2.5 s class this whole subsystem exists to bound)
    "tests/test_program_coverage.py",
    # r21 (ISSUE 16): quantized serving — on chip the engine's
    # projection matmuls route through the REAL in-kernel-dequant
    # Mosaic path (quant_matmul) and the scale-fed decode-attention
    # kernel, so HBM genuinely carries int8/fp8; the parity, page-
    # machinery, sync-audit, qpseg-coverage and replay tests all gain
    # their hardware half here
    "tests/test_quantized_serving.py",
    # r22 (ISSUE 17): disaggregated prefill/decode serving — on chip
    # the handoff's host-bytes seam becomes the device-to-device
    # device_put path, so token identity across the pool split, the
    # per-crossing budget audit, per-pool AOT coverage and the
    # cross-pool replay all gain their hardware half here
    "tests/test_disagg.py",
    # r23 (ISSUE 18): long-context serving — on chip the spseg slab's
    # batch axis rides a REAL 'sp' mesh (each chunk's rows on their own
    # devices, ring attention via ppermute), so the sp=1 pseg
    # degeneracy, sp=2 pool page parity, slab-vs-dense identity, the
    # spanning-reservation continuation and the spseg AOT/zero-compile
    # certificate all gain their hardware half here
    "tests/test_longctx_serving.py",
    # r25 (ISSUE 20): elastic autoscaling — on chip the §3o warmup of
    # every scaled-up replica compiles the REAL ladder, chip_fit proves
    # candidates against the real HBM envelope, and the zero-compile +
    # sync-audit bars over the full elastic loop (scale-ups, drains,
    # directory migrations) gain their hardware half here
    "tests/test_autoscaler.py",
]


def _run_budget_gate(env) -> dict:
    """r9: certify the canonical programs' hazard budgets on the
    real chip (``python -m paddle_tpu.analysis --gate``) and record the
    per-program metrics next to the test outcomes. On TPU the relayout
    ledger counts the REAL tiled-layout copies, so a chip-only
    regression (a new relayout XLA:TPU materialises that the CPU
    lowering fused) fails here even when tier-1 stayed green."""
    import tempfile

    out_json = os.path.join(tempfile.gettempdir(),
                            f"_analysis_gate_{os.getpid()}.json")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--gate",
         "--json", out_json],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=900)
    gate = {"returncode": proc.returncode, "programs": []}
    if os.path.exists(out_json):
        with open(out_json) as f:
            gate["programs"] = json.load(f)
        os.remove(out_json)
    # r24: the per-program liveness peak ON CHIP — the XLA:TPU schedule
    # fuses/tiles differently from the CPU lowering, so these are the
    # measurements a "tpu"-scoped peak_bytes_max budget gets pinned
    # from (the chip cells of the budget registry)
    gate["peak_hbm_bytes"] = {
        p["program"]: p["metrics"].get("peak_bytes")
        for p in gate["programs"]
        if isinstance(p, dict) and "metrics" in p
        and not p.get("program", "").startswith("_")}
    if proc.returncode != 0:
        gate["tail"] = proc.stdout[-1500:]
    return gate


def _run_serving_telemetry(env) -> dict:
    """r10: record a CHIP-SIDE runtime-telemetry snapshot — the serving
    smoke workload on the real backend with the observability subsystem
    on, so TPU_TESTS_r<N>.json embeds measured serving occupancy / TTFT
    / admission metrics next to the test outcomes (the telemetry analog
    of the budget gate: a metric that silently stops moving on chip is
    visible in the round record)."""
    proc = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "llama_serving.py"),
         "--smoke"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    out = {"returncode": proc.returncode}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            ev = json.loads(line)
            out["telemetry"] = ev.get("telemetry")
            out["throughput_vs_fixed"] = ev.get("throughput_vs_fixed")
            out["ttft_p50_s"] = ev.get("ttft_p50_s")
            break
        except json.JSONDecodeError:
            continue
    if proc.returncode != 0:
        out["tail"] = proc.stderr[-1500:]
    return out


def _round_number(argv) -> int:
    if len(argv) > 1:
        return int(argv[1])
    rounds = [int(m.group(1)) for f in glob.glob(os.path.join(ROOT, "BENCH_r*.json"))
              if (m := re.search(r"BENCH_r(\d+)\.json$", f))]
    return (max(rounds) + 1) if rounds else 1


def main() -> int:
    rnd = _round_number(sys.argv)
    report = os.path.join(ROOT, f"_tpu_lane_report_{os.getpid()}.xml")
    env = dict(os.environ, PADDLE_TPU_TEST_LANE="1")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *TPU_TEST_FILES, "-q",
         f"--junit-xml={report}"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=1800)
    dur = time.time() - t0
    tests = []
    counts = {"passed": 0, "failed": 0, "skipped": 0}
    if os.path.exists(report):
        import xml.etree.ElementTree as ET

        for tc in ET.parse(report).getroot().iter("testcase"):
            if tc.find("failure") is not None or tc.find("error") is not None:
                outcome = "failed"
            elif tc.find("skipped") is not None:
                outcome = "skipped"
            else:
                outcome = "passed"
            counts[outcome] += 1
            tests.append({
                "id": f"{tc.get('classname', '')}::{tc.get('name', '')}",
                "outcome": outcome,
                "duration_s": round(float(tc.get("time", 0.0)), 3)})
        os.remove(report)
    else:
        # junit report missing (collection error): parse the summary line
        m = re.search(r"(\d+) passed", proc.stdout)
        counts["passed"] = int(m.group(1)) if m else 0
        m = re.search(r"(\d+) failed", proc.stdout)
        counts["failed"] = int(m.group(1)) if m else 0
        m = re.search(r"(\d+) skipped", proc.stdout)
        counts["skipped"] = int(m.group(1)) if m else 0
    gate = _run_budget_gate(env)
    serving_telemetry = _run_serving_telemetry(env)
    result = {
        "round": rnd,
        "platform": "tpu" if counts["passed"] else "unknown",
        "passed": counts.get("passed", 0),
        "failed": counts.get("failed", 0),
        "skipped": counts.get("skipped", 0),
        "duration_s": round(dur, 1),
        "returncode": proc.returncode,
        "analysis_gate": gate,
        "serving_telemetry": serving_telemetry,
        "tests": tests,
    }
    out_path = os.path.join(ROOT, f"TPU_TESTS_r{rnd:02d}.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("round", "passed", "failed", "skipped", "duration_s")}
                     | {"analysis_gate_rc": gate["returncode"]}))
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-2000:])
    return proc.returncode or gate["returncode"]


if __name__ == "__main__":
    sys.exit(main())

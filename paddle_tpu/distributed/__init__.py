"""``paddle.distributed`` surface (reference: ``python/paddle/distributed/``;
SURVEY.md §2.2). Mesh-first TPU-native design: process groups map to mesh
axes, collectives are XLA ops, hybrid parallel lives in ``fleet``."""

from .collective import (
    Group,
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    alltoall,
    alltoall_single,
    gather,
    broadcast_object_list,
    barrier,
    broadcast,
    get_default_group,
    get_group,
    irecv,
    isend,
    new_group,
    recv,
    recv_prev,
    reduce,
    batch_isend_irecv,
    destroy_process_group,
    get_backend,
    reduce_scatter,
    scatter,
    scatter_object_list,
    send,
    send_next,
    split,
    wait,
)
from .env import (
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
from .parallel import DataParallel
from . import utils  # noqa: F401
from . import (auto_parallel, checkpoint, communication, fleet, launch, ps,
               rpc, sharding)
from .communication import stream  # noqa: F401
from .store import TCPStore
from .auto_parallel import (
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    unshard_dtensor,
    reshard,
    shard_layer,
    shard_tensor,
)
from .sharding import group_sharded_parallel, save_group_sharded_model

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "get_default_group",
    "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "broadcast", "scatter", "alltoall", "all_to_all",
    "send", "recv", "send_next", "recv_prev", "isend", "irecv", "barrier", "ParallelEnv", "get_rank",
    "P2POp", "batch_isend_irecv", "wait", "destroy_process_group",
    "get_backend", "scatter_object_list", "split", "utils",
    "get_world_size", "init_parallel_env", "is_initialized", "DataParallel",
    "spawn", "launch", "fleet", "sharding", "group_sharded_parallel",
    "save_group_sharded_model", "auto_parallel", "ProcessMesh", "Placement",
    "Shard", "Replicate", "Partial", "shard_tensor", "dtensor_from_fn",
    "unshard_dtensor", "alltoall_single", "gather", "broadcast_object_list",
    "reshard", "shard_layer", "TCPStore",
]


def spawn(func, args=(), nprocs=-1, **options):
    """``paddle.distributed.spawn`` analog (multiprocessing launcher)."""
    import multiprocessing as mp
    import os

    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
        }

        def target(rank=rank, env=env):
            os.environ.update(env)
            func(*args)

        p = mp.Process(target=target)
        p.start()
        procs.append(p)
    for p in procs:
        p.join()
        if p.exitcode != 0:
            raise RuntimeError(f"spawned process exited with {p.exitcode}")

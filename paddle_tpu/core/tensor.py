"""The Tensor.

TPU-native counterpart of ``phi::DenseTensor`` + Python ``paddle.Tensor``
(``paddle/phi/core/dense_tensor.h`` + pybind eager tensor; SURVEY.md §2.1).
A ``Tensor`` is a thin mutable wrapper over a ``jax.Array`` (or a jax tracer
while inside ``jit``): XLA/PJRT owns layout, memory and device placement
(replacing the reference's allocator stack), while this wrapper carries the
framework-level state the reference keeps in ``AutogradMeta`` — ``stop_gradient``,
``.grad``, hooks, name, persistable — and the dygraph in-place semantics
(methods like ``add_`` rebind the underlying immutable array).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..enforce import InvalidArgumentError
from . import autograd
from .dtype import convert_dtype, is_floating_dtype
from .place import CPUPlace, CUDAPlace, Place, TPUPlace, device_for_place, expected_place

__all__ = ["Tensor", "to_tensor"]

# Host-sync audit hook (analysis.syncs): while a SyncAudit is active it
# holds ONE context-factory `(kind, value) -> contextmanager`; every
# device→host coercion below enters it so the auditor can record the
# sync (and its call site) without the framework paying anything when no
# audit is running — the list is empty then and the check is one truth
# test. Reference hazard class: the r8 GradScaler per-param ``bool()``.
_SYNC_AUDIT_HOOK: list = []


def _sync_scope(kind, value):
    """Audit scope for one coercion; nullcontext-free fast path."""
    return _SYNC_AUDIT_HOOK[0](kind, value)


_tensor_counter = [0]


def _auto_name(prefix="tensor"):
    _tensor_counter[0] += 1
    return f"{prefix}_{_tensor_counter[0]}"


class Tensor:
    """Mutable framework tensor over an immutable jax value."""

    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "_hooks",
        "name",
        "persistable",
        "trainable",
        # auto-parallel dist attrs (reference: DistTensor.dist_attr)
        "process_mesh",
        "placements",
        "__weakref__",
    )

    def __init__(
        self,
        value: Any,
        stop_gradient: bool = True,
        name: Optional[str] = None,
        persistable: bool = False,
    ):
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self._hooks = []
        self.name = name or _auto_name()
        self.persistable = persistable
        self.trainable = not stop_gradient
        # auto-parallel dist attrs: None on dense tensors (reference:
        # DistTensor.dist_attr defaults), set by shard_tensor/reshard
        self.process_mesh = None
        self.placements = None

    # -- raw value access ---------------------------------------------------
    @property
    def value(self):
        return self._value

    # -- metadata (TensorMeta analog) --------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    @property
    def dtype(self):
        return jnp.dtype(self._value.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self) -> Place:
        devs = getattr(self._value, "devices", None)
        if devs is None:
            return expected_place()
        dev = next(iter(self._value.devices()))
        kind = {"cpu": CPUPlace, "tpu": TPUPlace, "axon": TPUPlace, "gpu": CUDAPlace}.get(
            dev.platform, CPUPlace
        )
        return kind(dev.id)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def strides(self):
        """Element strides of the (always densely-packed) row-major layout.

        Reference: ``Tensor.strides`` / ``DenseTensor::strides()``
        (SURVEY §2.1 other-tensor-kinds). XLA arrays carry no user-visible
        aliasing layout — every jax.Array is logically contiguous — so the
        strides are the canonical C-order ones; the strided-READ ops
        (``as_strided``, ``Tensor.unfold``) are gather-based shims over
        this contract, and strided aliasing MUTATION is out of scope by
        design (immutable arrays)."""
        shape = self._value.shape
        out = []
        acc = 1
        for s in reversed(shape):
            out.append(acc)
            acc *= int(s)
        return list(reversed(out))

    def get_strides(self):
        return self.strides

    def is_contiguous(self) -> bool:
        """Always True: XLA buffers have no non-contiguous aliasing views
        (reference Tensor.is_contiguous)."""
        return True

    def contiguous(self) -> "Tensor":
        """Identity — see ``is_contiguous`` (reference Tensor.contiguous)."""
        return self

    def numel(self) -> int:
        return self.size

    def dim(self) -> int:
        return self.ndim

    def is_floating_point(self) -> bool:
        return is_floating_dtype(self.dtype)

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        if _SYNC_AUDIT_HOOK:
            with _sync_scope("tensor.numpy", self._value):
                return self._numpy_impl()
        return self._numpy_impl()

    def _numpy_impl(self) -> np.ndarray:
        v = self._value
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            # some PJRT transports (the axon TPU tunnel) can't transfer
            # complex buffers — move real/imag separately and recombine
            try:
                return np.asarray(v)
            except Exception:
                re = np.asarray(jnp.real(v))
                im = np.asarray(jnp.imag(v))
                return (re + 1j * im).astype(np.dtype(v.dtype))
        return np.asarray(v)

    def item(self):
        if _SYNC_AUDIT_HOOK:
            with _sync_scope("tensor.item", self._value):
                return self._item_impl()
        return self._item_impl()

    def _item_impl(self):
        return self._value.item() if hasattr(self._value, "item") else self._value

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        if _SYNC_AUDIT_HOOK:
            with _sync_scope("tensor.numpy", self._value):
                a = self._numpy_impl()
        else:
            a = self._numpy_impl()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        if _SYNC_AUDIT_HOOK:
            with _sync_scope("tensor.float", self._value):
                return float(self._item_impl())
        return float(self._item_impl())

    def __int__(self):
        if _SYNC_AUDIT_HOOK:
            with _sync_scope("tensor.int", self._value):
                return int(self._item_impl())
        return int(self._item_impl())

    def __bool__(self):
        if _SYNC_AUDIT_HOOK:
            with _sync_scope("tensor.bool", self._value):
                return bool(self._item_impl())
        return bool(self._item_impl())

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            data = np.array2string(self.numpy(), precision=6, separator=", ", threshold=64)
        except Exception:
            data = f"<{type(self._value).__name__}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={sg},\n       {data})"
        )

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):  # paddle spelling
        self.grad = None

    def register_hook(self, hook):
        """Hook runs on this tensor's gradient during backward. For
        intermediates it can rewrite the flowing gradient; for leaves it runs
        before accumulation into ``.grad``."""
        if self._grad_node is not None:
            self._grad_node.hooks.setdefault(self._out_index, []).append(hook)
        else:
            self._hooks.append(hook)
        return hook

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def element_size(self) -> int:
        """Bytes per element (reference: ``Tensor.element_size``)."""
        return int(jnp.dtype(self._value.dtype).itemsize)

    def pin_memory(self) -> "Tensor":
        """API parity: XLA manages host staging buffers itself."""
        return self

    def contiguous(self) -> "Tensor":
        """API parity: jax.Arrays are always dense/contiguous."""
        return self

    def coalesce(self) -> "Tensor":
        """Reference ``Tensor.coalesce``: only meaningful for sparse COO
        tensors (``paddle.sparse.sparse_coo_tensor(...).coalesce()``,
        where SparseCooTensor implements it); a dense tensor raises like
        the reference does."""
        raise ValueError(
            "coalesce() expects a sparse COO tensor; this tensor is dense "
            "(create one with paddle.sparse.sparse_coo_tensor)")

    def is_contiguous(self) -> bool:
        return True

    def clone(self) -> "Tensor":
        from ..ops.dispatch import run_op

        return run_op("clone", lambda x: x + jnp.zeros((), self._value.dtype), self)

    # -- device / dtype movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=True) -> "Tensor":
        # dtype casts and device moves both go through run_op so autograd is
        # preserved (jax.device_put is differentiable).
        from ..ops.dispatch import run_op
        from .place import _parse_device

        target_dt = convert_dtype(dtype) if dtype is not None else None
        dev = device_for_place(_parse_device(device)) if device is not None else None

        def f(a):
            if target_dt is not None:
                a = a.astype(target_dt)
            if dev is not None and not isinstance(a, jax.core.Tracer):
                a = jax.device_put(a, dev)
            return a

        t = run_op("to", f, self)
        t.name = self.name
        if self.stop_gradient:
            t.stop_gradient = True
        return t

    def cpu(self) -> "Tensor":
        return self.to("cpu")

    def cuda(self, device_id: int = 0) -> "Tensor":
        return self.to(f"gpu:{device_id}")

    def tpu(self, device_id: int = 0) -> "Tensor":
        return self.to(f"tpu:{device_id}")

    def astype(self, dt) -> "Tensor":
        from ..ops.dispatch import run_op

        target = convert_dtype(dt)
        return run_op("cast", lambda x: x.astype(target), self)

    def cast(self, dt) -> "Tensor":
        return self.astype(dt)

    # -- in-place machinery (dygraph mutation over immutable arrays) -------
    def _inplace_set(self, new_value) -> "Tensor":
        """Rebind the underlying array (the dygraph ``x.add_(y)`` discipline).

        In-place ops on tensors that participate in an active autograd graph
        would corrupt saved VJP residuals, mirroring the reference's inplace
        version-counter check — so we forbid them on non-leaf tensors.

        Static-graph hook: assigning a *symbolic* value (a recorded op's
        output) onto an eager tensor — BN running-stat updates etc. — keeps
        the eager value and schedules a replay-time write-back instead.
        """
        from ..static.graph import _SymbolicValue, register_state_write

        if isinstance(new_value, _SymbolicValue):
            register_state_write(self, new_value)
            return self
        if self._grad_node is not None:
            raise InvalidArgumentError(
                f"In-place update on non-leaf tensor {self.name} would "
                "invalidate its autograd graph."
            )
        self._value = new_value
        return self

    def copy_(self, other: "Tensor") -> "Tensor":
        val = other._value if isinstance(other, Tensor) else jnp.asarray(other)
        return self._inplace_set(val.astype(self._value.dtype))

    def set_value(self, value) -> "Tensor":
        val = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        return self._inplace_set(val.astype(self._value.dtype))

    def zero_(self) -> "Tensor":
        return self._inplace_set(jnp.zeros_like(self._value))

    def fill_(self, v) -> "Tensor":
        return self._inplace_set(jnp.full_like(self._value, v))

    def scale_(self, s) -> "Tensor":
        return self._inplace_set(self._value * s)

    def add_(self, other) -> "Tensor":
        o = other._value if isinstance(other, Tensor) else other
        return self._inplace_set(self._value + o)

    def subtract_(self, other) -> "Tensor":
        o = other._value if isinstance(other, Tensor) else other
        return self._inplace_set(self._value - o)

    def multiply_(self, other) -> "Tensor":
        o = other._value if isinstance(other, Tensor) else other
        return self._inplace_set(self._value * o)

    def clip_(self, min=None, max=None) -> "Tensor":
        return self._inplace_set(jnp.clip(self._value, min, max))

    def exp_(self) -> "Tensor":
        return self._inplace_set(jnp.exp(self._value))

    def sqrt_(self) -> "Tensor":
        return self._inplace_set(jnp.sqrt(self._value))

    def floor_(self) -> "Tensor":
        return self._inplace_set(jnp.floor(self._value))

    def ceil_(self) -> "Tensor":
        return self._inplace_set(jnp.ceil(self._value))

    def round_(self) -> "Tensor":
        return self._inplace_set(jnp.round(self._value))

    def reciprocal_(self) -> "Tensor":
        return self._inplace_set(1.0 / self._value)

    def tanh_(self) -> "Tensor":
        return self._inplace_set(jnp.tanh(self._value))

    def scatter_(self, index, updates, overwrite=True) -> "Tensor":
        iv = index._value if isinstance(index, Tensor) else jnp.asarray(index)
        iv = iv.reshape(-1)  # paddle accepts (N,) or (N,1) row indices
        uv = (updates._value if isinstance(updates, Tensor)
              else jnp.asarray(updates))
        if overwrite:
            return self._inplace_set(self._value.at[iv].set(uv))
        return self._inplace_set(self._value.at[iv].add(uv))

    def flatten_(self, start_axis=0, stop_axis=-1) -> "Tensor":
        from ..ops.manipulation import flatten as _flatten

        # reuse the ops kernel's axis normalization/validation (0-d, ranges)
        flat = _flatten(Tensor(self._value, stop_gradient=True),
                        start_axis, stop_axis)
        return self._inplace_set(flat._value)

    def squeeze_(self, axis=None) -> "Tensor":
        return self._inplace_set(jnp.squeeze(
            self._value, axis=tuple(axis) if isinstance(axis, (list, tuple))
            else axis))

    def unsqueeze_(self, axis) -> "Tensor":
        return self._inplace_set(jnp.expand_dims(self._value, axis))

    def reshape_(self, shape) -> "Tensor":
        return self._inplace_set(self._value.reshape(tuple(shape)))

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        from ..ops.dispatch import run_op

        idx = _unwrap_index(idx)
        return run_op("slice", lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        v = value._value if isinstance(value, Tensor) else value
        self._inplace_set(self._value.at[idx].set(v))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # Arithmetic dunders are attached by paddle_tpu.ops._tensor_methods at
    # import time (single source: the op registry), keeping this class free of
    # per-op code — the ``_C_ops`` fast-path discipline.


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(i._value if isinstance(i, Tensor) else i for i in idx)
    return idx


def to_tensor(
    data: Any,
    dtype: Optional[Any] = None,
    place: Optional[Union[str, Place]] = None,
    stop_gradient: bool = True,
) -> Tensor:
    """``paddle.to_tensor`` analog."""
    from .place import _parse_device

    if isinstance(data, Tensor):
        val = data._value
    elif isinstance(data, (jax.Array,)):
        val = data
    else:
        val = np.asarray(data)
        # paddle defaults python floats to fp32, ints to int64; jax x64 is off
        # so int64 becomes int32 — acceptable TPU-native default.
        if val.dtype == np.float64 and dtype is None:
            val = val.astype(np.float32)
    dt = convert_dtype(dtype) if dtype is not None else None
    if place is None:
        dev = device_for_place(expected_place())
    else:
        dev = device_for_place(place if isinstance(place, Place) else _parse_device(place))
    if isinstance(val, jax.Array) and not isinstance(val, jax.core.Tracer):
        if place is None and getattr(val.sharding, "num_devices", 1) > 1:
            # a mesh-sharded array (GSPMD path: dist.shard_tensor /
            # sharded-input pipelines) keeps its NamedSharding — re-placing
            # it on the single default device would silently de-shard it;
            # an EXPLICIT place still wins
            arr = val.astype(dt) if dt is not None else val
        else:
            arr = jax.device_put(val.astype(dt) if dt is not None else val,
                                 dev)
    elif isinstance(val, jax.core.Tracer):
        arr = val.astype(dt) if dt is not None else val
    else:
        if np.issubdtype(np.asarray(val).dtype, np.complexfloating) and (
                dt is None or jnp.issubdtype(dt, jnp.complexfloating)):
            # complex device transfer is unsupported on some transports
            # (axon TPU tunnel — failures surface lazily, so no try/except):
            # keep complex tensors host-resident, like the fft op family
            # (see fft._host)
            if getattr(dev, "platform", "cpu") != "cpu":
                dev = jax.devices("cpu")[0]
            # device_put straight from numpy: jnp.asarray would eagerly
            # materialise on the (accelerator) default device first
            arr = jax.device_put(np.asarray(val), dev)
            if dt is not None:
                arr = arr.astype(dt)
        else:
            arr = jax.device_put(jnp.asarray(val, dtype=dt), dev)
    return Tensor(arr, stop_gradient=stop_gradient)

"""Fleet elastic training manager.

Reference counterpart: ``python/paddle/distributed/fleet/elastic/manager.py``
(SURVEY.md §2.2 "Elastic", §5.3): nodes register in ETCD with TTL
heartbeats; a watcher detects scale-in/out or dead nodes; all ranks exit and
the launcher re-rendezvouses with the surviving set.

TPU-native design: membership rides the native C++ ``TCPStore`` (the same
rendezvous plane as collective bootstrap) instead of ETCD — each node
heartbeats ``elastic/node/<id>`` with a timestamp; staleness > ``ttl`` means
dead. The launcher integration point is ``ElasticManager.watch()`` which
returns a scale event; the launcher then tears the pod down and restarts
training from the last checkpoint (``launch --elastic_level 1``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...store import TCPStore

__all__ = ["ElasticManager", "ElasticStatus", "ScaleEvent"]


class ElasticStatus:
    NORMAL = "normal"
    SCALE_IN = "scale_in"   # a node died / left
    SCALE_OUT = "scale_out"  # a new node joined
    EXIT = "exit"


@dataclass
class ScaleEvent:
    status: str
    alive: List[str] = field(default_factory=list)
    joined: List[str] = field(default_factory=list)
    dead: List[str] = field(default_factory=list)


class ElasticManager:
    """One instance per node. ``start()`` begins heartbeating; ``watch()``
    polls membership and reports changes against the last-known set."""

    def __init__(self, node_id: str, store: Optional[TCPStore] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, ttl: float = 3.0,
                 heartbeat_interval: float = 0.5):
        self.node_id = node_id
        self.ttl = ttl
        self.interval = heartbeat_interval
        self.store = store or TCPStore(host=host, port=port,
                                       is_master=is_master)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._known: Optional[set] = None

    # --- registration / heartbeat ----------------------------------------
    def start(self) -> None:
        self._register()
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def _register(self) -> None:
        # atomic membership: claim a slot index via the store's atomic add,
        # then write this node's id into the slot — no read-modify-write race
        # when several nodes register at once
        slot = self.store.add("elastic/nslots", 1) - 1
        self.store.set(f"elastic/slot/{slot}", self.node_id)
        self._heartbeat()

    def _roster(self) -> List[str]:
        n = self.store.add("elastic/nslots", 0)
        out = []
        for i in range(int(n)):
            try:
                nid = self.store.get(f"elastic/slot/{i}",
                                     timeout_ms=200).decode()
            except (TimeoutError, RuntimeError):
                continue
            if nid and nid not in out:  # "" = tombstone (graceful leave)
                out.append(nid)
        return out

    def _heartbeat(self) -> None:
        self.store.set(f"elastic/node/{self.node_id}", str(time.time()))

    def _beat(self) -> None:
        while not self._stop.is_set():
            self._heartbeat()
            self._stop.wait(self.interval)

    # --- watching ---------------------------------------------------------
    def alive_nodes(self) -> Dict[str, float]:
        """node_id -> seconds since last heartbeat, for live nodes."""
        now = time.time()
        out = {}
        for nid in self._roster():
            try:
                ts = float(self.store.get(f"elastic/node/{nid}",
                                          timeout_ms=200).decode())
            except (TimeoutError, RuntimeError, ValueError):
                continue
            age = now - ts
            if age <= self.ttl:
                out[nid] = age
        return out

    def watch(self) -> ScaleEvent:
        """Compare current membership to the previously observed set."""
        alive = set(self.alive_nodes())
        if self._known is None:
            self._known = alive
            return ScaleEvent(ElasticStatus.NORMAL, alive=sorted(alive))
        joined = alive - self._known
        dead = self._known - alive
        self._known = alive
        if dead:
            return ScaleEvent(ElasticStatus.SCALE_IN, alive=sorted(alive),
                              dead=sorted(dead))
        if joined:
            return ScaleEvent(ElasticStatus.SCALE_OUT, alive=sorted(alive),
                              joined=sorted(joined))
        return ScaleEvent(ElasticStatus.NORMAL, alive=sorted(alive))

    def leave(self) -> None:
        """Graceful departure: stop heartbeating and tombstone our slot."""
        self.stop()
        n = self.store.add("elastic/nslots", 0)
        for i in range(int(n)):
            try:
                nid = self.store.get(f"elastic/slot/{i}",
                                     timeout_ms=200).decode()
            except (TimeoutError, RuntimeError):
                continue
            if nid == self.node_id:
                self.store.set(f"elastic/slot/{i}", "")
        self.store.delete_key(f"elastic/node/{self.node_id}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

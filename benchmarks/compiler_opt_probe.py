"""Probe TPU compiler options on the headline step via compile-time
compiler_options (the tunneled client rejects XLA_FLAGS, but per-compile
options reach the remote compiler). Usage: python compiler_opt_probe.py
[key=value ...] — no args = baseline."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    opts = {}
    for a in sys.argv[1:]:
        k, v = a.split("=", 1)
        opts[k] = v
    from paddle_tpu.models import llama
    from paddle_tpu.parallel import create_hybrid_mesh, set_mesh

    batch, seq = 48, 512
    cfg = llama.LlamaConfig.bert_base_equiv(max_seq_len=seq)
    mesh = create_hybrid_mesh(devices=jax.devices()[:1])
    params = llama.init_params(cfg)
    opt_state = llama.init_opt_state(params)
    rng = np.random.RandomState(0)
    tokens = jnp.array(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    step = llama.make_sharded_train_step(cfg, mesh, lr=1e-4)
    lowered = step.lower(params, opt_state, tokens, tokens)
    try:
        compiled = lowered.compile(compiler_options=opts or None)
    except Exception as e:
        print(f"[{opts}] compile REJECTED: {type(e).__name__}: {str(e)[:300]}",
              flush=True)
        set_mesh(None)
        return
    params, opt_state, loss = compiled(params, opt_state, tokens, tokens)
    float(loss)
    params, opt_state, loss = compiled(params, opt_state, tokens, tokens)
    float(loss)
    iters, best = 20, None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = compiled(params, opt_state, tokens,
                                               tokens)
        float(loss)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    tps = iters * batch * seq / best
    print(f"[{opts}] {tps:,.0f} tok/s, step {best/iters*1e3:.1f} ms",
          flush=True)
    set_mesh(None)


if __name__ == "__main__":
    main()

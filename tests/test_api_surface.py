"""API-surface completeness tests for the audit additions: communication
stream collectives, incubate.asp, VisualDL/ReduceLROnPlateau callbacks,
Flowers dataset, paddle.text datasets + viterbi decode."""

import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, text
from paddle_tpu.distributed.communication import stream
from paddle_tpu.incubate import asp
from paddle_tpu.hapi.callbacks import ReduceLROnPlateau, VisualDL
from paddle_tpu.vision.datasets import Flowers


def test_stream_all_reduce_task():
    t = paddle.to_tensor(np.ones(4, np.float32))
    task = stream.all_reduce(t, sync_op=False)  # world=1: identity
    assert task is not None and task.wait() is True
    assert stream.all_reduce(t, sync_op=True) is None


def test_asp_prune_and_decorate():
    lin = nn.Linear(8, 8)
    masks = asp.prune_model(lin)
    assert "weight" in next(iter(masks)) or masks
    assert asp.calculate_density(lin.weight) <= 0.51
    opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.01,
                                            parameters=lin.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(
        np.float32))
    loss = paddle.mean(lin(x) ** 2)
    loss.backward()
    opt.step()
    assert asp.calculate_density(lin.weight) <= 0.51


def test_visualdl_callback(tmp_path):
    cb = VisualDL(log_dir=str(tmp_path))

    class FakeModel:
        pass

    cb.set_model(FakeModel())
    cb.on_train_batch_end(0, {"loss": 1.5})
    cb.on_train_batch_end(1, {"loss": np.float32(1.2)})
    cb.on_eval_end({"acc": 0.9})
    cb.on_train_end()
    recs = [json.loads(l) for l in
            open(os.path.join(tmp_path, "vdlrecords.jsonl"))]
    assert len(recs) == 3
    assert recs[0]["tag"] == "train/loss" and recs[0]["value"] == 1.5
    assert recs[2]["tag"] == "eval/acc"


def test_reduce_lr_on_plateau():
    lin = nn.Linear(2, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())

    class FakeModel:
        _optimizer = opt

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2, verbose=0)
    cb.set_model(FakeModel())
    cb.on_train_begin()
    for _ in range(4):
        cb.on_eval_end({"loss": 1.0})  # flat -> plateau
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_flowers_dataset():
    ds = Flowers(mode="test")
    img, label = ds[0]
    assert img.shape == (3, 96, 96)
    assert 0 <= int(np.asarray(label).reshape(-1)[0]) < 102


def test_text_datasets():
    imdb = text.Imdb(mode="train", synthetic_size=100)
    doc, lab = imdb[0]
    assert doc.dtype == np.int64 and lab in (0, 1)
    uci = text.UCIHousing(mode="test")
    x, y = uci[0]
    assert x.shape == (13,) and y.shape == (1,)
    ngram = text.Imikolov(window_size=5, synthetic_size=50)
    item = ngram[0]
    assert len(item) == 5


def test_viterbi_decode():
    # deterministic chain: transition strongly favors staying; emissions pick
    # the start state
    em = np.full((1, 4, 3), -10.0, np.float32)
    em[0, 0, 1] = 10.0  # start in state 1
    trans = np.full((3, 3), -5.0, np.float32)
    np.fill_diagonal(trans, 5.0)
    scores, paths = text.viterbi_decode(paddle.to_tensor(em),
                                        paddle.to_tensor(trans))
    assert paths.numpy().tolist() == [[1, 1, 1, 1]]

"""Capacity & memory observability — page-level HBM metering,
per-request resource attribution, and predictive exhaustion alerting
(ISSUE 13 tentpole).

The paged KV pool is the resource that actually caps "millions of
users" (SCALING §3f sized it; r12's pages-aware routing and r13's
pages-backpressure valve act on it), yet until r18 it was a black box:
``serving.pages_free`` was a point gauge, COW sharing and reclaimable
cache-held pages were invisible, and no request knew what it cost.
This module is the capacity signal plane, all under the zero-extra-sync
contract — the page allocator's bookkeeping is already host-side numpy
refcounts, so every signal below is free of device reads:

* :class:`PoolMonitor` — a per-pool observer fed by the new
  ``paged_kv.POOL_HOOKS`` broadcast (every ``PageAllocator``
  alloc/retain/release and every ``PagedPrefixCache`` retain/evict
  notifies): occupancy timeline (stride-decimated, bounded),
  high-water mark with a declared-fraction ``pool_high_water`` flight
  event (journaled through the r16 forwarding), a page-seconds
  integral (∫ pages_used dt — the allocator-log side of the meter
  identity the tests pin), and an on-demand :meth:`PoolMonitor.snapshot`
  breakdown: free / live (slot-referenced) / cache-held with the
  reclaimable subset / trash, COW sharing ratio (virtual ÷ physical
  pages, i.e. Σ refcounts ÷ pages used), per-slot residency histogram.
* **Per-request resource meter** — fields the serving stack stamps on
  ``Request`` (see ``inference/serving.py``): ``page_seconds``
  (reserve→release host stamps, accumulated across preempt/requeue
  cycles), ``meter_ticks`` (weight streams the request was live for:
  admit prefill + decode/verify ticks) and ``meter_streams`` (the FAIR
  share of those streams — N co-resident requests split one stream N
  ways, so Σ streams over a serve == total segment steps exactly).
  :func:`attribute_request` / :func:`aggregate_meters` join them with
  ``perf.serving_ledger`` bytes/FLOPs arithmetic into per-request and
  per-priority-class cost attribution — the substrate ROADMAP item 5's
  tenant classes reuse verbatim.
* :class:`CapacityMonitor` — predictive exhaustion alerting in the
  slo.py shape: fast/slow SEGMENT windows of fresh-page demand, a
  time-to-exhaustion estimate ``(free + reclaimable) / demand`` in
  segments, ok→warning→page with immediate escalation and hysteretic
  clear. The scheduler evaluates it BEFORE each segment dispatch
  (``begin_segment``), so at overload the page fires before the first
  pages-backpressure deferral — the r14 alert-leads-valve bar applied
  to memory.
* :func:`capacity_plan` — the what-if surface: SCALING §3f pages-free
  arithmetic (span pages × concurrency from Little's law) joined with
  §3g replica scaling (offered tok/s ÷ per-replica capacity) answers
  "what pool size / how many replicas for this trace", validated ±10%
  against a measured serve in SERVING_r18.json. ROADMAP item 4's
  autoscaler closes its loop over exactly this surface.

Chunked-prefill caveat (honest accounting): the host replay skips
non-final chunk steps (no token surfaced), so ``meter_streams`` does
not attribute mid-prefill chunk streams to anyone — the Σ streams ==
steps identity holds on the plain paged family only; chunked serves
undercount by the chunk steps (visible as ``serving.prefill_chunks``).
"""

from __future__ import annotations

import collections
import math
import time
from typing import Dict, List, Optional

from . import flight as _flight
from . import metrics as _metrics

__all__ = ["PoolMonitor", "CapacityMonitor", "attribute_request",
           "aggregate_meters", "capacity_plan", "install", "uninstall"]

_LEVELS = ("ok", "warning", "page")
_LEVEL_RANK = {lvl: i for i, lvl in enumerate(_LEVELS)}


# ---------------------------------------------------------------------------
# pool monitor: the allocator/cache event observer
# ---------------------------------------------------------------------------


class PoolMonitor:
    """Observe ONE paged pool through ``paged_kv.POOL_HOOKS``.

    ``pager`` is the ``PagedKVCache`` whose allocator's events this
    monitor keeps (events from other engines' allocators in the same
    process are filtered out by identity — the r12 fleet-isolation
    contract applied to observability). ``prefix_cache`` (optional, the
    pool's ``PagedPrefixCache``) enables the cache-held/reclaimable
    breakdown. ``high_water_frac`` declares the occupancy fraction
    whose first crossing emits a ``pool_high_water`` flight event
    (hysteretic re-arm ``rearm_margin`` below it, so churn at the line
    cannot storm the ring). Attach/detach explicitly (or use the
    context manager) — constructing one costs nothing."""

    def __init__(self, pager, prefix_cache=None,
                 high_water_frac: float = 0.9,
                 rearm_margin: float = 0.05,
                 timeline_cap: int = 256):
        if not 0.0 < high_water_frac <= 1.0:
            raise ValueError(f"high_water_frac must be in (0, 1], got "
                             f"{high_water_frac}")
        self.pager = pager
        self.prefix_cache = prefix_cache
        self.high_water_frac = float(high_water_frac)
        self.rearm_margin = float(rearm_margin)
        self.timeline_cap = int(timeline_cap)
        self.events = 0
        self.cache_retains = 0            # PagedPrefixCache inserts
        self.cache_releases = 0           # PagedPrefixCache evictions
        # r19 tiered KV (ISSUE 14): tier traffic observed through the
        # same POOL_HOOKS broadcast — event counts and page totals per
        # direction (stage / spill / restore / import)
        self.tier_events: Dict[str, int] = {}
        self.tier_pages: Dict[str, int] = {}
        self.high_water_pages = 0
        self.high_water_events = 0
        self._hw_armed = True
        # stride-decimated (event_no, pages_used) timeline: bounded
        # memory whatever the serve length, always covering the whole
        # run (when full, every other point drops and the stride
        # doubles — the classic streaming-decimation trick)
        self.timeline: List[tuple] = []
        self._stride = 1
        # ∫ pages_used dt over the observed event stream — the
        # allocator-log side of the page-seconds identity (with no
        # prefix cache and no forks every held page belongs to exactly
        # one request, so Σ request.page_seconds == this integral)
        self.page_seconds_integral = 0.0
        self._last_t: Optional[float] = None
        self._last_used = 0
        self._attached = False

    # --- lifecycle --------------------------------------------------------
    def attach(self) -> "PoolMonitor":
        from ..inference import paged_kv as _pk

        if not self._attached:
            _pk.POOL_HOOKS.append(self._on_event)
            self._attached = True
            # open the integral at attach so a pool that is already
            # partially occupied integrates from here, not from zero
            self._last_t = time.perf_counter()
            self._last_used = self.pager.allocator.pages_used
        return self

    def detach(self) -> None:
        from ..inference import paged_kv as _pk

        if self._attached:
            if self._on_event in _pk.POOL_HOOKS:
                _pk.POOL_HOOKS.remove(self._on_event)
            self._attached = False

    def __enter__(self) -> "PoolMonitor":
        return self.attach()

    def __exit__(self, *exc) -> None:
        self.detach()

    # --- the event intake (host ints only) --------------------------------
    def _on_event(self, event: str, n: int, alloc) -> None:
        if alloc is not self.pager.allocator:
            return
        t = time.perf_counter()
        if self._last_t is not None:
            self.page_seconds_integral += self._last_used * (t - self._last_t)
        self._last_t = t
        used = alloc.pages_used
        self._last_used = used
        self.events += 1
        if event == "cache_retain":
            self.cache_retains += 1
        elif event == "cache_release":
            self.cache_releases += 1
        elif event.startswith("tier_"):
            d = event[len("tier_"):]
            self.tier_events[d] = self.tier_events.get(d, 0) + 1
            self.tier_pages[d] = self.tier_pages.get(d, 0) + int(n)
        if used > self.high_water_pages:
            self.high_water_pages = used
            _metrics.gauge("capacity.high_water_pages").set(used)
        occ = used / max(1, alloc.num_pages - 1)
        _metrics.gauge("capacity.pages_free").set(alloc.pages_free)
        _metrics.gauge("capacity.occupancy").set(occ)
        if self._hw_armed and occ >= self.high_water_frac:
            self._hw_armed = False
            self.high_water_events += 1
            _metrics.counter("capacity.high_water_events").inc()
            _flight.record("pool_high_water",
                           occupancy=round(occ, 4), pages_used=used,
                           pages_free=alloc.pages_free,
                           frac=self.high_water_frac)
        elif not self._hw_armed \
                and occ < self.high_water_frac - self.rearm_margin:
            self._hw_armed = True
        if self.events % self._stride == 0:
            self.timeline.append((self.events, used))
            if len(self.timeline) > self.timeline_cap:
                self.timeline = self.timeline[::2]
                self._stride *= 2

    # --- on-demand breakdown (host numpy scans; pools are small) ----------
    def snapshot(self) -> dict:
        """The full pool breakdown, computed from host state at call
        time. ``pages_free + live_only + shared + reclaimable`` tiles
        the usable pool exactly when no dispatched segment is in flight
        (mid-flight reservations are counted under ``live``: the pages
        belong to picked requests the slot mirrors haven't bound yet —
        ``reserved_unbound`` names that remainder)."""
        alloc = self.pager.allocator
        used = alloc.pages_used
        slot_set = {p for pages in self.pager.slot_pages for p in pages}
        cache_set = set()
        if self.prefix_cache is not None:
            cache_set = {p for ent in self.prefix_cache._entries.values()
                         for p in ent.pages}
        reclaimable = len(cache_set - slot_set)
        virtual = int(alloc._ref.sum())
        residency = collections.Counter(
            len(pages) for pages in self.pager.slot_pages if pages)
        return {
            "num_pages": alloc.num_pages - 1,        # usable (sans trash)
            "page_size": self.pager.page_size,
            "pages_free": alloc.pages_free,
            "pages_used": used,
            "live_pages": len(slot_set),
            "cache_held_pages": len(cache_set),
            "reclaimable_pages": reclaimable,
            "reserved_unbound_pages": used - len(slot_set | cache_set),
            "trash_pages": 1,
            "occupancy": round(used / max(1, alloc.num_pages - 1), 4),
            "high_water_pages": self.high_water_pages,
            "high_water_occupancy": round(
                self.high_water_pages / max(1, alloc.num_pages - 1), 4),
            "high_water_events": self.high_water_events,
            "cow_virtual_pages": virtual,
            "cow_ratio": round(virtual / used, 4) if used else 1.0,
            "slot_residency": {str(k): v
                               for k, v in sorted(residency.items())},
            "events": self.events,
            "cache_retains": self.cache_retains,
            "cache_releases": self.cache_releases,
            "page_seconds_integral": round(self.page_seconds_integral, 6),
            "timeline_stride": self._stride,
            "timeline": list(self.timeline),
            **self._tier_section(),
        }

    def _tier_section(self) -> dict:
        """The r19 tier breakdown, when the attached cache has a host
        tier: host-resident pages + observed transfer traffic (empty
        dict otherwise, so the r18 snapshot shape is unchanged)."""
        tier = (getattr(self.prefix_cache, "host_tier", None)
                if self.prefix_cache is not None else None)
        if tier is None and not self.tier_events:
            return {}
        out = {"events": dict(self.tier_events),
               "pages": dict(self.tier_pages)}
        if tier is not None:
            out.update(tier.stats())
            out["spillable_pages"] = self.prefix_cache.spillable_pages()
        return {"tiers": out}

    def reclaimable(self) -> int:
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.reclaimable_pages()


# ---------------------------------------------------------------------------
# per-request resource attribution (the meter join)
# ---------------------------------------------------------------------------


def attribute_request(req, ledger: Optional[dict] = None,
                      page_size: Optional[int] = None) -> dict:
    """One request's resource bill from its meter fields, joined with
    the analytic ledger when given (``perf.serving_ledger``): HBM bytes
    streamed = fair-share weight streams × bytes/stream + the KV rows
    this request's own ticks read (ledger ``avg_pos`` arithmetic),
    prefill FLOPs from the prompt span. Host arithmetic only."""
    out = {
        "rid": req.rid,
        "priority": req.priority,
        "prompt_tokens": int(len(req.prompt)),
        "gen_tokens": len(req.tokens),
        "pages_reserved": req.pages_reserved,
        "page_seconds": round(req.page_seconds, 6),
        "ticks": req.meter_ticks,
        "streams": round(req.meter_streams, 4),
        "spec_effective_tok_per_tick": (
            round(len(req.tokens) / req.meter_ticks, 4)
            if req.meter_ticks else None),
    }
    if page_size:
        out["page_tokens_reserved"] = req.pages_reserved * int(page_size)
    if ledger is not None:
        wb = ledger["weight_bytes_per_tick"]
        # per-slot KV bytes/tick at the ledger's avg_pos (the §3c term,
        # divided back to one slot since kv_bytes is batch-scaled)
        kv_slot = ledger["kv_bytes_per_tick"] / max(1, ledger["batch"])
        out["hbm_bytes"] = int(req.meter_streams * wb
                               + req.meter_ticks * kv_slot)
        out["prefill_flops"] = int(ledger["flops_per_token"]
                                   * len(req.prompt))
    return out


def aggregate_meters(reqs, ledger: Optional[dict] = None,
                     page_size: Optional[int] = None) -> dict:
    """Per-priority-class aggregation of the request meters — the
    ``OnlineReport.meter`` section (and the accounting substrate
    ROADMAP item 5's tenant classes will bill against)."""
    classes: Dict[int, dict] = {}
    totals = {"n": 0, "page_seconds": 0.0, "ticks": 0, "streams": 0.0,
              "hbm_bytes": 0, "prefill_flops": 0}
    for r in reqs:
        a = attribute_request(r, ledger=ledger, page_size=page_size)
        c = classes.setdefault(r.priority, {
            "n": 0, "page_seconds": 0.0, "ticks": 0, "streams": 0.0,
            "hbm_bytes": 0, "prefill_flops": 0})
        for agg in (c, totals):
            agg["n"] += 1
            agg["page_seconds"] += a["page_seconds"]
            agg["ticks"] += a["ticks"]
            agg["streams"] += a["streams"]
            agg["hbm_bytes"] += a.get("hbm_bytes", 0)
            agg["prefill_flops"] += a.get("prefill_flops", 0)
    for agg in list(classes.values()) + [totals]:
        agg["page_seconds"] = round(agg["page_seconds"], 6)
        agg["streams"] = round(agg["streams"], 4)
    return {"per_class": {str(p): c for p, c in sorted(classes.items())},
            "total": totals,
            "ledger_joined": ledger is not None}


# ---------------------------------------------------------------------------
# predictive exhaustion alerting
# ---------------------------------------------------------------------------


class CapacityMonitor:
    """Time-to-exhaustion alerting over the page pool, in slo.py's
    shape: segment-counted windows, ok→warning→page with immediate
    escalation and hysteretic clear.

    Intake (all host ints, fed from state the serve loop already
    holds):

    * :meth:`note_admission` — fresh pages reserved (shared prefix
      pages excluded: they consume no free pages);
    * :meth:`observe_pool` — the current ``(pages_free, reclaimable)``;
    * :meth:`begin_segment` — evaluate the alert rules against the
      CURRENT availability and the demand EWMFs of CLOSED buckets.
      The scheduler calls this before each dispatch, which is what
      makes the page LEAD the first pages-backpressure deferral;
    * :meth:`close_segment` — push the open demand bucket into the
      windows.

    Time-to-exhaustion = (free + reclaimable) / demand, in SEGMENTS:
    ``demand_fast`` is the mean fresh-page demand over the newest
    ``fast_window`` buckets, ``demand_slow`` over ``slow_window`` —
    page fires only when BOTH estimates fall under ``page_horizon``
    (the fast window gives reaction time, the slow one suppresses a
    one-segment burst), warning likewise under ``warn_horizon``.
    ``ledger`` (optional ``perf.serving_ledger``) rides into
    :func:`aggregate_meters` for the byte/FLOP join of the report's
    meter section."""

    def __init__(self, fast_window: int = 2, slow_window: int = 8,
                 warn_horizon: float = 16.0, page_horizon: float = 6.0,
                 clear_after: int = 4, ledger: Optional[dict] = None):
        if not 0 < fast_window <= slow_window:
            raise ValueError(f"need 0 < fast_window <= slow_window, got "
                             f"{fast_window}/{slow_window}")
        if not 0 < page_horizon <= warn_horizon:
            raise ValueError(f"need 0 < page_horizon <= warn_horizon, "
                             f"got {page_horizon}/{warn_horizon}")
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        self.warn_horizon = float(warn_horizon)
        self.page_horizon = float(page_horizon)
        self.clear_after = int(clear_after)
        self.ledger = ledger
        self._reset_state()

    def _reset_state(self) -> None:
        self.segment_no = 0
        self.level = "ok"
        self.clear_streak = 0
        self.alert_log: List[dict] = []
        self._window = collections.deque(maxlen=self.slow_window)
        self._cur_pages = 0
        self._cur_admits = 0
        self.pages_admitted_total = 0
        self.admitted_total = 0
        self.pool_events = 0
        self._free = 0
        self._reclaimable = 0
        # r19 (ISSUE 14): the tier dimension of the availability term —
        # host-resident staged pages (None until a tiered feed reports)
        self._host_pages: Optional[int] = None
        self.tte_fast = math.inf
        self.tte_slow = math.inf
        self.demand_fast = 0.0
        self.demand_slow = 0.0

    # --- intake -----------------------------------------------------------
    def note_admission(self, pages: int, admitted: int = 1) -> None:
        self._cur_pages += int(pages)
        self._cur_admits += int(admitted)
        self.pages_admitted_total += int(pages)
        self.admitted_total += int(admitted)
        self.pool_events += 1

    def observe_pool(self, pages_free: int, reclaimable: int = 0,
                     host_pages: Optional[int] = None) -> None:
        self._free = int(pages_free)
        self._reclaimable = int(reclaimable)
        if host_pages is not None:
            self._host_pages = int(host_pages)
        self.pool_events += 1

    # --- evaluation -------------------------------------------------------
    def _demand(self, n: int) -> float:
        buckets = list(self._window)[-n:]
        return sum(buckets) / len(buckets) if buckets else 0.0

    def begin_segment(self, pages_free: Optional[int] = None,
                      reclaimable: Optional[int] = None,
                      host_pages: Optional[int] = None) -> str:
        """Run the alert rules against the CURRENT availability —
        call before dispatching the segment. Returns the level.

        r19 tier dimension: the HBM time-to-exhaustion keeps its r18
        meaning (free + reclaimable — with a spill tier, 'reclaimable'
        pages demote instead of dying, so the term is unchanged while
        its COST changed); ``host_pages`` rides the report/gauge as the
        second availability axis the autoscaler and the /capacity
        scrape read."""
        if pages_free is not None:
            self._free = int(pages_free)
        if reclaimable is not None:
            self._reclaimable = int(reclaimable)
        if host_pages is not None:
            self._host_pages = int(host_pages)
            _metrics.gauge("capacity.host_pages").set(self._host_pages)
        avail = self._free + self._reclaimable
        self.demand_fast = self._demand(self.fast_window)
        self.demand_slow = self._demand(self.slow_window)
        self.tte_fast = (avail / self.demand_fast
                         if self.demand_fast > 0 else math.inf)
        self.tte_slow = (avail / self.demand_slow
                         if self.demand_slow > 0 else math.inf)
        _metrics.gauge("capacity.tte_fast_segments").set(
            min(self.tte_fast, 1e9))
        _metrics.gauge("capacity.tte_slow_segments").set(
            min(self.tte_slow, 1e9))
        _metrics.gauge("capacity.avail_pages").set(avail)
        if (self.tte_fast <= self.page_horizon
                and self.tte_slow <= self.page_horizon):
            target = "page"
        elif (self.tte_fast <= self.warn_horizon
                and self.tte_slow <= self.warn_horizon):
            target = "warning"
        else:
            target = "ok"
        if _LEVEL_RANK[target] > _LEVEL_RANK[self.level]:
            self._transition(target)          # escalate immediately
            self.clear_streak = 0
        elif _LEVEL_RANK[target] < _LEVEL_RANK[self.level]:
            self.clear_streak += 1            # hysteretic clear
            if self.clear_streak >= self.clear_after:
                self._transition(target)
                self.clear_streak = 0
        else:
            self.clear_streak = 0
        return self.level

    def close_segment(self) -> None:
        """Close the open demand bucket (call once per segment, after
        the fetch distributed its admissions)."""
        self.segment_no += 1
        self._window.append(self._cur_pages)
        self._cur_pages = 0
        self._cur_admits = 0

    def note_segment(self, admitted: int, pages: int,
                     pages_free: Optional[int] = None,
                     reclaimable: Optional[int] = None) -> None:
        """Convenience one-shot: note + observe + close (for callers
        without a pre-dispatch hook; the alert then trails by one
        segment — the scheduler uses the split calls instead)."""
        self.note_admission(pages, admitted)
        if pages_free is not None:
            self.observe_pool(pages_free, reclaimable or 0)
        self.close_segment()

    def _transition(self, level: str) -> None:
        prev, self.level = self.level, level
        rec = {"segment": self.segment_no, "level": level, "prev": prev,
               "tte_fast": (round(self.tte_fast, 3)
                            if math.isfinite(self.tte_fast) else None),
               "tte_slow": (round(self.tte_slow, 3)
                            if math.isfinite(self.tte_slow) else None),
               "avail_pages": self._free + self._reclaimable,
               "demand_fast": round(self.demand_fast, 3)}
        self.alert_log.append(rec)
        if _LEVEL_RANK[level] > _LEVEL_RANK[prev]:
            _metrics.counter("capacity.alerts").inc()
            _metrics.counter(f"capacity.alerts[{level}]").inc()
        _flight.record("capacity_alert", **rec)

    # --- introspection ----------------------------------------------------
    def report(self) -> dict:
        """The ``/capacity`` endpoint's monitor section."""
        return {
            "segments": self.segment_no,
            "level": self.level,
            "windows": {"fast": self.fast_window,
                        "slow": self.slow_window},
            "horizons": {"warn": self.warn_horizon,
                         "page": self.page_horizon,
                         "clear_after": self.clear_after,
                         "unit": "segments"},
            "avail_pages": self._free + self._reclaimable,
            "pages_free": self._free,
            "reclaimable_pages": self._reclaimable,
            # r19 (ISSUE 14): the per-tier availability view — host
            # pages are reclaimable AT RESTORE COST, so they report as
            # their own axis instead of inflating the HBM horizon
            "avail_by_tier": {
                "hbm": self._free + self._reclaimable,
                "host": self._host_pages,
            },
            "demand_fast": round(self.demand_fast, 3),
            "demand_slow": round(self.demand_slow, 3),
            "tte_fast_segments": (round(self.tte_fast, 3)
                                  if math.isfinite(self.tte_fast)
                                  else None),
            "tte_slow_segments": (round(self.tte_slow, 3)
                                  if math.isfinite(self.tte_slow)
                                  else None),
            "pages_admitted_total": self.pages_admitted_total,
            "admitted_total": self.admitted_total,
            "alerts": list(self.alert_log),
        }

    def reset(self) -> None:
        """Zero windows/alert state (warm-run isolation)."""
        self._reset_state()

    # r25 (ISSUE 20): with an autoscaler attached the monitor becomes a
    # DECIDER (``capacity_alert`` is a scale-up input), so its config
    # rides the journal header and replay rebuilds it from this.
    def describe(self) -> dict:
        """Rebuildable config snapshot for the journal header."""
        return {"fast_window": self.fast_window,
                "slow_window": self.slow_window,
                "warn_horizon": self.warn_horizon,
                "page_horizon": self.page_horizon,
                "clear_after": self.clear_after}

    @classmethod
    def from_description(cls, d: dict) -> "CapacityMonitor":
        return cls(**d)


# ---------------------------------------------------------------------------
# capacity planner: §3f pages-free arithmetic × §3g replica scaling
# ---------------------------------------------------------------------------


def capacity_plan(trace_stats: dict, ledger: Optional[dict] = None, *,
                  page_size: int, slots: int,
                  measured: Optional[dict] = None,
                  headroom: float = 0.0,
                  cfg=None, params=None, quant: Optional[str] = None,
                  hbm_bytes: Optional[int] = None,
                  mesh_devices: int = 1,
                  transient_bytes: Optional[int] = None) -> dict:
    """Answer "what pool size / how many replicas for this trace".

    ``trace_stats``: ``mean_prompt_tokens``, ``mean_new_tokens``, and
    either ``rate_req_s`` (Little's-law concurrency ``λ·W``) or
    ``concurrency`` directly (``None`` rate ⇒ saturated: concurrency =
    ``slots``). ``mean_service_s`` (a measured ``W``) sharpens the
    concurrency estimate; without it ``W ≈ (G+1) · per_tick_s`` (each
    live slot retires one token per tick).

    ``measured``: ``per_tick_s`` (measured seconds/segment-step) and
    ``slot_occupancy`` (useful slot-ticks fraction) from a probe serve;
    without them the §3c analytic ``tick_floor_s`` from ``ledger``
    prices the ticks (the chip-ceiling what-if).

    The two SCALING joins:

    * **§3f pool arithmetic** — a request spans exactly
      ``ceil((S+G−1)/p)`` pages (generation length fixed at
      admission), so pool high-water ≈ concurrency × span and the
      recommended pool adds ``headroom`` plus the trash page;
    * **§3g replica scaling** — offered tok/s = λ·E[G] against one
      replica's capacity ``occupancy × slots / per_tick_s`` gives the
      replica count at ``headroom`` utilisation margin.

    r24: pass ``cfg`` (+ ``params``/``quant``) and ``hbm_bytes`` and
    the plan gains a ``chip_fit`` section — the §3s static HBM
    envelope (weights + recommended pool + peak transient, via
    ``analysis.memory.chip_fit``) priced for the recommended
    ``pool_pages``, answering will-this-replica-fit BEFORE a pool is
    ever allocated. ``transient_bytes`` overrides the analytic
    estimate with a measured liveness peak.
    """
    S = float(trace_stats["mean_prompt_tokens"])
    G = float(trace_stats["mean_new_tokens"])
    rate = trace_stats.get("rate_req_s")
    span_pages = max(1, -(-int(math.ceil(S + G - 1)) // int(page_size)))
    meas = measured or {}
    per_tick_s = meas.get("per_tick_s")
    if per_tick_s is None and ledger is not None:
        per_tick_s = ledger["tick_floor_s"]
    occupancy = float(meas.get("slot_occupancy", 1.0))
    tok_s_replica = (occupancy * slots / per_tick_s
                     if per_tick_s else None)
    service_s = trace_stats.get("mean_service_s")
    if service_s is None and per_tick_s is not None:
        service_s = (G + 1.0) * per_tick_s
    if "concurrency" in trace_stats:
        concurrency = float(trace_stats["concurrency"])
    elif rate is None:
        concurrency = float(slots)            # saturated: slots bind
    else:
        concurrency = min(float(slots), float(rate) * (service_s or 0.0))
    high_water_pages = int(math.ceil(concurrency * span_pages))
    pool_pages = int(math.ceil(high_water_pages * (1.0 + headroom))) + 1
    offered_tok_s = (float(rate) * G if rate is not None
                     else tok_s_replica)
    replicas = 1
    if offered_tok_s is not None and tok_s_replica:
        replicas = max(1, int(math.ceil(
            offered_tok_s / (tok_s_replica * (1.0 - headroom)))))
    predicted_tok_s = (min(offered_tok_s, replicas * tok_s_replica)
                       if offered_tok_s is not None and tok_s_replica
                       else tok_s_replica)
    chip_fit = None
    if hbm_bytes is not None and cfg is not None:
        from ..analysis import memory as _memory

        chip_fit = _memory.chip_fit(
            cfg, params, page_size=int(page_size), num_pages=pool_pages,
            quant=quant, mesh_devices=mesh_devices, hbm_bytes=hbm_bytes,
            transient_bytes=transient_bytes, n_pad=int(slots),
            s_max=int(math.ceil(S + G)), live_pages=high_water_pages)
    return {
        "arithmetic": "SCALING §3f pages-free x §3g replica scaling",
        "span_pages": span_pages,
        "span_rows": int(math.ceil(S + G - 1)),
        "page_size": int(page_size),
        "slots": int(slots),
        "service_s": (round(service_s, 4)
                      if service_s is not None else None),
        "concurrency": round(concurrency, 3),
        "predicted_high_water_pages": high_water_pages,
        "pool_pages": pool_pages,            # recommended (headroom+trash)
        "headroom": headroom,
        "tok_s_replica": (round(tok_s_replica, 2)
                          if tok_s_replica else None),
        "offered_tok_s": (round(offered_tok_s, 2)
                          if offered_tok_s is not None else None),
        "replicas": replicas,
        "predicted_tok_s": (round(predicted_tok_s, 2)
                            if predicted_tok_s is not None else None),
        "chip_fit": chip_fit,
    }


# ---------------------------------------------------------------------------
# Ambient attachment (the gate's --capacity mode): every allocator event
# and every engine segment feed the monitor through POOL_HOOKS /
# SEGMENT_HOOKS — no scheduler, no engine reference, host ints only.
# The attachment proves hazard-neutrality (budgets bit-identical
# --capacity on|off); the schedulers provide the pool-aware feed.
# ---------------------------------------------------------------------------

_INSTALLED: List[tuple] = []


def install(monitor: CapacityMonitor) -> None:
    from ..inference import paged_kv as _pk
    from ..inference import serving as _serving

    for m, _, _ in _INSTALLED:
        if m is monitor:
            return

    def pool_hook(event: str, n: int, alloc) -> None:
        if event == "alloc":
            monitor.note_admission(n, admitted=0)
        monitor.observe_pool(alloc.pages_free)

    def seg_hook(steps: int, new_tokens: int, finished: int) -> None:
        monitor.begin_segment()
        monitor.close_segment()

    _pk.POOL_HOOKS.append(pool_hook)
    _serving.SEGMENT_HOOKS.append(seg_hook)
    _INSTALLED.append((monitor, pool_hook, seg_hook))


def uninstall(monitor: Optional[CapacityMonitor] = None) -> None:
    from ..inference import paged_kv as _pk
    from ..inference import serving as _serving

    keep = []
    for m, ph, sh in _INSTALLED:
        if monitor is None or m is monitor:
            if ph in _pk.POOL_HOOKS:
                _pk.POOL_HOOKS.remove(ph)
            if sh in _serving.SEGMENT_HOOKS:
                _serving.SEGMENT_HOOKS.remove(sh)
        else:
            keep.append((m, ph, sh))
    _INSTALLED[:] = keep

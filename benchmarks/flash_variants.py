"""Experimental packed flash-attention kernel variants, timed on the chip.

Variants over the production packed kernels (ops/pallas/flash_attention.py):

* ``nsplit`` — intra-kernel causal row-blocking: the [S, S] elementwise
  chain (exp2 / mask / ds) runs only on each row-block's causal column
  extent (trapezoid), skipping the strictly-upper region entirely. Unlike
  the r3 split-causal experiment this splits INSIDE one kernel (no extra
  pallas launches). nsplit=1 reproduces the production kernel.
* ``exp_bf16`` — run the exp2 recompute on a bf16 argument (half the
  transcendental width; p is cast to bf16 for the matmuls anyway).

Winner gets ported into flash_attention.py with parity tests.

Usage: python benchmarks/flash_variants.py [b S h d]
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from flash_micro import timeit  # slope-timed on-device loop

_LOG2_E = float(np.log2(np.e))


def _iota_ge(rows, cols, row0):
    qp = row0 + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
    kp = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    return qp >= kp


def make_fwd(S, d, hp, is_causal, nsplit=1, exp_bf16=False):
    R = S // nsplit  # row-block height

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
        masks = None
        if is_causal:
            # per row-block causal mask over that block's column extent
            # (hoisted: shared by all heads in the cell)
            masks = [_iota_ge(R, R * (r + 1), r * R) for r in range(nsplit)]
        for i in range(hp):
            sl = slice(i * d, (i + 1) * d)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            for r in range(nsplit):
                cols = R * (r + 1)
                qr = q[r * R:(r + 1) * R]
                s = jax.lax.dot_general(qr, k[:cols],
                                        (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                if is_causal:
                    s = jnp.where(masks[r], s, -jnp.inf)
                m = jnp.max(s, axis=1)
                arg = s - m[:, None]
                if exp_bf16:
                    arg = arg.astype(jnp.bfloat16)
                p = jnp.exp2(arg)
                l = jnp.sum(p.astype(jnp.float32), axis=1)
                o = jax.lax.dot_general(p.astype(v.dtype), v[:cols],
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                o_ref[0, r * R:(r + 1) * R, sl] = \
                    (o / l[:, None]).astype(o_ref.dtype)
                lse_ref[0, 0, i, r * R:(r + 1) * R] = m + jnp.log2(l)
    return kernel


def make_bwd(S, d, hp, is_causal, scale, nsplit=1, exp_bf16=False):
    R = S // nsplit
    inv_log2e = 1.0 / _LOG2_E

    def kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
               dq_ref, dk_ref, dv_ref):
        masks = None
        if is_causal:
            masks = [_iota_ge(R, R * (r + 1), r * R) for r in range(nsplit)]
        for i in range(hp):
            sl = slice(i * d, (i + 1) * d)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            o = o_ref[0, :, sl]
            lse = lse_ref[0, 0, i, :]
            delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                            axis=1)
            dk_acc = jnp.zeros((S, d), jnp.float32)
            dv_acc = jnp.zeros((S, d), jnp.float32)
            for r in range(nsplit):
                cols = R * (r + 1)
                rows = slice(r * R, (r + 1) * R)
                qr = q[rows]
                dor = do[rows]
                s = jax.lax.dot_general(qr, k[:cols],
                                        (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                arg = s - lse[rows][:, None]
                if exp_bf16:
                    arg = arg.astype(jnp.bfloat16)
                p = jnp.exp2(arg).astype(jnp.float32)
                if is_causal:
                    p = jnp.where(masks[r], p, 0.0)
                pb = p.astype(dor.dtype)
                dv_c = jax.lax.dot_general(pb, dor, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
                dp = jax.lax.dot_general(dor, v[:cols],
                                         (((1,), (1,)), ((), ())),
                                         preferred_element_type=jnp.float32)
                ds = (p * (dp - delta[rows][:, None])).astype(q.dtype)
                dq = jax.lax.dot_general(ds, k[:cols],
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
                dq_ref[0, rows, sl] = (dq * scale).astype(dq_ref.dtype)
                dk_c = jax.lax.dot_general(ds, qr, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
                if nsplit == 1:
                    dk_acc = dk_c
                    dv_acc = dv_c
                elif cols == S:
                    dk_acc = dk_acc + dk_c
                    dv_acc = dv_acc + dv_c
                else:
                    pad = ((0, S - cols), (0, 0))
                    dk_acc = dk_acc + jnp.pad(dk_c, pad)
                    dv_acc = dv_acc + jnp.pad(dv_c, pad)
            dk_ref[0, :, sl] = (dk_acc * inv_log2e).astype(dk_ref.dtype)
            dv_ref[0, :, sl] = dv_acc.astype(dv_ref.dtype)
    return kernel


def run_fwd(q, k, v, is_causal=True, nsplit=1, exp_bf16=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, S, h, d = q.shape
    hp = 128 // d
    G = h // hp
    scale = 1.0 / np.sqrt(d)
    hd = h * d
    qf = (q * (scale * _LOG2_E)).astype(q.dtype).reshape(b, S, hd)
    kf = k.reshape(b, S, hd)
    vf = v.reshape(b, S, hd)
    blk = pl.BlockSpec((1, S, hp * d), lambda bb, g: (bb, 0, g))
    out, lse = pl.pallas_call(
        make_fwd(S, d, hp, is_causal, nsplit, exp_bf16),
        grid=(b, G),
        in_specs=[blk, blk, blk],
        out_specs=[blk, pl.BlockSpec((1, 1, hp, S),
                                     lambda bb, g: (bb, g, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, S, hd), q.dtype),
                   jax.ShapeDtypeStruct((b, G, hp, S), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(qf, kf, vf)
    return out.reshape(b, S, h, d), lse


def run_bwd(q, k, v, do, out, lse, is_causal=True, nsplit=1, exp_bf16=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, S, h, d = q.shape
    hp = 128 // d
    G = h // hp
    scale = 1.0 / np.sqrt(d)
    hd = h * d
    qf = (q * (scale * _LOG2_E)).astype(q.dtype).reshape(b, S, hd)
    kf = k.reshape(b, S, hd)
    vf = v.reshape(b, S, hd)
    dof = do.reshape(b, S, hd)
    of = out.reshape(b, S, hd)
    blk = pl.BlockSpec((1, S, hp * d), lambda bb, g: (bb, 0, g))
    lse_blk = pl.BlockSpec((1, 1, hp, S), lambda bb, g: (bb, g, 0, 0))
    dq, dk, dv = pl.pallas_call(
        make_bwd(S, d, hp, is_causal, scale, nsplit, exp_bf16),
        grid=(b, G),
        in_specs=[blk, blk, blk, blk, blk, lse_blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((b, S, hd), q.dtype),
                   jax.ShapeDtypeStruct((b, S, hd), k.dtype),
                   jax.ShapeDtypeStruct((b, S, hd), v.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(qf, kf, vf, dof, of, lse)
    r4 = lambda x: x.reshape(b, S, h, d)
    return r4(dq), r4(dk), r4(dv)


def main():
    b, S, h, d = 44, 512, 12, 64
    argv = [int(a) for a in sys.argv[1:]]
    if argv:
        b, S, h, d = argv + [b, S, h, d][len(argv):]
    from paddle_tpu.ops.pallas import flash_attention as F

    rng = np.random.RandomState(0)
    mk = lambda bb: jnp.asarray(rng.randn(bb, S, h, d), jnp.bfloat16)
    # ---- parity check on a small batch vs the production kernels
    bs = 4
    qs, ks, vs, dos = mk(bs), mk(bs), mk(bs), mk(bs)
    out0, lse0 = jax.jit(
        lambda q, k, v: F._pallas_flash_fwd_packed(q, k, v, True))(qs, ks, vs)
    g0 = jax.jit(lambda q, k, v, do, o, l:
                 F._pallas_flash_bwd_packed(q, k, v, do, o, l, True))(
        qs, ks, vs, dos, out0, lse0)
    for nsplit in (1, 2, 4):
        for ebf in (False, True):
            o1, l1 = jax.jit(functools.partial(
                run_fwd, nsplit=nsplit, exp_bf16=ebf))(qs, ks, vs)
            g1 = jax.jit(functools.partial(
                run_bwd, nsplit=nsplit, exp_bf16=ebf))(
                qs, ks, vs, dos, o1, l1)
            eo = float(jnp.max(jnp.abs(o1.astype(jnp.float32)
                                       - out0.astype(jnp.float32))))
            eg = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                           - b_.astype(jnp.float32))))
                     for a, b_ in zip(g1, g0))
            print(f"parity nsplit={nsplit} exp_bf16={ebf}: "
                  f"max|do|={eo:.2e} max|dgrad|={eg:.2e}", flush=True)

    # ---- timing at the bench shape
    q, k, v, do = mk(b), mk(b), mk(b), mk(b)
    print(f"\nshape b{b} S{S} h{h} d{d}", flush=True)
    base_f = jax.jit(lambda q, k, v: F._pallas_flash_fwd_packed(q, k, v, True))
    out, lse = base_f(q, k, v)
    timeit(base_f, (q, k, v), 30, "fwd production")
    base_b = jax.jit(lambda q, k, v, do, o, l:
                     F._pallas_flash_bwd_packed(q, k, v, do, o, l, True))
    timeit(base_b, (q, k, v, do, out, lse), 30, "bwd production")
    for nsplit in (1, 2, 4):
        for ebf in (False, True):
            f1 = jax.jit(functools.partial(run_fwd, nsplit=nsplit,
                                           exp_bf16=ebf))
            timeit(f1, (q, k, v), 30, f"fwd nsplit={nsplit} bf16exp={ebf}")
            b1 = jax.jit(functools.partial(run_bwd, nsplit=nsplit,
                                           exp_bf16=ebf))
            timeit(b1, (q, k, v, do, out, lse), 30,
                   f"bwd nsplit={nsplit} bf16exp={ebf}")


if __name__ == "__main__":
    main()


# ---------------------------------------------------------------------------
# Diagnostic ablations (WRONG numerics — timing only): drop one stage at a
# time to locate the kernel's true bottleneck.
# ---------------------------------------------------------------------------

def make_fwd_diag(S, d, hp, drop):
    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
        causal = None
        if "mask" not in drop:
            causal = _iota_ge(S, S, 0)
        for i in range(hp):
            sl = slice(i * d, (i + 1) * d)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if "mask" not in drop:
                s = jnp.where(causal, s, -jnp.inf if "exp" not in drop else 0.0)
            if "max" not in drop:
                m = jnp.max(s, axis=1)
                arg = s - m[:, None]
            else:
                m = jnp.zeros((S,), jnp.float32)
                arg = s
            p = arg if "exp" in drop else jnp.exp2(arg)
            if "sum" not in drop:
                l = jnp.sum(p, axis=1)
            else:
                l = jnp.ones((S,), jnp.float32)
            o = jax.lax.dot_general(p.astype(v.dtype), v,
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            o_ref[0, :, sl] = (o / l[:, None]).astype(o_ref.dtype)
            lse_ref[0, 0, i, :] = m + l
    return kernel


def make_bwd_diag(S, d, hp, drop):
    def kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
               dq_ref, dk_ref, dv_ref):
        causal = None
        if "mask" not in drop:
            causal = _iota_ge(S, S, 0)
        for i in range(hp):
            sl = slice(i * d, (i + 1) * d)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            o = o_ref[0, :, sl]
            lse = lse_ref[0, 0, i, :]
            if "delta" not in drop:
                delta = jnp.sum(do.astype(jnp.float32) *
                                o.astype(jnp.float32), axis=1)
            else:
                delta = jnp.zeros((S,), jnp.float32)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            arg = s - lse[:, None]
            p = arg if "exp" in drop else jnp.exp2(arg)
            if "mask" not in drop:
                p = jnp.where(causal, p, 0.0)
            pb = p.astype(do.dtype)
            dv = jax.lax.dot_general(pb, do, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            if "ds" not in drop:
                ds = (p * (dp - delta[:, None])).astype(q.dtype)
            else:
                ds = dp.astype(q.dtype)
            dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            dk = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            dq_ref[0, :, sl] = dq.astype(dq_ref.dtype)
            dk_ref[0, :, sl] = dk.astype(dk_ref.dtype)
            dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)
    return kernel


def run_diag(q, k, v, do, out, lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, S, h, d = q.shape
    hp = 128 // d
    G = h // hp
    hd = h * d
    qf = q.reshape(b, S, hd)
    kf = k.reshape(b, S, hd)
    vf = v.reshape(b, S, hd)
    dof = do.reshape(b, S, hd)
    of = out.reshape(b, S, hd)
    blk = pl.BlockSpec((1, S, hp * d), lambda bb, g: (bb, 0, g))
    lse_blk = pl.BlockSpec((1, 1, hp, S), lambda bb, g: (bb, g, 0, 0))
    for drop in ([], ["mask"], ["max"], ["sum"], ["exp"],
                 ["mask", "max", "sum", "exp"]):
        f = jax.jit(lambda qf, kf, vf, dr=tuple(drop): pl.pallas_call(
            make_fwd_diag(S, d, hp, dr),
            grid=(b, G), in_specs=[blk, blk, blk],
            out_specs=[blk, pl.BlockSpec((1, 1, hp, S),
                                         lambda bb, g: (bb, g, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((b, S, hd), q.dtype),
                       jax.ShapeDtypeStruct((b, G, hp, S), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")))(qf, kf, vf))
        timeit(f, (qf, kf, vf), 30, f"fwd diag drop={drop}")
    for drop in ([], ["mask"], ["exp"], ["delta"], ["ds"],
                 ["mask", "exp", "delta", "ds"]):
        f = jax.jit(lambda qf, kf, vf, dof, of, lse, dr=tuple(drop):
                    pl.pallas_call(
            make_bwd_diag(S, d, hp, dr),
            grid=(b, G), in_specs=[blk, blk, blk, blk, blk, lse_blk],
            out_specs=[blk, blk, blk],
            out_shape=[jax.ShapeDtypeStruct((b, S, hd), q.dtype)] * 3,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")))(
            qf, kf, vf, dof, of, lse))
        timeit(f, (qf, kf, vf, dof, of, lse), 30, f"bwd diag drop={drop}")


def diag_main():
    b, S, h, d = 44, 512, 12, 64
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, S, h, d), jnp.bfloat16)
    q, k, v, do = mk(), mk(), mk(), mk()
    from paddle_tpu.ops.pallas import flash_attention as F
    out, lse = jax.jit(
        lambda q, k, v: F._pallas_flash_fwd_packed(q, k, v, True))(q, k, v)
    run_diag(q, k, v, do, out, lse)


# ---------------------------------------------------------------------------
# Forward refinements: scale folded INSIDE the kernel (kills the XLA-level
# prescale pass), bf16 p single-materialization, q-block grid split.
# ---------------------------------------------------------------------------

def make_fwd2(S, d, hp, is_causal, qblocks=1, p_bf16=False, cst=1.0):
    from jax.experimental import pallas as pl

    R = S // qblocks

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
        r = 0 if qblocks == 1 else None
        qi = pl.program_id(2) if qblocks > 1 else 0
        causal = None
        if is_causal:
            if qblocks == 1:
                causal = _iota_ge(S, S, 0)
            else:
                qp = R * qi + jax.lax.broadcasted_iota(jnp.int32, (R, S), 0)
                kp = jax.lax.broadcasted_iota(jnp.int32, (R, S), 1)
                causal = qp >= kp
        for i in range(hp):
            sl = slice(i * d, (i + 1) * d)
            q = (q_ref[0, :, sl] * cst).astype(q_ref.dtype)
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if is_causal:
                s = jnp.where(causal, s, -jnp.inf)
            m = jnp.max(s, axis=1)
            p = jnp.exp2(s - m[:, None])
            if p_bf16:
                pb = p.astype(v.dtype)
                l = jnp.sum(pb.astype(jnp.float32), axis=1)
            else:
                l = jnp.sum(p, axis=1)
                pb = p.astype(v.dtype)
            o = jax.lax.dot_general(pb, v, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            o_ref[0, :, sl] = (o / l[:, None]).astype(o_ref.dtype)
            lse_ref[0, 0, i, :] = m + jnp.log2(l)
    return kernel


from jax.experimental import pallas as pl


def run_fwd2(q, k, v, is_causal=True, qblocks=1, p_bf16=False,
             in_kernel_scale=True):
    from jax.experimental.pallas import tpu as pltpu

    b, S, h, d = q.shape
    hp = 128 // d
    G = h // hp
    scale = 1.0 / np.sqrt(d)
    hd = h * d
    cst = scale * _LOG2_E
    if in_kernel_scale:
        qf = q.reshape(b, S, hd)
    else:
        qf = (q * cst).astype(q.dtype).reshape(b, S, hd)
        cst = 1.0
    kf = k.reshape(b, S, hd)
    vf = v.reshape(b, S, hd)
    R = S // qblocks
    if qblocks == 1:
        grid = (b, G)
        blk = pl.BlockSpec((1, S, hp * d), lambda bb, g: (bb, 0, g))
        qblk = oblk = blk
        lse_blk = pl.BlockSpec((1, 1, hp, S), lambda bb, g: (bb, g, 0, 0))
    else:
        grid = (b, G, qblocks)
        blk = pl.BlockSpec((1, S, hp * d), lambda bb, g, r: (bb, 0, g))
        qblk = oblk = pl.BlockSpec((1, R, hp * d),
                                   lambda bb, g, r: (bb, r, g))
        lse_blk = pl.BlockSpec((1, 1, hp, R),
                               lambda bb, g, r: (bb, g, 0, r))
    out, lse = pl.pallas_call(
        make_fwd2(S, d, hp, is_causal, qblocks, p_bf16, cst),
        grid=grid,
        in_specs=[qblk, blk, blk],
        out_specs=[oblk, lse_blk],
        out_shape=[jax.ShapeDtypeStruct((b, S, hd), q.dtype),
                   jax.ShapeDtypeStruct((b, G, hp, S), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",) * len(grid)),
    )(qf, kf, vf)
    return out.reshape(b, S, h, d), lse

"""Tier-transfer budget pass (r19, ISSUE 14 tentpole part c).

A memory tier is only a win while it moves LESS than it saves: a
restore that uploads more bytes than the request's own KV footprint, or
an import that copies a prefix bigger than the prefill it replaced,
would be a regression wearing a cache's clothes. This pass makes that
arithmetic enforceable, the budgets.py way:

* **per-request budget** — every request's billed tier traffic
  (``Request.tier_pages`` / ``tier_bytes``: restores + cross-replica
  imports stamped at admission) must satisfy ``tier_bytes <=
  pages_reserved x page_bytes`` (the request's own KV size — the §3n
  cost-model ceiling). ``tier_transfer_audit`` returns one violation
  string per offender.
* **conservation identities** — the tier's byte counters must agree
  with its page counters at exactly ``page_bytes`` per page (a drifted
  counter means a transfer went unmetered), and restores can never
  outnumber spills + imports (you cannot promote an entry that never
  left HBM; an entry stages once but may spill/restore many times).

The zero-extra-sync half of the tiered contract is enforced where sync
contracts live: ``SyncAudit`` over the tiered serve loop (the staging
D2H rides the per-segment event fetch, restores are dispatches), pinned
in tests/test_kv_tiers.py with allowed == segment fetches exactly.

r22 (ISSUE 17) generalizes the same arithmetic to the inter-pool
transfer: ``handoff_audit`` walks a ``DisaggRouter``'s handoff ledger
and holds EVERY individual crossing to the bytes-migrated ≤ KV-size
budget — per handoff, not just per request, because a request that
bounced (failover after handoff) may legally cross twice and each
crossing must independently fit its reserved footprint.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["tier_transfer_audit", "tier_conservation_audit",
           "tiered_serve_audit", "handoff_audit", "disagg_serve_audit",
           "HandoffAuditor"]


class HandoffAuditor:
    """Ambient handoff observer for the gate's ``--disagg on`` mode
    (r22): a flight listener that live-checks every ``handoff`` event
    against the per-crossing budget as it lands — pure observation on
    the existing flight stream, so the audited programs' budgets must
    be bit-identical with it attached or not (the --tiers TierMeter
    contract, applied to the inter-pool plane). Install/uninstall
    around the audit loop; ``violations`` holds one string per
    over-budget crossing."""

    def __init__(self, page_bytes: int = 0):
        self.page_bytes = int(page_bytes)   # 0 = pages-only checks
        self.handoffs = 0
        self.pages = 0
        self.bytes = 0
        self.violations: List[str] = []

    def __call__(self, kind: str, data: dict) -> None:
        if kind != "handoff":
            return
        self.handoffs += 1
        self.pages += data.get("pages", 0)
        self.bytes += data.get("bytes", 0)
        if self.page_bytes:
            self.violations += handoff_audit([data], self.page_bytes)
        elif data.get("pages", 0) > data.get("pages_reserved", 0):
            self.violations.append(
                f"handoff rid {data['rid']}: moved {data['pages']} "
                f"pages > {data['pages_reserved']} reserved")

    def install(self) -> None:
        from ..observability import flight

        flight.LISTENERS.append(self)

    def uninstall(self) -> None:
        from ..observability import flight

        if self in flight.LISTENERS:
            flight.LISTENERS.remove(self)


def tier_transfer_audit(requests, page_bytes: int) -> List[str]:
    """Per-request tier-budget check: bytes migrated for a request must
    not exceed the KV bytes the request itself spans. Empty list =
    within budget."""
    v: List[str] = []
    if page_bytes <= 0:
        return [f"page_bytes must be positive, got {page_bytes}"]
    for r in requests:
        kv_bytes = r.pages_reserved * page_bytes
        if r.tier_bytes > kv_bytes:
            v.append(f"request {r.rid}: tier bytes {r.tier_bytes} > "
                     f"KV size {kv_bytes} "
                     f"({r.pages_reserved} pages x {page_bytes} B)")
        if r.tier_pages > r.pages_reserved:
            v.append(f"request {r.rid}: {r.tier_pages} tier pages > "
                     f"{r.pages_reserved} reserved")
    return v


def tier_conservation_audit(tier_stats: dict) -> List[str]:
    """Counter-consistency check over a ``HostTier.stats()`` snapshot:
    bytes and pages must agree at page_bytes per page, and the host
    store can never hold more than its bound."""
    v: List[str] = []
    pb = tier_stats.get("page_bytes", 0)
    if pb <= 0:
        return ["tier stats carry no page_bytes"]
    for bkey, ckey in (("bytes_to_host", "stages"),
                       ("bytes_to_hbm", "restores"),
                       ("bytes_imported", "imports")):
        if tier_stats[bkey] % pb:
            v.append(f"{bkey} {tier_stats[bkey]} is not a multiple of "
                     f"page_bytes {pb} — an unmetered partial transfer")
    if tier_stats["pages_host"] > tier_stats["capacity_pages"]:
        v.append(f"host store holds {tier_stats['pages_host']} pages > "
                 f"capacity {tier_stats['capacity_pages']}")
    # an entry stages ONCE and may spill/restore many times, but every
    # restore promotes an entry a spill (or import) previously demoted
    if tier_stats["restores"] > (tier_stats["spills"]
                                 + tier_stats["imports"]):
        v.append(f"{tier_stats['restores']} restores > "
                 f"{tier_stats['spills']} spills + "
                 f"{tier_stats['imports']} imports — a promotion of an "
                 f"entry that never left HBM")
    return v


def tiered_serve_audit(requests, host_tier,
                       page_bytes: Optional[int] = None) -> List[str]:
    """The combined pass a lane/test runs after a tiered serve: the
    per-request budget + the tier's conservation identities."""
    pb = page_bytes if page_bytes is not None else host_tier.page_bytes()
    return (tier_transfer_audit(requests, pb)
            + tier_conservation_audit(host_tier.stats()))


def handoff_audit(handoff_log, page_bytes: int) -> List[str]:
    """Per-handoff budget check over a ``DisaggRouter.handoff_log``
    ledger (r22): every inter-pool crossing must move at most the
    request's own reserved KV footprint — ``pages <= pages_reserved``
    and ``bytes <= pages_reserved x page_bytes`` — and its byte count
    must be exactly ``pages x page_bytes`` (whole pages cross, never a
    partial plane). Empty list = every handoff within budget."""
    v: List[str] = []
    if page_bytes <= 0:
        return [f"page_bytes must be positive, got {page_bytes}"]
    for h in handoff_log:
        who = (f"handoff rid {h['rid']} "
               f"({h['src']}->{h['dst']})")
        if h["pages"] > h["pages_reserved"]:
            v.append(f"{who}: moved {h['pages']} pages > "
                     f"{h['pages_reserved']} reserved")
        if h["bytes"] > h["pages_reserved"] * page_bytes:
            v.append(f"{who}: moved {h['bytes']} B > KV size "
                     f"{h['pages_reserved'] * page_bytes} B "
                     f"({h['pages_reserved']} pages x {page_bytes} B)")
        if h["bytes"] != h["pages"] * page_bytes:
            v.append(f"{who}: {h['bytes']} B is not {h['pages']} pages "
                     f"x {page_bytes} B — a partial-plane transfer "
                     f"went unmetered")
    return v


def disagg_serve_audit(router, page_bytes: Optional[int] = None
                       ) -> List[str]:
    """The combined pass after a disaggregated serve: every handoff
    within its budget, every request's total tier traffic within ITS
    budget (handoffs bill ``tier_pages``/``tier_bytes`` exactly like
    r19 migrations), and each replica tier's conservation identities."""
    reps = router._replicas
    pb = (page_bytes if page_bytes is not None
          else reps[0].prefix_cache.host_tier.page_bytes())
    reqs = [req for _idx, req in router._reqs.values()]
    v = handoff_audit(router.handoff_log, pb)
    v += tier_transfer_audit(reqs, pb)
    for r in reps:
        v += [f"replica {r.idx} ({r.pool}): {s}" for s in
              tier_conservation_audit(r.prefix_cache.host_tier.stats())]
    return v

"""Distributed checkpoint tests: sharded save + reshard-on-load across a
DIFFERENT mesh (reference: auto-parallel save/load_state_dict;
SURVEY.md §2.2 "Distributed checkpoint")."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed.checkpoint as dckpt
from paddle_tpu.parallel import create_hybrid_mesh, set_mesh


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    set_mesh(None)


def test_save_reshard_load_different_mesh(tmp_path):
    devs = jax.devices()
    mesh_a = create_hybrid_mesh(dp=2, mp=4, devices=devs[:8])
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    sharded = jax.device_put(w, NamedSharding(mesh_a, P("mp", None)))
    state = {"w": paddle.Tensor(sharded, stop_gradient=True),
             "step": paddle.to_tensor(np.int32(7))}
    dckpt.save_state_dict(state, str(tmp_path / "ck"))
    set_mesh(None)

    # load into a DIFFERENT topology: 4x2 mesh, sharded on the other axis
    mesh_b = create_hybrid_mesh(dp=4, mp=2, devices=devs[:8])
    target = {"w": paddle.Tensor(
        jax.device_put(np.zeros((8, 8), np.float32),
                       NamedSharding(mesh_b, P(None, "mp"))),
        stop_gradient=True),
        "step": paddle.to_tensor(np.int32(0))}
    dckpt.load_state_dict(target, str(tmp_path / "ck"))
    np.testing.assert_allclose(np.asarray(target["w"]._value), w)
    assert int(target["step"]._value) == 7
    # loaded array carries the TARGET sharding, not the saved one
    sh = target["w"]._value.sharding
    assert isinstance(sh, NamedSharding) and sh.spec == P(None, "mp")

"""The registered canonical programs the gate audits.

Each is a miniaturised-but-structurally-faithful instance of a hot path
whose hazard ledger earlier rounds paid for by hand:

* ``amp_o2_train_step``      — conv+BN+linear AMP-O2 ``fused_train_step``
  (the r8 GradScaler/donation territory: params+opt state must alias,
  zero host syncs per step).
* ``decode_tick``            — the serving engine's fused decode chunk
  (r6 territory: pure device loop, zero syncs, zero relayouts of the KV
  cache).
* ``serving_segment``        — the re-entrant continuous-batching
  segment + its host replay (r7 territory: exactly ONE allowed
  device_get per segment, no stray shape compiles).
* ``fused_optimizer_update`` — ``Optimizer.step``'s donated jit update
  over a mixed-shape population (the r8 relayout-ledger territory: the
  stack/concat pack bytes are THE metric).
* ``paged_serving_segment``  — the r11 page-table segment (zero pack
  bytes: prefix reuse is refcount data, not row copies).
* ``tp_serving_segment``     — the r12 mp-sharded segment (collectives
  must attribute to the 'mp' axis; the one-fetch contract survives
  GSPMD).
* ``chunked_serving_segment`` — the r13 chunked-prefill paged segment
  (prefill split into ladder-width chunks interleaved with decode
  ticks; still exactly one event fetch, chunk widths declared so the
  program-key family stays finite).
* ``spec_serving_segment``   — the r15 speculative segment (in-program
  n-gram draft + K+1-position verified ticks through the paged
  q_len>1 path; acceptance rides the single event fetch).
* ``quality_serving_segment`` — the r17 quality-digest paged segment
  (per-emitted-token logit + top-k ids/values computed in-program and
  rolled into the event log; the shadow-diff evidence stream must ride
  the SAME single fetch at zero extra syncs/compiles).
* ``quant_serving_segment``  — the r21 int8-quantized paged segment
  (narrow weight/KV streams with in-kernel or adjacent-to-dot dequant,
  per-page KV scale planes riding the pool; same one-dispatch/one-fetch
  loop on the qpseg dtype axis — zero extra syncs/compiles is the
  contract that makes the quantized rollout a pure bytes win).
* ``longctx_serving_segment`` — the r23 sequence-parallel long-context
  segment (a past-the-buckets prompt prefills as [sp, C] slabs whose
  rows scatter straight into the paged pool; decode proceeds on the
  ordinary page-indirect path with zero relayout at the boundary;
  still exactly one event fetch, spseg keys statically enumerated).

Builders are deterministic (fixed seeds, fixed shapes) so the measured
metrics are stable run to run and ``budgets.py`` can pin them as exact
ceilings.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["ProgramHandle", "register", "build", "names", "CANONICAL"]


@dataclass
class ProgramHandle:
    name: str
    hlo: Callable[[], str]          # optimized HLO text (compiled, cached)
    replay: Callable[[], Any]       # ONE warm iteration of the hot loop
    mesh: Any = None
    donation_threshold: int = 1 << 20
    expected_undonated: Tuple[str, ...] = ()
    allowed_axes: Optional[Tuple[str, ...]] = None
    notes: str = ""
    keepalive: tuple = ()           # pins models/engines for the handle's life
    # r20 (ISSUE 15): the serving programs carry their engine + the
    # workload envelope their replay stays inside, so the gate's --aot
    # mode can lint/enumerate/warm the full program space before the
    # audit and diff enumerated-vs-used after it (budgets must come out
    # bit-identical --aot on|off — warmup only moves WHEN compiles
    # happen, never what the warm replay does)
    aot_engine: Any = None
    aot_envelope: Any = None


CANONICAL: Dict[str, Callable[[], ProgramHandle]] = {}


def register(name: str):
    def deco(fn):
        CANONICAL[name] = fn
        return fn
    return deco


def names() -> List[str]:
    return sorted(CANONICAL)


def build(name: str) -> ProgramHandle:
    if name not in CANONICAL:
        raise KeyError(f"unknown canonical program {name!r}; "
                       f"registered: {names()}")
    return CANONICAL[name]()


def _memo(fn):
    box: list = []

    def wrapped():
        if not box:
            box.append(fn())
        return box[0]
    return wrapped


def _gate_envelope(seg_steps, max_prompt: int = 12,
                   max_new_tokens: int = 4):
    """The workload envelope the gate's canonical serving replays stay
    inside (12-token prompts, short generations, one seg_steps value —
    exactly what each ``replay()`` enqueues). ``--aot on`` enumerates +
    compiles this space up front and diffs it against what the audit
    replays actually use."""
    from paddle_tpu.inference.program_space import WorkloadEnvelope

    return WorkloadEnvelope(max_prompt=max_prompt,
                            max_new_tokens=max_new_tokens,
                            seg_steps=tuple(seg_steps))


# ---------------------------------------------------------------------------
# 1. AMP-O2 train step
# ---------------------------------------------------------------------------


@register("amp_o2_train_step")
def _build_amp_o2_train_step() -> ProgramHandle:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    model = nn.Sequential(
        nn.Conv2D(3, 16, 3, padding=1), nn.BatchNorm2D(16), nn.ReLU(),
        nn.MaxPool2D(2), nn.Flatten(),
        nn.Linear(16 * 16 * 16, 128), nn.ReLU(), nn.Linear(128, 10))
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    model, opt = paddle.amp.decorate(models=model, optimizers=opt,
                                     level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            return ce(model(x), y)

    step = paddle.jit.fused_train_step(loss_fn, opt, model=model)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(8, 3, 32, 32).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (8,)))

    return ProgramHandle(
        name="amp_o2_train_step",
        hlo=_memo(lambda: step.compiled_text(x, y)),
        replay=lambda: step(x, y),
        # the batch, labels, RNG key, BN buffers and per-step scalars ride
        # undonated by design; params + velocity alias in place
        donation_threshold=1 << 18,
        expected_undonated=(),
        notes="conv+BN AMP-O2 fused train step, b8 32x32, Momentum",
        keepalive=(model, opt, step, x, y))


# ---------------------------------------------------------------------------
# 2 + 3. Serving programs (one tiny engine serves both)
# ---------------------------------------------------------------------------


def _tiny_engine():
    import jax.numpy as jnp

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,))
    return cfg, params, eng, jnp


@register("decode_tick")
def _build_decode_tick() -> ProgramHandle:
    import jax.numpy as jnp

    from paddle_tpu.models import llama

    cfg, params, eng, _ = _tiny_engine()
    decode = eng._decode_prog

    def fresh_args():
        cache = llama.init_kv_cache(cfg, eng.slots, eng.max_len)
        pos = jnp.full((eng.slots,), 4, jnp.int32)
        nxt = jnp.ones((eng.slots,), jnp.int32)
        rem = jnp.full((eng.slots,), eng.chunk, jnp.int32)
        return params, cache, pos, nxt, rem

    def hlo():
        return decode.lower(*fresh_args()).compile().as_text()

    def replay():
        # the chunk donates the cache, so every iteration rebuilds one
        # (zeros program: compiles once in warmup); NO host fetch — the
        # tick is the pure device loop
        return decode(*fresh_args())

    return ProgramHandle(
        name="decode_tick",
        hlo=_memo(hlo),
        replay=replay,
        # model weights legitimately stay live across ticks; only the KV
        # cache is donation-critical and the budget pins the measured
        # undonated total so a NEW large undonated buffer regresses it
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="fused decode chunk (8 ticks), llama-tiny, 4 slots",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(12,)),
        keepalive=(eng,))


@register("serving_segment")
def _build_serving_segment() -> ProgramHandle:
    import numpy as np

    cfg, params, eng, jnp = _tiny_engine()
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end segment: enqueue two requests, run ONE fused
        # segment, host-replay the event log. The device_get inside
        # run_segment is the intended per-segment fetch (allowed_sync);
        # every request finishes inside the segment so slot state drains
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(12)

    def hlo():
        seg = eng._segment_prog(eng._pow2(eng.slots), eng.buckets[-1], 0, 12)
        n_pad = eng._pow2(eng.slots)
        s_max = eng.buckets[-1]
        import jax.numpy as j

        from paddle_tpu.models import llama

        L, Hkv, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
        cache = llama.init_kv_cache(cfg, eng.slots, eng.max_len)
        return seg.lower(
            params, cache, j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((n_pad, s_max), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, L, 0, Hkv, D), cache["k"].dtype),
            j.zeros((n_pad, L, 0, Hkv, D), cache["v"].dtype),
            j.zeros((n_pad,), j.int32), j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="re-entrant fused segment + host event replay, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(12,)),
        keepalive=(eng,))


@register("paged_serving_segment")
def _build_paged_serving_segment() -> ProgramHandle:
    import numpy as np

    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), paged=True, page_size=16)
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end PAGED segment: reserve pages host-side, one fused
        # dispatch, one allowed event fetch, page bookkeeping on host
        # mirrors — every request finishes inside the segment so pages
        # drain back to the free list each iteration
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(12)

    def hlo():
        n_pad = eng._pow2(eng.slots)
        s_max = eng.buckets[-1]
        seg = eng._paged_segment_prog(n_pad, s_max, 12)
        pgr = eng.pager
        return seg.lower(
            params, pgr.pool, pgr.page_table,
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((n_pad, s_max), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32), j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, pgr.max_pages), j.int32),
            j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="paged_serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="paged re-entrant segment (page-table pool, COW-ready) + "
              "host event replay with page bookkeeping, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(12,)),
        keepalive=(eng,))


@register("chunked_serving_segment")
def _build_chunked_serving_segment() -> ProgramHandle:
    """The r13 chunked-prefill segment (ISSUE 8a): the paged segment
    with admits split into declared-ladder chunks interleaved with
    decode ticks. The contract the budget pins: chunking must not cost
    a single extra host sync (still exactly ONE event fetch per
    segment), zero warm compiles (chunk widths come from the declared
    ladder, so the ("cseg", ...) key family is finite and the warm
    replay covers it), and no new relayout/pack traffic beyond the
    while-body carries the paged segment already pays."""
    import numpy as np

    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), paged=True, page_size=16,
                        chunked_prefill=True, prefill_chunks=(8,))
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end CHUNKED segment: two 12-token prompts each prefill
        # as 2 interleaved 8-token chunks, decode to completion inside
        # the segment (slots + pages drain), one allowed event fetch
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(16)

    def hlo():
        n_pad = eng._pow2(eng.slots)
        C = eng._prefill_chunk_for(eng.buckets[-1])
        s_max_c = -(-eng.buckets[-1] // C) * C
        seg = eng._chunked_segment_prog(n_pad, s_max_c, C, 16)
        pgr = eng.pager
        return seg.lower(
            params, pgr.pool, pgr.page_table,
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((n_pad, s_max_c), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32), j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, pgr.max_pages), j.int32),
            j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="chunked_serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="chunked-prefill paged segment (8-token chunks interleaved "
              "with decode ticks) + host event replay, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(16,)),
        keepalive=(eng,))


@register("longctx_serving_segment")
def _build_longctx_serving_segment() -> ProgramHandle:
    """The r23 sequence-parallel long-context segment (ISSUE 18): a
    prompt PAST the regular bucket ladder prefills as sp-row slabs —
    each slab step covers ``sp * C`` prompt tokens reshaped to [sp, C]
    rows at absolute offsets ``base + r*C``, every row scattering its
    K/V straight into the shared paged pool — interleaved with ordinary
    decode ticks for co-resident slots. The contract the budget pins:
    long-context must be free at the hazard level — still exactly ONE
    event fetch per segment, zero warm compiles (the ("spseg", n_pad,
    s_max, C, sp, steps) family is closed over the declared long-bucket
    ladder, so sp_rungs is statically enumerable), no pack traffic, and
    the relayout ledger stays in the while-body pool-carry class: the
    prefill→decode boundary costs ZERO relayout because decode reads
    the very pages the slab rows scattered."""
    import numpy as np

    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), paged=True, page_size=16,
                        prefill_chunks=(8,), seq_parallel=2,
                        long_buckets=(32,))
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end LONG-CONTEXT segment: one 24-token prompt (past
        # the 16 bucket — slab-prefills as 2 steps of [2, 8] rows) plus
        # one co-resident 12-token prompt, decode to completion inside
        # the segment (slots + pages drain), one allowed event fetch
        eng.add_request(rng.randint(0, cfg.vocab_size, (24,)), 4)
        eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(16)

    def hlo():
        n_pad = eng._pow2(eng.slots)
        C = eng.prefill_chunks[-1]
        Cs = eng.seq_parallel * C
        s_max = -(-eng.long_buckets[-1] // Cs) * Cs
        seg = eng._sp_segment_prog(n_pad, s_max, C, 16)
        pgr = eng.pager
        return seg.lower(
            params, pgr.pool, pgr.page_table,
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((n_pad, s_max), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32), j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, pgr.max_pages), j.int32),
            j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="longctx_serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="sequence-parallel long-context segment (sp=2 slab prefill "
              "scattering into the paged pool, page-indirect decode) + "
              "host event replay, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(16,), max_prompt=24),
        keepalive=(eng,))


@register("spec_serving_segment")
def _build_spec_serving_segment() -> ProgramHandle:
    """The r15 speculative segment (ISSUE 10): the paged segment whose
    decode steps draft K tokens from the slot's in-program n-gram table
    and verify all K+1 positions in one batched tick through the paged
    q_len>1 path. The contract the budget pins: speculation must be
    free at the hazard level — still exactly ONE event fetch per
    segment (the acceptance counts ride the same fetch; per-request
    accepted lengths are host replay arithmetic), zero flagged syncs,
    zero warm compiles (the ("sseg", n_pad, K, steps) key family pins
    the admit width to the largest bucket, so prefix hits and arrival
    jitter add no shapes), and no pack traffic beyond the while-body
    pool carries the paged segment already pays."""
    import numpy as np

    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), paged=True, page_size=16,
                        speculative=3)
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end SPECULATIVE segment: two requests, drafts verified
        # in multi-token ticks, ONE fused dispatch, the single allowed
        # event fetch, host replay recovers acceptance — every request
        # finishes inside the segment so slots + pages drain
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 6)
        return eng.run_segment(16)

    def hlo():
        n_pad = eng._pow2(eng.slots)
        K = eng.speculative
        seg = eng._spec_segment_prog(n_pad, 16)
        pgr = eng.pager
        return seg.lower(
            params, pgr.pool, pgr.page_table,
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots, eng.max_len + 1), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots, 2), j.uint32),
            j.zeros((n_pad, eng.buckets[-1]), j.int32),
            j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32), j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, pgr.max_pages), j.int32),
            j.zeros((n_pad,), j.int32),
            j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="spec_serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="speculative paged segment (K=3 n-gram draft, multi-token "
              "verified ticks) + host acceptance replay, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(16,), max_new_tokens=6),
        keepalive=(eng,))


@register("quality_serving_segment")
def _build_quality_serving_segment() -> ProgramHandle:
    """The r17 quality-digest segment (ISSUE 12): the paged segment
    whose event log additionally carries per-step per-slot logit
    digests — the emitted token's logit plus the tick's top-k ids and
    values, computed in-program from logits the tick already produced.
    The contract the budget pins: quality evidence must be FREE at the
    hazard level — still exactly ONE event fetch per segment (the
    digest columns ride the same fetch; the shadow-diff comparison is
    host arithmetic on the replayed log), zero flagged syncs, zero warm
    compiles (the ("qseg", n_pad, s_max, steps) family is bucketed
    exactly like the plain paged family), and the relayout ledger is
    the paged while-body pool-carry class plus the digest columns'
    tiny carries."""
    import numpy as np

    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), paged=True, page_size=16,
                        quality_digest=True, digest_top_k=4)
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end DIGEST segment: two requests decode to completion
        # inside the segment, the single allowed event fetch returns
        # tokens AND digests, the host replay distributes both
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(12)

    def hlo():
        n_pad = eng._pow2(eng.slots)
        s_max = eng.buckets[-1]
        seg = eng._paged_segment_prog(n_pad, s_max, 12)
        pgr = eng.pager
        return seg.lower(
            params, pgr.pool, pgr.page_table,
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((n_pad, s_max), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32), j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, pgr.max_pages), j.int32),
            j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="quality_serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="quality-digest paged segment (k=4 top-k logit digests "
              "in the event log) + host digest replay, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(12,)),
        keepalive=(eng,))


@register("quant_serving_segment")
def _build_quant_serving_segment() -> ProgramHandle:
    """The r21 quantized paged segment (ISSUE 16): the paged segment
    with int8 weight streaming (per-output-channel scales, dequant
    in-kernel on TPU / adjacent-to-dot on the dense fallback) and an
    int8 KV pool carrying per-page scale planes. The contract the
    budget pins: quantization must be FREE at the hazard level — the
    ("qpseg", n_pad, s_max, steps, dtype) family is bucketed exactly
    like the plain paged family, still exactly ONE event fetch per
    segment, zero flagged syncs, zero warm compiles — so the narrow
    HBM stream is a pure bytes win the roofline model (SCALING §3p)
    can bank without hazard caveats."""
    import numpy as np

    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), paged=True, page_size=16,
                        quant="int8")
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end QUANTIZED segment: two requests decode to
        # completion inside the segment — narrow weight/KV streams,
        # ONE fused dispatch, the single allowed event fetch
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(12)

    def hlo():
        n_pad = eng._pow2(eng.slots)
        s_max = eng.buckets[-1]
        seg = eng._paged_segment_prog(n_pad, s_max, 12)
        pgr = eng.pager
        return seg.lower(
            eng.params, pgr.pool, pgr.page_table,
            j.zeros((eng.slots,), j.int32), j.zeros((eng.slots,), j.int32),
            j.zeros((eng.slots,), j.int32),
            j.zeros((n_pad, s_max), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32), j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, pgr.max_pages), j.int32),
            j.int32(2)).compile().as_text()

    return ProgramHandle(
        name="quant_serving_segment",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="int8-quantized paged segment (narrow weight/KV streams, "
              "per-page KV scales, in-kernel dequant) — qpseg dtype "
              "axis, llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(12,)),
        keepalive=(eng,))


@register("tp_serving_segment")
def _build_tp_serving_segment() -> ProgramHandle:
    """The r12 tensor-parallel serving segment: the re-entrant fused
    segment with weights GSPMD-sharded Megatron-style and the KV cache
    sharded on the head dim over an 'mp' mesh. The contract the budget
    pins: the ONE-dispatch/one-fetch shape survives sharding (same
    single allowed event fetch, zero warm compiles) and every collective
    in the program attributes to the 'mp' axis — an unattributed or
    off-axis collective is a GSPMD repartition hazard, exactly the class
    ``collective_check`` was promoted to catch. Builds mp=2 when two
    devices exist (tier-1's virtual-CPU platform, the MULTICHIP dryrun
    pattern), mp=1 on a single chip — the sync/compile budgets bind
    either way, the collective attribution bites at mp=2."""
    import numpy as np

    import jax
    import jax.numpy as j

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import llama
    from paddle_tpu.parallel.mesh import create_hybrid_mesh

    devs = jax.devices()
    mp = 2 if len(devs) >= 2 else 1
    mesh = create_hybrid_mesh(mp=mp, devices=devs[:mp],
                              set_as_global=False)
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    eng = ServingEngine(cfg, params, slots=4, max_len=64, chunk=8,
                        prompt_buckets=(16,), mesh=mesh)
    rng = np.random.RandomState(0)

    def replay():
        # end-to-end mp-sharded segment: two requests, ONE fused
        # dispatch over the mesh, the single allowed event fetch, host
        # replay — every request finishes inside the segment so slot
        # state drains (the engine scopes the mesh itself)
        for _ in range(2):
            eng.add_request(rng.randint(0, cfg.vocab_size, (12,)), 4)
        return eng.run_segment(12)

    def hlo():
        from jax.sharding import NamedSharding

        n_pad = eng._pow2(eng.slots)
        s_max = eng.buckets[-1]
        seg = eng._progs[("seg", n_pad, s_max, 0, 12)]
        L, Hkv, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
        cache = jax.device_put(
            llama.init_kv_cache(cfg, eng.slots, eng.max_len),
            NamedSharding(mesh, llama.kv_cache_spec()))
        return seg.lower(
            eng.params, cache, eng._pos, eng._nxt, eng._rem,
            j.zeros((n_pad, s_max), j.int32), j.ones((n_pad,), j.int32),
            j.zeros((n_pad,), j.int32),
            j.zeros((n_pad, L, 0, Hkv, D), cache["k"].dtype),
            j.zeros((n_pad, L, 0, Hkv, D), cache["v"].dtype),
            j.zeros((n_pad,), j.int32), j.int32(2)).compile().as_text()

    def hlo_warm():
        replay()              # materialise the ("seg", ...) program
        return hlo()

    return ProgramHandle(
        name="tp_serving_segment",
        hlo=_memo(hlo_warm),
        replay=replay,
        mesh=mesh,
        donation_threshold=1 << 16,
        expected_undonated=(),
        allowed_axes=("mp",),
        notes=f"mp={mp} GSPMD-sharded re-entrant segment (column/row-"
              f"parallel weights, head-sharded KV cache), llama-tiny",
        aot_engine=eng,
        aot_envelope=_gate_envelope(seg_steps=(12,)),
        keepalive=(eng,))


# ---------------------------------------------------------------------------
# 4. Fused optimizer update
# ---------------------------------------------------------------------------


@register("fused_optimizer_update")
def _build_fused_optimizer_update() -> ProgramHandle:
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    # the r8 ledger population in miniature: a few big tiled tensors +
    # a crowd of small 1-D rows (the launch-bound class the flat pack
    # exists for)
    shapes = ([(128, 256)] * 2 + [(256,)] * 8 + [(64, 64)] * 4
              + [(32,)] * 6)
    rng = np.random.RandomState(0)
    params = [nn.Parameter(jnp.asarray(rng.randn(*s), jnp.float32))
              for s in shapes]
    opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                    parameters=params)

    def grads(seed):
        r = np.random.RandomState(seed)
        return [jnp.asarray(r.randn(*s).astype(np.float32)) for s in shapes]

    gsets = [grads(s) for s in range(3)]
    it = [0]

    def replay():
        gs = gsets[it[0] % len(gsets)]
        it[0] += 1
        for p, g in zip(params, gs):
            p.grad = paddle.Tensor(g, stop_gradient=True)
        opt.step()

    def hlo():
        replay()  # materialise _jit_update + warm state
        pvals = [p._value for p in params]
        svals = [{k: opt._accumulators[id(p)][k]
                  for k in opt._state_names()} for p in params]
        evals = [opt._per_param_extras(p) for p in params]
        return opt._jit_update.lower(
            pvals, gsets[0], svals, evals, jnp.float32(opt.get_lr()),
            jnp.int32(opt._step_count + 1)).compile().as_text()

    return ProgramHandle(
        name="fused_optimizer_update",
        hlo=_memo(hlo),
        replay=replay,
        donation_threshold=1 << 16,
        expected_undonated=(),
        notes="Momentum multi-tensor update, 20 mixed-shape tensors "
              "(pack/relayout ledger program)",
        keepalive=(params, opt))

"""Online request-lifecycle scheduler (r7 tentpole; VERDICT r5 items 3/9).

The layer between the decode kernels (PR 1) and a real workload: the
serving engine proves itself OFFLINE — ``run()`` drains a pre-loaded
queue — but production traffic arrives over time, and the TPU-native win
of the fused drain (admission costs no host round trip) only matters if
the scheduler can keep slots full under a live arrival process. This
module owns that loop:

* **Clocked arrivals** — seeded Poisson (``poisson_arrivals``) or
  staggered/uniform (``staggered_arrivals``) traces; every trace is a
  plain list of ``Arrival`` rows so benchmarks replay the identical
  trace against the engine AND the fixed-batching baseline.
* **Admission control / backpressure** — a bounded intake queue:
  arrivals past ``max_queue`` stay client-side (the arrival stream
  blocks) and each refusal is counted; the queue drains FCFS.
* **Continuous batching** — the engine's re-entrant fused segments
  (``ServingEngine.run_segment``): each turn of the loop ingests due
  arrivals, then runs ONE compiled segment that admits queued requests
  into free slots and decodes up to ``seg_steps`` ticks — one dispatch
  + one fetch per segment, in-program refill when slots retire
  mid-segment.
* **Measured telemetry** — per-request arrival / admit / first-token /
  finish wall-clock stamps, taken at the host sync that actually
  surfaced each event (a token "exists" for a client only once a fetch
  delivered it), yielding TTFT and e2e latency percentiles that are
  measurements, not the uniform-step model r5 shipped. Segment spans
  are emitted through ``profiler._hooks`` so ``paddle.profiler``
  captures scheduler activity like any op.
* **Shared-prefix KV reuse** — pass a ``PrefixCache``; admission
  detects cached prefixes and the segment program prefills suffixes
  only (see inference/prefix_cache.py).

Audited sync contract (r9, ``paddle_tpu.analysis``): the serve loop
performs exactly ONE device→host sync per segment — the event fetch in
``ServingEngine.run_segment``, marked ``allowed_sync
("serving.segment_event_fetch")``. The r9 audit over the full online
loop found no other sync: the host replay, telemetry stamping, queue
management and prefix bookkeeping all work on host mirrors of the
fetched event log. ``tests/test_analysis.py::TestSchedulerAudit``
enforces this per segment, so a per-token poll cannot silently return.

r10 (``paddle_tpu.observability``): the loop feeds the runtime
telemetry registry from those same host mirrors — queue-depth /
occupancy gauges, TTFT / e2e / queue-wait histograms, backpressure
counters, per-request lifecycle spans, flight-recorder events — with
zero additional syncs (the metrics layer refuses device values, and the
audit above passes with telemetry enabled; overhead gated at ≤2 % in
``tests/test_observability.py``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import capacity as _capacity
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..observability.metrics import percentile as _pctl
from ..profiler import _hooks
from .prefix_cache import PrefixCache
from .serving import Request, ServingEngine

__all__ = ["Arrival", "OnlineScheduler", "SLOScheduler",
           "poisson_arrivals", "staggered_arrivals", "scale_rate"]


@dataclass
class Arrival:
    t: float                  # seconds after serve() start
    prompt: np.ndarray        # [S] int32
    max_new_tokens: int
    # r13 SLO-aware serving (ISSUE 8): smaller priority outranks larger
    # (class 0 = interactive, class 1+ = batch); deadline_s is an e2e
    # deadline RELATIVE to this request's arrival (None = never shed).
    # Plain OnlineScheduler ignores both — SLOScheduler enforces them.
    priority: int = 0
    deadline_s: Optional[float] = None


def poisson_arrivals(seed: int, n: int, rate: float, vocab: int,
                     prompt_lens: Sequence[int] = (32, 64, 128),
                     gen_lens: Sequence[int] = (16, 32, 64),
                     prefix: Optional[np.ndarray] = None) -> List[Arrival]:
    """Seeded Poisson process: exponential inter-arrival gaps at ``rate``
    requests/sec; prompt/generation lengths drawn uniformly from the
    given grids. ``prefix`` (optional) is prepended to every prompt —
    the shared-prefix workload generator."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    rng = np.random.RandomState(seed)
    t = 0.0
    out = []
    for _ in range(n):
        t += float(rng.exponential(1.0 / rate))
        body = rng.randint(0, vocab, (int(rng.choice(prompt_lens)),)
                           ).astype(np.int32)
        if prefix is not None:
            body = np.concatenate([np.asarray(prefix, np.int32), body])
        out.append(Arrival(t, body, int(rng.choice(gen_lens))))
    return out


def staggered_arrivals(seed: int, n: int, gap: float, vocab: int,
                       prompt_lens: Sequence[int] = (32, 64, 128),
                       gen_lens: Sequence[int] = (16, 32, 64),
                       prefix: Optional[np.ndarray] = None) -> List[Arrival]:
    """Deterministically spaced arrivals (one every ``gap`` seconds) —
    the fully reproducible trace for tests."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        body = rng.randint(0, vocab, (int(rng.choice(prompt_lens)),)
                           ).astype(np.int32)
        if prefix is not None:
            body = np.concatenate([np.asarray(prefix, np.int32), body])
        out.append(Arrival(i * gap, body, int(rng.choice(gen_lens))))
    return out


def scale_rate(arrivals: Sequence[Arrival], factor: float) -> List[Arrival]:
    """THE SAME trace at ``factor``x the arrival rate: identical
    prompts, generation lengths and arrival ORDER, every inter-arrival
    gap divided by ``factor``. The fleet benchmark's load axis (r12) —
    comparing fleet sizes on a re-drawn trace would confound routing
    with sampling noise; compressing the clock of one seeded trace
    isolates the capacity question."""
    if factor <= 0:
        raise ValueError(f"factor must be > 0, got {factor}")
    return [Arrival(a.t / factor, a.prompt, a.max_new_tokens)
            for a in arrivals]


@dataclass
class OnlineReport:
    """Measured outcome of one serve() run (all times in seconds)."""
    n_requests: int
    total_tokens: int
    makespan_s: float
    throughput_tok_s: float
    ttft_p50_s: float
    ttft_p99_s: float
    e2e_p50_s: float
    e2e_p99_s: float
    queue_wait_p50_s: float
    slot_occupancy: float          # useful decode slot-steps / total
    segments: int
    ticks: int
    backpressure_events: int
    # r11 paged engine: admissions deferred because the PAGE POOL (not
    # the queue bound) was the constraint — backpressure{reason="pages"}
    # — plus the pool's occupancy stats; 0/None on contiguous engines
    backpressure_pages: int = 0
    pages: Optional[dict] = None
    prefix: Optional[dict] = None  # PrefixCache.stats() when enabled
    # r13 SLO-aware serving: retry_after_s is the LAST machine-readable
    # backpressure hint handed to a refused client (seconds until the
    # bounded queue is expected to have drained one slot, derived from
    # the measured finish rate — None when nothing was refused); the
    # rest is the overload control plane's accounting, all zero/None
    # under the plain scheduler.
    retry_after_s: Optional[float] = None
    preemptions: int = 0
    shed: int = 0
    shed_per_class: Optional[Dict[int, int]] = None
    displaced: int = 0             # queue spots yielded to a higher class
    per_class: Optional[Dict[int, dict]] = None  # class -> latency stats
    # r14 (ISSUE 9): cold-start→first-token of the engine this serve
    # drove (None until the engine emitted its first post-build token),
    # and — when the monitors are attached — the SLO monitor's
    # budget/burn/alert state and the explained-perf interval report
    cold_start_s: Optional[float] = None
    slo: Optional[dict] = None
    perf: Optional[dict] = None
    # r18 (ISSUE 13): the capacity monitor's exhaustion-alert state and
    # the per-priority-class resource-attribution aggregate (page-
    # seconds, weight streams, ledger-joined HBM bytes/FLOPs) — the
    # meter section is always present on paged serves (the stamps are
    # free host arithmetic); capacity needs the monitor attached
    capacity: Optional[dict] = None
    meter: Optional[dict] = None
    # r19 (ISSUE 14): the host-tier breakdown when the prefix cache has
    # a spill tier attached — pages staged/spilled/restored + the byte
    # counters the tier-transfer budget audits (None otherwise)
    tiers: Optional[dict] = None
    per_request: List[dict] = field(default_factory=list)

    def as_dict(self, with_requests: bool = False) -> dict:
        d = {k: v for k, v in self.__dict__.items() if k != "per_request"}
        if with_requests:
            d["per_request"] = self.per_request
        return d


# percentiles: the ONE shared nearest-rank rule (r10 dedup — this module's
# private copy moved to observability.metrics.percentile, bit-identical;
# tests/test_observability.py pins exact parity against the r7 rule)

class OnlineScheduler:
    """Drive a ``ServingEngine`` under a clocked arrival trace.

    ``seg_steps`` is the control-latency knob: the host regains control
    (to ingest arrivals and stamp times) every ``seg_steps`` device
    ticks — small values tighten TTFT under bursty arrivals, large
    values amortise dispatch cost (the fused segment makes either cheap:
    one dispatch + one fetch regardless)."""

    def __init__(self, engine: ServingEngine, max_queue: int = 64,
                 seg_steps: int = 32,
                 prefix_cache: Optional[PrefixCache] = None,
                 slo_monitor=None, perf_monitor=None,
                 capacity_monitor=None):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.seg_steps = int(seg_steps)
        self.prefix_cache = prefix_cache
        # r14 (ISSUE 9): optional live-ops monitors. Both consume only
        # the host stamps this loop already takes at the per-segment
        # allowed_sync fetch — attaching them adds zero device contacts
        # (tests/test_slo_monitor.py pins bit-identical sync audits).
        self.slo_monitor = slo_monitor
        self.perf_monitor = perf_monitor
        # r18 (ISSUE 13): predictive exhaustion alerting. The monitor
        # is evaluated BEFORE each paged dispatch (begin_segment) so a
        # capacity page can LEAD the first pages-backpressure deferral,
        # and fed after each fetch with the segment's fresh-page
        # admissions — host mirrors only, same zero-sync contract.
        self.capacity_monitor = capacity_monitor
        self.backpressure_events = 0
        self._reqs: Dict[int, Request] = {}
        # r13: drain-rate bookkeeping for the retry_after_s backpressure
        # hint (finished requests this serve / elapsed); the SLO
        # subclass reuses it for deadline estimates
        self.last_retry_after_s: Optional[float] = None
        self._finished_count = 0
        self._serve_t0 = 0.0
        # r15: measured seconds per segment STEP (EWMA over segments,
        # available from the first fetch — before any request finishes).
        # With the engine's acceptance EWMA this prices remaining work
        # in ticks: a speculative engine retires ~accept_ewma tokens
        # per tick, so owed/accept ticks x per-tick seconds is the
        # acceptance-aware service estimate (ISSUE 10 satellite: the
        # one-token-per-tick assumption over-shed speculative serves)
        self._per_tick_s = 0.0

    # --- intake ----------------------------------------------------------
    def retry_after_hint(self, now: float) -> float:
        """Machine-readable backoff for a refused client (r13 satellite):
        seconds until the bounded queue is expected to free one slot,
        derived from the CURRENT drain rate (requests finished this
        serve / elapsed). Before any finish the measured rate is
        unknown and the hint falls back to one second scaled by the
        engine's acceptance EWMA (a speculative engine drains ~accept
        times faster than one-token-per-tick would suggest — r15) —
        still a signal to stop hammering the queue. Clamped to
        [1 ms, 60 s]."""
        if self._finished_count and now > 0:
            return min(max(now / self._finished_count, 1e-3), 60.0)
        accept = max(float(getattr(self.engine, "spec_accept_ewma", 1.0)),
                     1.0)
        return 1.0 / accept

    def _note_arrival(self, r: Request, a: Arrival) -> None:
        """Per-request intake hook (the SLO subclass stamps priority /
        deadline and reorders the queue here)."""

    def _ingest(self, pending: List[Arrival], now: float, t0: float) -> int:
        """Move due arrivals into the engine queue, honouring the bound.
        Returns how many were refused (left client-side) this poll."""
        refused = 0
        while pending and pending[0].t <= now:
            if len(self.engine._queue) >= self.max_queue:
                refused += 1
                break
            a = pending.pop(0)
            rid = self.engine.add_request(a.prompt, a.max_new_tokens)
            r = self.engine._queue[-1]
            assert r.rid == rid
            r.arrival_time = t0 + a.t   # client-side timestamp
            self._reqs[rid] = r
            self._note_arrival(r, a)
            _journal.record("arrival", rid=rid, at=a.t,
                            priority=r.priority,
                            deadline_s=getattr(a, "deadline_s", None),
                            prompt_len=len(r.prompt),
                            gen=r.max_new_tokens)
        if refused:
            hint = self.retry_after_hint(now)
            self.last_retry_after_s = hint
            self.backpressure_events += 1
            _metrics.counter("serving.backpressure_events").inc()
            _metrics.gauge("serving.retry_after_s").set(hint)
            _flight.record("backpressure", refused=refused,
                           queue=len(self.engine._queue),
                           retry_after_s=round(hint, 4))
        return refused

    # --- the serve loop --------------------------------------------------
    def serve(self, arrivals: Sequence[Arrival],
              warm: bool = False) -> OnlineReport:
        """Serve the trace to completion and return measured stats.

        ``warm=True`` first replays the identical trace once (same gaps,
        so the same admit groupings and segment shapes compile), then
        resets slot state — the measured pass times scheduling, not
        XLA."""
        if warm:
            self.serve(arrivals, warm=False)
            self.engine.reset_slots()
            self._reqs.clear()
            self.backpressure_events = 0
            if self.prefix_cache is not None:
                # warmup must not pre-populate measured-run hits (paged
                # caches also hand their page refs back to the pool)
                self.prefix_cache.reset()
            self._reset_monitors()

        # r16 (ISSUE 11): with a journal attached, this serve records
        # its header (rebuildable topology + the full trace) and every
        # decision-relevant clock read routes through ``journal.now()``
        # — the black-box recording an offline replay feeds back to
        # reproduce the decision stream bit-exactly. With no journal,
        # ``journal.now()`` is a plain perf_counter behind one check.
        _j = _journal.active()
        if _j is not None:
            _j.begin_serve(self._journal_header(arrivals))
        pending = sorted(arrivals, key=lambda a: a.t)
        eng = self.engine
        eng.last_run_ticks = 0
        eng.last_run_chunks = 0
        segments = 0
        self.last_retry_after_s = None
        self._finished_count = 0
        # telemetry handles hoisted out of the loop (one dict lookup each,
        # paid once per serve, not per segment); all values recorded below
        # are host mirrors — the loop's only device contact stays the one
        # audited allowed_sync fetch inside run_segment
        m_queue = _metrics.gauge("serving.queue_depth")
        m_ttft = _metrics.histogram("serving.ttft_s")
        m_e2e = _metrics.histogram("serving.e2e_s")
        m_qwait = _metrics.histogram("serving.queue_wait_s")
        t0 = _journal.now()
        self._serve_t0 = t0
        while pending or eng._queue or eng.free_slot_count() < eng.slots:
            now = _journal.now() - t0
            self._ingest(pending, now, t0)
            m_queue.set(len(eng._queue))
            # r13 SLO hook: the subclass sheds unmeetable-deadline
            # requests and preempts for blocked higher classes here —
            # host bookkeeping between segments, zero device contact
            self._pre_segment(now, t0)
            idle = (not eng._queue
                    and eng.free_slot_count() == eng.slots)
            if idle:
                # nothing admitted and nothing decoding: sleep to the
                # next arrival instead of spinning
                if pending:
                    gap = pending[0].t - (_journal.now() - t0)
                    if gap > 0:
                        _journal.sleep(min(gap, 0.05))
                continue
            cap = self.capacity_monitor
            if cap is not None and eng.paged:
                # r18: evaluate time-to-exhaustion BEFORE the dispatch
                # that could hit pages-backpressure — the alert must
                # lead the valve (ISSUE 13 acceptance bar). r19: the
                # availability term gains the tier dimension — host-
                # tier pages ride the same evaluation as a separate
                # (reclaimable-at-restore-cost) pool.
                pc = self.prefix_cache
                has_rec = (pc is not None
                           and hasattr(pc, "reclaimable_pages"))
                cap.begin_segment(
                    eng.pager.pages_free,
                    pc.reclaimable_pages() if has_rec else 0,
                    host_pages=(pc.host_pages if has_rec
                                and getattr(pc, "host_tier", None)
                                is not None else None))
            t_seg = _hooks.now_ns()
            t_seg_pc = _journal.now()
            ev = eng.run_segment(self.seg_steps,
                                 prefix_cache=self.prefix_cache)
            t_sync = _journal.now()
            _hooks.emit("serving.segment", t_seg, _hooks.now_ns(),
                        kind="serving")
            segments += 1
            mon = self.slo_monitor
            for rid in ev["admitted"]:
                r = self._reqs[rid]
                _journal.record("admit", rid=rid,
                                prefix_hit_len=r.prefix_hit_len,
                                priority=r.priority,
                                resumed=bool(r.preemptions or r.requeues),
                                tokens_done=len(r.tokens))
            for rid in ev["first_tokens"]:
                r = self._reqs[rid]
                r.first_token_time = t_sync
                m_ttft.observe(t_sync - r.arrival_time)
                m_qwait.observe(r.admit_time - r.arrival_time)
                if mon is not None:
                    mon.note_ttft(r.priority, t_sync - r.arrival_time)
                self._on_first_token(r, t_sync)
                _journal.record("first_token", rid=rid,
                                ttft_s=t_sync - r.arrival_time)
            for rid in ev["finished"]:
                # the engine stamps finish during replay (marginally
                # earlier); the sync is when the client can SEE the
                # tokens, and keeps finish >= first_token by definition
                r = self._reqs[rid]
                r.finish_time = t_sync
                self._finished_count += 1
                m_e2e.observe(t_sync - r.arrival_time)
                if mon is not None:
                    mon.note_e2e(r.priority, t_sync - r.arrival_time)
                self._on_finish(r, t_sync)
                _tracing.emit_request_trace(
                    rid, r.arrival_time, r.admit_time, r.first_token_time,
                    r.finish_time, prefix_hit_len=r.prefix_hit_len)
                # the token-identity ground truth: the FULL emitted
                # stream rides the finish record (host mirrors of the
                # segment fetch — nothing extra was synced for this)
                _journal.record("finish", rid=rid, tokens=r.tokens,
                                n_tokens=len(r.tokens),
                                e2e_s=t_sync - r.arrival_time,
                                priority=r.priority,
                                preemptions=r.preemptions,
                                requeues=r.requeues,
                                spec_proposed=r.spec_proposed,
                                spec_accepted=r.spec_accepted)
            # r14 monitor hooks: advance the SLO burn windows and feed
            # the explained-perf intervals — host ints from the event
            # log just fetched, plus this segment's dispatch→fetch span
            if mon is not None:
                # r17 accept-drift feed (ISSUE 12 satellite): this
                # segment's speculative acceptance rate, from the spec
                # stats the replay already recovered
                sp = ev.get("spec")
                if sp and sp.get("proposed"):
                    mon.note_accept_rate(sp["accepted"] / sp["proposed"])
                mon.end_segment()
            if self.perf_monitor is not None:
                self.perf_monitor.note_segment(
                    ev["steps"], ev.get("tokens", 0),
                    elapsed_s=t_sync - t_seg_pc)
            if cap is not None and eng.paged:
                cap.note_admission(
                    sum(self._reqs[rid].pages_fresh
                        for rid in ev["admitted"]),
                    admitted=len(ev["admitted"]))
                cap.close_segment()
            # r15: per-tick wall EWMA (host arithmetic on already-taken
            # stamps) — the acceptance-aware service estimates' clock
            dt = (t_sync - t_seg_pc) / max(ev["steps"], 1)
            self._per_tick_s = (dt if not self._per_tick_s
                                else 0.5 * self._per_tick_s + 0.5 * dt)
        makespan = _journal.now() - t0

        reqs = list(self._reqs.values())
        assert all(r.done or (self.engine.eos is not None
                              and self.engine.eos in r.tokens)
                   for r in reqs), "scheduler exited with unserved requests"
        total_tokens = sum(len(r.tokens) for r in reqs)
        ttfts = [r.first_token_time - r.arrival_time for r in reqs]
        e2es = [r.finish_time - r.arrival_time for r in reqs]
        qwaits = [r.admit_time - r.arrival_time for r in reqs]
        occupancy = (total_tokens / (eng.last_run_ticks * eng.slots)
                     if eng.last_run_ticks else 0.0)
        _metrics.gauge("serving.slot_occupancy").set(occupancy)
        _metrics.gauge("serving.throughput_tok_s").set(
            total_tokens / makespan if makespan else 0.0)
        return OnlineReport(
            n_requests=len(reqs),
            total_tokens=total_tokens,
            makespan_s=makespan,
            throughput_tok_s=total_tokens / makespan if makespan else 0.0,
            ttft_p50_s=_pctl(ttfts, 0.50),
            ttft_p99_s=_pctl(ttfts, 0.99),
            e2e_p50_s=_pctl(e2es, 0.50),
            e2e_p99_s=_pctl(e2es, 0.99),
            queue_wait_p50_s=_pctl(qwaits, 0.50),
            slot_occupancy=occupancy,
            segments=segments,
            ticks=eng.last_run_ticks,
            backpressure_events=self.backpressure_events,
            backpressure_pages=eng.page_backpressure_events,
            pages=eng.pager.stats() if eng.paged else None,
            prefix=(self.prefix_cache.stats()
                    if self.prefix_cache is not None else None),
            retry_after_s=self.last_retry_after_s,
            cold_start_s=(round(eng.cold_start_s, 4)
                          if eng.cold_start_s is not None else None),
            slo=(self.slo_monitor.report()
                 if self.slo_monitor is not None else None),
            perf=(self.perf_monitor.end_interval()
                  if self.perf_monitor is not None else None),
            capacity=(self.capacity_monitor.report()
                      if self.capacity_monitor is not None else None),
            meter=(_capacity.aggregate_meters(
                reqs,
                ledger=(self.capacity_monitor.ledger
                        if self.capacity_monitor is not None else None),
                page_size=eng.page_size if eng.paged else None)
                if eng.paged else None),
            tiers=(self.prefix_cache.host_tier.stats()
                   if self.prefix_cache is not None
                   and getattr(self.prefix_cache, "host_tier", None)
                   is not None else None),
            **self._report_extras(reqs),
            per_request=[{
                "rid": r.rid,
                "prompt_len": int(len(r.prompt)),
                "gen_len": len(r.tokens),
                "prefix_hit_len": r.prefix_hit_len,
                "priority": r.priority,
                "preemptions": r.preemptions,
                "ttft_s": round(r.first_token_time - r.arrival_time, 4),
                "e2e_s": round(r.finish_time - r.arrival_time, 4),
                # r18 meter: the request's own resource bill
                "pages": r.pages_reserved,
                "page_seconds": round(r.page_seconds, 4),
                "ticks": r.meter_ticks,
                "streams": round(r.meter_streams, 4),
                # r19: the request's tier-transfer bill (0 untiered)
                "tier_pages": r.tier_pages,
                "tier_bytes": r.tier_bytes,
            } for r in reqs],
        )

    def _reset_monitors(self) -> None:
        """Warm-run isolation for the attached monitors: the warm pass
        must not leave alerts/windows behind (the perf monitor's
        self-pinned tick budget deliberately SURVIVES — the warm
        baseline is exactly what the measured run should be judged
        against)."""
        if self.slo_monitor is not None:
            self.slo_monitor.reset()
        if self.perf_monitor is not None:
            self.perf_monitor.end_interval()
        if self.capacity_monitor is not None:
            self.capacity_monitor.reset()

    # --- SLO hooks (no-ops here; SLOScheduler overrides) -----------------
    def _pre_segment(self, now: float, t0: float) -> None:
        pass

    def _on_first_token(self, r: Request, t_sync: float) -> None:
        pass

    def _on_finish(self, r: Request, t_sync: float) -> None:
        pass

    def _report_extras(self, reqs) -> dict:
        return {}

    def _journal_header(self, arrivals) -> dict:
        """The r16 replay contract's root: everything an offline
        ``observability.replay`` needs to rebuild THIS serve — driver
        kind + knobs, engine geometry/seeds, the prefix-cache shape,
        the full arrival trace, and the mutable state decisions start
        from (the per-tick EWMA, the engine's rid offset)."""
        return {
            "driver": "online",
            "scheduler": {"max_queue": self.max_queue,
                          "seg_steps": self.seg_steps,
                          "per_tick_s": self._per_tick_s},
            "engines": [_journal.describe_engine(self.engine)],
            "llama": _journal.describe_config(self.engine.cfg),
            "prefix_cache": _journal.describe_prefix_cache(
                self.prefix_cache),
            "monitors": {"slo": self.slo_monitor is not None,
                         "perf": self.perf_monitor is not None,
                         "capacity": self.capacity_monitor is not None},
            "telemetry_enabled": _metrics.enabled(),
            "trace": _journal.describe_arrivals(arrivals),
        }

    def results(self) -> Dict[int, List[int]]:
        """rid -> generated tokens for every served request (truncated
        at max_new_tokens / first EOS, like ``ServingEngine.run``)."""
        self.engine.collect_finished()
        return {rid: r.tokens for rid, r in self._reqs.items()}


class SLOScheduler(OnlineScheduler):
    """``OnlineScheduler`` with the r13 overload control plane (ISSUE 8b):
    priority classes, preempt-and-requeue, and deadline load-shedding.

    * **Priority admission.** The intake queue is kept ordered by
      (priority, engine rid) — class 0 ahead of class 1, FCFS within a
      class — so the engine's FCFS segment pick IS priority scheduling.
      A preempted request re-enters at the head of its class (it keeps
      its original rid).
    * **Preempt-and-requeue.** Before each segment, if the queue head
      outranks a running request and admission is blocked (no free slot,
      or — paged — not enough free pages), the lowest-priority running
      slot is preempted via ``ServingEngine.preempt_slot``: its pages
      are parked in the prefix cache by reference (or freed), the
      request requeues with its generated prefix, and the eventual
      resume is a page-ref bump + suffix prefill. Never same-class:
      FCFS fairness holds within a priority level.
    * **Deadline load-shedding.** A queued request whose e2e deadline is
      already unmeetable — now plus a MEASURED minimum service estimate
      (EWMA seconds/token from finished requests x tokens owed) exceeds
      it — is shed instead of served late: removed from the queue,
      counted per class, never billed into the latency percentiles.
      The estimate deliberately excludes queueing (an underestimate),
      so shedding only fires on requests that could not make it even
      with an empty machine.

    Per-class TTFT/e2e histograms land in ``request.ttft[class<p>]`` /
    ``request.e2e[class<p>]``; shed/preempt counters in
    ``scheduler.shed[class<p>]`` / ``scheduler.preemptions``. All of it
    is host bookkeeping between segments — the audited one-fetch-per-
    segment contract is untouched (tests/test_slo_serving.py pins it).
    """

    def __init__(self, engine: ServingEngine, max_queue: int = 64,
                 seg_steps: int = 32,
                 prefix_cache: Optional[PrefixCache] = None,
                 preempt: bool = True, shed_deadlines: bool = True,
                 slo_monitor=None, perf_monitor=None,
                 capacity_monitor=None):
        super().__init__(engine, max_queue=max_queue, seg_steps=seg_steps,
                         prefix_cache=prefix_cache,
                         slo_monitor=slo_monitor,
                         perf_monitor=perf_monitor,
                         capacity_monitor=capacity_monitor)
        self.preempt = bool(preempt)
        self.shed_deadlines = bool(shed_deadlines)
        self.preemptions = 0
        self.shed_count = 0
        self.shed_per_class: Dict[int, int] = {}
        self.shed_log: List[dict] = []
        self.displaced = 0            # queue-level class displacements
        self._arrivals: Dict[int, Arrival] = {}   # rid -> its Arrival
        self._per_token_s = 0.0       # EWMA decode seconds/token

    # --- class-ordered queue ---------------------------------------------
    def _insert_by_class(self, r: Request) -> None:
        """(Re)insert into the engine queue at its class position:
        ordered by (priority, rid) — rid is assignment-ordered, so a
        preempted request's ORIGINAL rid lands it ahead of everything
        that arrived after it in the same class."""
        q = self.engine._queue
        key = (r.priority, r.rid)
        lo = 0
        while lo < len(q) and (q[lo].priority, q[lo].rid) < key:
            lo += 1
        q.insert(lo, r)

    def _note_arrival(self, r: Request, a: Arrival) -> None:
        r.priority = int(getattr(a, "priority", 0))
        dls = getattr(a, "deadline_s", None)
        r.deadline = r.arrival_time + dls if dls else 0.0
        self._arrivals[r.rid] = a
        # _ingest appended at the tail; move to the class position
        assert self.engine._queue[-1] is r
        self.engine._queue.pop()
        self._insert_by_class(r)

    def _ingest(self, pending: List[Arrival], now: float, t0: float) -> int:
        """Class-aware admission control (the SLO twist on the bounded
        queue): the base scheduler's intake is strictly FIFO — a refused
        arrival blocks the whole client stream, so under overload a
        high-priority request queues CLIENT-SIDE behind backpressured
        batch traffic and its TTFT rides the overload it was supposed to
        be insulated from. Here a full queue (1) refuses only the
        arrival itself, not everything behind it (due arrivals are
        scanned past a refusal), and (2) yields to a HIGHER class by
        displacement: the worst queued request (lowest class, latest
        rid) is bumped back client-side — it was only queued, so nothing
        is lost and its deadline/arrival accounting carries over — and
        the high-class arrival takes its place."""
        refused = 0
        i = 0
        while i < len(pending) and pending[i].t <= now:
            a = pending[i]
            q = self.engine._queue
            displaced_arrival = None
            if len(q) >= self.max_queue:
                victim = max(q, key=lambda r: (r.priority, r.rid))
                if int(getattr(a, "priority", 0)) < victim.priority:
                    q.remove(victim)
                    del self._reqs[victim.rid]
                    displaced_arrival = self._arrivals.pop(victim.rid)
                    self.displaced += 1
                    _metrics.counter("scheduler.displaced").inc()
                    _flight.record("displaced", rid=victim.rid,
                                   cls=victim.priority,
                                   by_cls=int(getattr(a, "priority", 0)))
                else:
                    refused += 1
                    i += 1
                    continue
            # admit ``a``: POP FIRST, reinsert the displaced arrival
            # after — inserting before the pop shifts the index and a
            # stale element gets popped (the arrival would then be
            # admitted twice)
            pending.pop(i)
            if displaced_arrival is not None:
                j = 0
                while (j < len(pending)
                       and pending[j].t <= displaced_arrival.t):
                    j += 1
                pending.insert(j, displaced_arrival)
                if j <= i:
                    i += 1     # keep scanning from the same arrival
            rid = self.engine.add_request(a.prompt, a.max_new_tokens)
            r = self.engine._queue[-1]
            assert r.rid == rid
            r.arrival_time = t0 + a.t
            self._reqs[rid] = r
            self._note_arrival(r, a)
            _journal.record("arrival", rid=rid, at=a.t,
                            priority=r.priority,
                            deadline_s=getattr(a, "deadline_s", None),
                            prompt_len=len(r.prompt),
                            gen=r.max_new_tokens)
        if refused:
            hint = self.retry_after_hint(now)
            self.last_retry_after_s = hint
            self.backpressure_events += 1
            _metrics.counter("serving.backpressure_events").inc()
            _metrics.gauge("serving.retry_after_s").set(hint)
            _flight.record("backpressure", refused=refused,
                           queue=len(self.engine._queue),
                           retry_after_s=round(hint, 4))
        return refused

    # --- the control plane (runs between segments, host-only) -----------
    def _pre_segment(self, now: float, t0: float) -> None:
        if self.shed_deadlines:
            self._shed_pass()
        if self.preempt:
            self._preempt_pass()

    def _min_service_s(self, r: Request) -> float:
        """Lower bound on time to FINISH ``r`` from a standing start:
        tokens owed x the measured per-token EWMA (0.0 until the first
        finish — before any measurement only an already-expired
        deadline sheds).

        r15 (ISSUE 10 satellite): on a SPECULATIVE engine each verify
        tick retires ~``spec_accept_ewma`` tokens, so remaining work is
        owed/accept ticks priced at the measured per-tick EWMA — the
        old one-token-per-tick arithmetic over-estimates service time
        by the acceptance factor and sheds requests that would have
        finished comfortably inside their deadlines."""
        owed = r.max_new_tokens - len(r.tokens)
        if getattr(self.engine, "speculative", 0):
            accept = max(float(self.engine.spec_accept_ewma), 1.0)
            per_tick = self._per_tick_s or self._per_token_s
            return owed / accept * per_tick
        return owed * self._per_token_s

    def _shed_pass(self) -> None:
        t_abs = _journal.now()
        eng = self.engine
        for r in [q for q in eng._queue if q.deadline]:
            min_s = self._min_service_s(r)
            if t_abs + min_s <= r.deadline:
                continue
            eng._queue.remove(r)
            del self._reqs[r.rid]
            self.shed_count += 1
            self.shed_per_class[r.priority] = \
                self.shed_per_class.get(r.priority, 0) + 1
            self.shed_log.append({
                "rid": r.rid, "priority": r.priority,
                "late_by_s": round(t_abs + min_s - r.deadline, 4),
                "tokens_done": len(r.tokens)})
            _metrics.counter("scheduler.shed").inc()
            _metrics.counter(f"scheduler.shed[class{r.priority}]").inc()
            # r16: the decision WITH its arithmetic inputs — a
            # postmortem can re-derive exactly why this request died
            # (measured EWMAs x owed tokens vs the deadline), and the
            # replay must reproduce every term bit-for-bit
            _journal.record("shed_decision", rid=r.rid,
                            priority=r.priority, now_abs=t_abs,
                            deadline_abs=r.deadline,
                            min_service_s=min_s,
                            late_by_s=t_abs + min_s - r.deadline,
                            owed=r.max_new_tokens - len(r.tokens),
                            per_token_s=self._per_token_s,
                            per_tick_s=self._per_tick_s,
                            accept_ewma=float(getattr(
                                self.engine, "spec_accept_ewma", 1.0)),
                            tokens_done=len(r.tokens))
            _flight.record("shed", rid=r.rid, cls=r.priority,
                           queue=len(eng._queue))

    def _head_admissible(self, head: Request) -> bool:
        """Could the queue head be admitted right now without evicting
        anyone? Slots are the resource on a contiguous engine; pages on
        a paged one (a conservative full-need check — prefix hits only
        reduce it)."""
        eng = self.engine
        if eng.free_slot_count() == 0:
            return False
        if not eng.paged:
            return True
        fp, remaining = head.resume_view()
        need = eng.pager.pages_needed(len(fp) + remaining - 1)
        return need <= eng.pager.pages_free

    def _preempt_pass(self) -> None:
        eng = self.engine
        if not eng._queue:
            return
        head = eng._queue[0]          # highest class, earliest rid
        # victims: strictly LOWER class than the blocked head, worst
        # class first, least progress first (least work discarded)
        victims = sorted(
            (s for s, r in enumerate(eng._active)
             if r is not None and r.priority > head.priority),
            key=lambda s: (-eng._active[s].priority,
                           len(eng._active[s].tokens)))
        for s in victims:
            if self._head_admissible(head):
                return
            if not eng.can_preempt(s):
                continue
            # r16: victim selection with its inputs — who was blocked,
            # who was considered (class/progress ranking), who lost
            _journal.record(
                "preempt_decision", rid=eng._active[s].rid,
                victim_slot=s, victim_priority=eng._active[s].priority,
                victim_tokens_done=len(eng._active[s].tokens),
                head_rid=head.rid, head_priority=head.priority,
                considered=[(v, eng._active[v].rid,
                             eng._active[v].priority,
                             len(eng._active[v].tokens))
                            for v in victims
                            if eng._active[v] is not None])
            victim = eng.preempt_slot(s, prefix_cache=self.prefix_cache)
            self._insert_by_class(victim)
            self.preemptions += 1
            _metrics.counter("scheduler.preemptions").inc()

    # --- per-class telemetry / report ------------------------------------
    def _on_first_token(self, r: Request, t_sync: float) -> None:
        _metrics.histogram(f"request.ttft[class{r.priority}]").observe(
            t_sync - r.arrival_time)

    def _on_finish(self, r: Request, t_sync: float) -> None:
        _metrics.histogram(f"request.e2e[class{r.priority}]").observe(
            t_sync - r.arrival_time)
        if r.first_token_time and r.tokens:
            per_tok = ((t_sync - r.admit_time) / len(r.tokens)
                       if r.admit_time else 0.0)
            if per_tok > 0:
                self._per_token_s = (per_tok if not self._per_token_s
                                     else 0.5 * self._per_token_s
                                     + 0.5 * per_tok)

    def _report_extras(self, reqs) -> dict:
        per_class: Dict[int, dict] = {}
        for p in sorted({r.priority for r in reqs}):
            rs = [r for r in reqs if r.priority == p]
            ttfts = [r.first_token_time - r.arrival_time for r in rs]
            e2es = [r.finish_time - r.arrival_time for r in rs]
            per_class[p] = {
                "n": len(rs),
                "ttft_p50_s": round(_pctl(ttfts, 0.50), 4),
                "ttft_p99_s": round(_pctl(ttfts, 0.99), 4),
                "e2e_p50_s": round(_pctl(e2es, 0.50), 4),
                "e2e_p99_s": round(_pctl(e2es, 0.99), 4),
                "preemptions": sum(r.preemptions for r in rs),
                "shed": self.shed_per_class.get(p, 0),
            }
        return {"preemptions": self.preemptions,
                "shed": self.shed_count,
                "shed_per_class": dict(self.shed_per_class) or None,
                "displaced": self.displaced,
                "per_class": per_class or None}

    def _journal_header(self, arrivals) -> dict:
        d = super()._journal_header(arrivals)
        d["driver"] = "slo"
        # the shed estimator's measured state: decisions in the first
        # segments depend on what a warm pass (or earlier traffic)
        # taught the EWMAs — a replay must start from the same numbers
        d["scheduler"].update(preempt=self.preempt,
                              shed_deadlines=self.shed_deadlines,
                              per_token_s=self._per_token_s)
        return d

    def serve(self, arrivals: Sequence[Arrival],
              warm: bool = False) -> OnlineReport:
        if warm:
            # the base warm pass resets engine/prefix state; the SLO
            # counters must reset with it or the measured report counts
            # warm-pass sheds/preemptions
            self.serve(arrivals, warm=False)
            self.engine.reset_slots()
            self._reqs.clear()
            self.backpressure_events = 0
            if self.prefix_cache is not None:
                self.prefix_cache.reset()
            self.preemptions = 0
            self.shed_count = 0
            self.shed_per_class = {}
            self.shed_log = []
            self.displaced = 0
            self._arrivals.clear()
            self._reset_monitors()
            return super().serve(arrivals, warm=False)
        return super().serve(arrivals, warm=False)

"""Program auditor (ISSUE 4): seeded known-bad fixtures per pass — each
hazard class the analyzer exists to catch is reconstructed in miniature
and must be FLAGGED (zero false negatives on this corpus), with a clean
twin asserting no false positive — plus the tier-1 budget gate over the
four canonical programs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import budgets, hlo, recompile, syncs


# ---------------------------------------------------------------------------
# pass 1: host-sync detector
# ---------------------------------------------------------------------------


class TestHostSyncDetector:
    def test_hidden_bool_sync_flagged(self):
        """The GradScaler bug class: a per-iteration ``bool()`` on a
        device value inside a host loop."""
        x = paddle.to_tensor(np.ones(8, np.float32))
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            for _ in range(3):
                if x.sum() > 0:        # hidden device->host sync
                    pass
        flagged = sa.flagged("replay")
        assert len(flagged) == 3
        assert flagged[0].kind == "tensor.bool"
        assert "test_analysis.py" in flagged[0].site

    def test_item_and_numpy_flagged(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            _ = x.numpy()
            _ = float(x.sum())
        kinds = [e.kind for e in sa.flagged("replay")]
        assert "tensor.numpy" in kinds and "tensor.float" in kinds

    def test_raw_array_and_device_get_flagged(self):
        """Syncs that bypass the framework Tensor (serving's event fetch
        pattern) are still seen via the jax-level patches."""
        v = jnp.arange(8)
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            _ = int(v[0])
            _ = jax.device_get(v)
        kinds = {e.kind for e in sa.flagged("replay")}
        assert "device_get" in kinds
        assert any(k.startswith("array.") for k in kinds)

    def test_allowed_sync_not_flagged(self):
        x = paddle.to_tensor(np.ones(4, np.float32))
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            with syncs.allowed_sync("test.intended_fetch"):
                _ = float(x.sum())
        assert sa.flagged("replay") == []
        assert sa.allowed("replay") == {"test.intended_fetch": 1}

    def test_clean_device_loop_negative(self):
        """A pure device loop (no coercion) records nothing."""
        f = jax.jit(lambda a: a * 2 + 1)
        v = jnp.ones(16)
        f(v)  # warm outside the audit
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            for _ in range(4):
                v = f(v)
        assert sa.flagged("replay") == []

    def test_one_coercion_one_event(self):
        """bool() -> item() -> __array__ nests: exactly ONE event."""
        x = paddle.to_tensor(np.ones((), np.float32))
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            bool(x)
        assert len(sa.events) == 1

    def test_patches_removed_after_audit(self):
        import jax as j

        with syncs.SyncAudit():
            pass
        assert not syncs._ORIG  # originals restored
        assert j.device_get.__module__ != "paddle_tpu.analysis.syncs"

    def test_grad_scaler_single_allowed_sync(self):
        """The r8 fix, enforced: unscale_ makes exactly ONE allowed
        finite-check sync for the whole parameter list — not one bool()
        per parameter."""
        params = [paddle.nn.Parameter(jnp.ones((8, 8), jnp.float32))
                  for _ in range(12)]
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=params)
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
        for p in params:
            p.grad = paddle.to_tensor(np.ones((8, 8), np.float32))
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            scaler.unscale_(opt)
        assert sa.flagged("replay") == []
        assert sa.allowed("replay") == {"amp.grad_scaler.finite_check": 1}


# ---------------------------------------------------------------------------
# pass 2: recompile-hazard lint
# ---------------------------------------------------------------------------


class TestRecompileLint:
    def test_unbucketed_shape_fn_flagged(self):
        """A jit fn replayed over free-floating widths compiles once per
        width — the 2.5 s mid-serve class."""

        @paddle.jit.to_static
        def f(x):
            return x * 2

        with recompile.CompileWatch() as cw:
            for w in (3, 5, 7, 9, 11, 13):   # unbucketed dynamic dim
                f(paddle.to_tensor(np.ones((w,), np.float32)))
        assert cw.compiles >= 6
        lint = recompile.lint_cache_keys(**{
            "name": "fixture", "keys": f.cache_info()["keys"]})
        assert lint.hazard
        assert lint.n_shape_variants == 6
        assert "unbucketed" in lint.detail

    def test_bucketed_fn_negative(self):
        """Bucketed replay (two widths, many calls) stays under the
        variant bound and a warm replay compiles nothing."""

        @paddle.jit.to_static
        def g(x):
            return x + 1

        for w in (8, 16, 8, 16, 8, 16):
            g(paddle.to_tensor(np.ones((w,), np.float32)))
        with recompile.CompileWatch() as cw:
            for w in (8, 16, 8, 16):
                g(paddle.to_tensor(np.ones((w,), np.float32)))
        assert cw.compiles == 0
        lint = recompile.lint_cache_keys("fixture",
                                         g.cache_info()["keys"])
        assert not lint.hazard

    def test_live_cache_registry_sees_programs(self):
        @paddle.jit.to_static
        def h(x):
            return x - 1

        h(paddle.to_tensor(np.ones((4,), np.float32)))
        names = [r.name for r in recompile.live_cache_report()]
        assert any(n.startswith("to_static:") for n in names)


# ---------------------------------------------------------------------------
# pass 3: relayout accounting
# ---------------------------------------------------------------------------


class TestRelayoutAccounting:
    def test_stack_unstack_relayout_flagged(self):
        """The r8 ledger fixture: transpose forced to materialise (a
        concatenate consumes both orientations)."""

        def f(a):
            return jnp.concatenate([a.T, a], 0)

        txt = jax.jit(f).lower(jnp.ones((64, 64))).compile().as_text()
        inv = hlo.relayout_inventory(txt)
        assert any(e.op == "transpose" for e in inv)
        # 64x64 f32 = 16 KiB transposed (+ the layout-restoring copy)
        assert hlo.relayout_bytes(txt) >= 16384

    def test_elementwise_program_negative(self):
        txt = jax.jit(lambda a: a * 2 + 1).lower(
            jnp.ones((128, 128))).compile().as_text()
        assert hlo.relayout_bytes(txt) == 0

    def test_pack_class_counted_outside_fusions(self):
        inv = hlo.relayout_inventory(
            "ENTRY %main (p0: f32[4,8]) -> f32[8,8] {\n"
            "  %p0 = f32[4,8]{1,0} parameter(0)\n"
            "  ROOT %concatenate.1 = f32[8,8]{1,0} concatenate("
            "f32[4,8]{1,0} %p0, f32[4,8]{1,0} %p0), dimensions={0}\n"
            "}\n")
        assert [e.klass for e in inv] == ["pack"]
        assert inv[0].bytes == 8 * 8 * 4


# ---------------------------------------------------------------------------
# pass 4: donation / aliasing audit
# ---------------------------------------------------------------------------


class TestDonationAudit:
    def test_undonated_buffer_flagged(self):
        """A large param updated without donation: HBM holds input and
        output copies."""
        f = jax.jit(lambda a: a + 1)          # no donate_argnums
        txt = f.lower(jnp.ones((512, 512))).compile().as_text()
        rep = hlo.donation_report(txt, threshold=1 << 18)
        assert len(rep.large_undonated) == 1
        assert rep.large_undonated[0].bytes == 512 * 512 * 4

    def test_donated_buffer_negative(self):
        f = jax.jit(lambda a: a + 1, donate_argnums=(0,))
        txt = f.lower(jnp.ones((512, 512))).compile().as_text()
        rep = hlo.donation_report(txt, threshold=1 << 18)
        assert rep.large_undonated == []
        assert rep.donated_bytes == 512 * 512 * 4

    def test_expected_undonated_excused(self):
        f = jax.jit(lambda a: a + 1)
        txt = f.lower(jnp.ones((512, 512))).compile().as_text()
        rep = hlo.donation_report(txt, threshold=1 << 18,
                                  expected_undonated=("Arg_0",))
        assert rep.large_undonated == []


# ---------------------------------------------------------------------------
# pass 5: collective / mesh audit
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual mesh")
class TestCollectiveAudit:
    def _mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("dp", "mp"))

    def test_matched_axis_collective_negative(self):
        from functools import partial

        from paddle_tpu.parallel.mesh import shard_map_compat as smap

        mesh = self._mesh()
        f = partial(jax.lax.psum, axis_name="mp")
        g = smap(f, mesh=mesh, in_specs=jax.sharding.PartitionSpec("mp"),
                 out_specs=jax.sharding.PartitionSpec())
        txt = jax.jit(g).lower(jnp.ones((8, 64))).compile().as_text()
        chk = hlo.collective_check(txt, mesh, allowed_axes=("mp",))
        assert chk.inventory, "psum must lower to a collective"
        assert chk.ok

    def test_mismatched_axis_collective_flagged(self):
        """The seeded bad fixture: the program declares its collectives
        ride 'mp' but the psum actually spans 'dp' — the audit must
        refuse the axis set."""
        from functools import partial

        from paddle_tpu.parallel.mesh import shard_map_compat as smap

        mesh = self._mesh()
        f = partial(jax.lax.psum, axis_name="dp")
        g = smap(f, mesh=mesh, in_specs=jax.sharding.PartitionSpec("dp"),
                 out_specs=jax.sharding.PartitionSpec())
        txt = jax.jit(g).lower(jnp.ones((8, 64))).compile().as_text()
        chk = hlo.collective_check(txt, mesh, allowed_axes=("mp",))
        assert chk.disallowed_axes, "dp traffic must violate an mp-only "\
            "declaration"
        assert not chk.ok


# ---------------------------------------------------------------------------
# the canonical programs + budget gate (tier-1 enforcement)
# ---------------------------------------------------------------------------


class TestBudgetGate:
    def test_gate_canonical_programs_within_budget(self):
        """THE tier-1 smoke gate: every canonical program (eight as of
        r15, incl. the mp-sharded tp_serving_segment, the chunked-
        prefill chunked_serving_segment and the speculative
        spec_serving_segment) audits clean against its pinned budget —
        a reintroduced host sync, stray shape compile, new relayout,
        dropped donation, or off-axis collective fails here."""
        from paddle_tpu.analysis.__main__ import main

        assert main(["--gate"]) == 0

    def test_budget_check_catches_regression(self):
        """A synthetic report over budget produces violations (the gate
        actually bites)."""
        rep = analysis.AuditReport(program="decode_tick")
        rep.metrics.update(host_syncs_flagged=1, warm_compiles=2,
                           relayout_bytes=10 << 20, replays=2,
                           host_syncs_allowed={})
        v = budgets.check(rep)
        assert any("host_syncs_flagged" in s for s in v)
        assert any("warm_compiles" in s for s in v)
        assert any("relayout_bytes" in s for s in v)

    def test_unknown_allowed_label_is_violation(self):
        rep = analysis.AuditReport(program="decode_tick")
        rep.metrics.update(host_syncs_flagged=0, warm_compiles=0,
                           replays=2,
                           host_syncs_allowed={"rogue.label": 4})
        v = budgets.check(rep)
        assert any("rogue.label" in s for s in v)


class TestSchedulerAudit:
    def test_online_serve_loop_syncs(self):
        """Satellite 1: the auditor over the ONLINE serve loop. Per
        segment the loop may sync exactly once (the event fetch); the
        host replay, telemetry stamping and queue management must not
        touch the device."""
        from paddle_tpu.inference.scheduler import (OnlineScheduler,
                                                    staggered_arrivals)
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        eng = ServingEngine(cfg, llama.init_params(cfg), slots=4,
                            max_len=64, chunk=8, prompt_buckets=(16,))
        sched = OnlineScheduler(eng, seg_steps=16)
        arrivals = staggered_arrivals(0, 6, 0.01, cfg.vocab_size,
                                      prompt_lens=(8, 12), gen_lens=(4, 6))
        sched.serve(arrivals)          # warm: compiles + first fetches
        eng.reset_slots()
        sched._reqs.clear()
        with syncs.SyncAudit() as sa:
            sa.phase = "replay"
            report = sched.serve(arrivals)
        assert report.n_requests == 6
        flagged = sa.flagged("replay")
        assert flagged == [], [f"{e.kind}@{e.site}" for e in flagged]
        allowed = sa.allowed("replay")
        assert set(allowed) == {"serving.segment_event_fetch"}
        assert allowed["serving.segment_event_fetch"] == report.segments

    def test_engine_cache_keys_bucketed(self):
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        eng = ServingEngine(cfg, llama.init_params(cfg), slots=4,
                            max_len=64, chunk=8, prompt_buckets=(16,))
        for _ in range(2):
            eng.add_request(np.arange(8, dtype=np.int32) % cfg.vocab_size,
                            3)
            eng.run_segment(8)
        lint = recompile.lint_cache_keys(**eng.cache_info())
        assert not lint.hazard

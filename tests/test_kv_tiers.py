"""Tiered KV memory (r19 tentpole, ISSUE 14): host-RAM page spill
behind the paged prefix cache + the fleet-global cache directory.

Pins the subsystem's contracts:

* spill→restore token identity vs an un-spilled reference serve, with
  the unified ``prefix_evict``-with-reason eviction path;
* the staging contract — D2H stage rides the per-segment event fetch
  (SyncAudit over the tiered loop: flagged == [], allowed == segment
  fetches EXACTLY) and restore is a dispatch;
* host-tier pages as the capacity plane's second availability axis
  (``reclaimable_pages(tier=...)`` + CapacityMonitor ``avail_by_tier``);
* directory steering (a hot prefix's owner takes repeat traffic;
  migration-on-miss imports host bytes instead of recomputing) and the
  journaled dispatch candidates' directory-hit info;
* journal replay identity of a spill-heavy serve (tier_transfer is a
  diffed decision kind);
* the analysis.tiers budget pass (bytes/request <= KV size).

Suite-time contract: everything rides the session ``tiny_llama``
fixture, one module-scoped spill-heavy recorded serve, and the same
engine geometries as tests/test_capacity.py so ``serving._SHARED_PROGS``
serves the compiles.
"""

import numpy as np
import pytest

from paddle_tpu.inference.kv_tiers import HostTier, TierMeter, page_bytes
from paddle_tpu.inference.prefix_cache import PagedPrefixCache
from paddle_tpu.inference.scheduler import Arrival, OnlineScheduler
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.observability import flight, journal, replay_serve
from paddle_tpu.parallel import set_mesh


@pytest.fixture(scope="module")
def tiny(tiny_llama):
    set_mesh(None)
    return tiny_llama


def _mk(cfg, params, tiered=True, num_pages=11, host_pages=64, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("prompt_buckets", (8, 16, 32, 64))
    eng = ServingEngine(cfg, params, paged=True, page_size=16,
                        num_pages=num_pages, **kw)
    tier = HostTier(eng.pager, capacity_pages=host_pages) if tiered \
        else None
    pc = PagedPrefixCache(eng.pager, capacity_pages=8, host_tier=tier)
    return eng, pc


def _tenant_trace(cfg, seed=7, tenants=4, rounds=2, gen=24):
    """Round-robin multi-tenant trace whose 2-page prefixes working set
    (tenants x 2 pages + live spans) overflows the tight 10-page pool —
    the spill-heavy shape."""
    rng = np.random.RandomState(seed)
    prefs = [rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
             for _ in range(tenants)]
    out = []
    for r in range(rounds):
        for t in range(tenants):
            tail = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
            out.append(Arrival(0.0, np.concatenate([prefs[t], tail]), gen))
    return out


# ---------------------------------------------------------------------------
# module-scoped spill-heavy recorded serve (single compile+serve cost,
# read by the identity / replay / audit / report tests)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def spill_serve(tiny):
    cfg, params = tiny
    arr = _tenant_trace(cfg)
    flight.clear()
    eng, pc = _mk(cfg, params)
    sch = OnlineScheduler(eng, seg_steps=12, prefix_cache=pc)
    j = journal.Journal()                 # in-memory
    with journal.attach(j):
        rep = sch.serve(arr)
    results = sch.results()
    reqs = list(sch._reqs.values())
    events = flight.events()
    # un-spilled reference: same trace, same geometry, NO cache at all
    # (prefix reuse off — the token-identity oracle)
    eng_ref = ServingEngine(cfg, params, slots=2, max_len=96,
                            prompt_buckets=(8, 16, 32, 64), paged=True,
                            page_size=16, num_pages=11)
    sch_ref = OnlineScheduler(eng_ref, seg_steps=12)
    sch_ref.serve(arr)
    return {"arr": arr, "eng": eng, "pc": pc, "sch": sch, "rep": rep,
            "results": results, "reqs": reqs, "events": events,
            "journal": j, "ref_results": sch_ref.results(),
            "params": params}


class TestSpillRestore:
    def test_spill_heavy_and_token_identical(self, spill_serve):
        """The tentpole identity: the tiered serve actually spilled and
        restored (working set 3x the pool forces the tier to carry the
        prefixes), and every request's tokens are identical to the
        un-spilled no-cache reference serve."""
        pc = spill_serve["pc"]
        assert pc.spills > 0, "trace never spilled — pool not tight"
        assert pc.restores > 0, "no restore-on-hit happened"
        assert pc.hits > 0
        assert spill_serve["results"] == spill_serve["ref_results"]

    def test_tiered_beats_hbm_only_hit_rate(self, spill_serve, tiny):
        """The capacity lever: on the same trace the HBM-only cache
        (entries die on pressure) reuses NOTHING, the tiered cache
        serves every repeat round from spilled prefixes."""
        cfg, params = tiny
        eng, pc = _mk(cfg, params, tiered=False)
        sch = OnlineScheduler(eng, seg_steps=12, prefix_cache=pc)
        sch.serve(spill_serve["arr"])
        assert sch.results() == spill_serve["ref_results"]
        assert spill_serve["pc"].hit_tokens > pc.hit_tokens

    def test_eviction_reasons_unified(self, spill_serve):
        """The r19 small fix: every eviction emits ``prefix_evict``
        with a reason; the spill-heavy serve demotes (reason=spill)
        instead of dropping, and stage/spill/restore all leave
        tier_transfer events with byte counts."""
        evs = spill_serve["events"]
        reasons = {e.get("reason") for e in evs
                   if e["kind"] == "prefix_evict"}
        assert reasons and reasons <= {"capacity", "pressure", "spill",
                                       "subsumed", "reset"}
        assert "spill" in reasons
        tt = [e for e in evs if e["kind"] == "tier_transfer"]
        dirs = {e["direction"] for e in tt}
        assert {"stage", "spill", "restore"} <= dirs
        assert all(e["bytes"] % 1 == 0 and e["pages"] >= 0 for e in tt)
        pb = spill_serve["pc"].host_tier.page_bytes()
        for e in tt:
            if e["direction"] in ("stage", "restore", "import"):
                assert e["bytes"] == e["pages"] * pb

    def test_tier_budget_audit(self, spill_serve):
        """analysis.tiers: bytes-migrated/request <= the request's own
        KV size, and the tier's conservation identities hold."""
        from paddle_tpu.analysis import tiered_serve_audit

        tier = spill_serve["pc"].host_tier
        assert tiered_serve_audit(spill_serve["reqs"], tier) == []
        billed = [r for r in spill_serve["reqs"] if r.tier_bytes]
        assert billed, "no request was billed a restore"
        pb = tier.page_bytes()
        for r in billed:
            assert r.tier_bytes <= r.pages_reserved * pb

    def test_report_sections(self, spill_serve):
        rep = spill_serve["rep"]
        assert rep.tiers is not None
        assert rep.tiers["spills"] == spill_serve["pc"].host_tier.spills
        assert rep.prefix["spills"] == spill_serve["pc"].spills
        rows = rep.per_request
        assert any(row["tier_bytes"] > 0 for row in rows)

    def test_journal_replay_identity_spill_heavy(self, spill_serve):
        """The black-box bar: the spill-heavy serve's decision stream
        — tier_transfer records included — replays bit-exactly."""
        recs = spill_serve["journal"].records()
        assert any(r["kind"] == "tier_transfer" for r in recs)
        res = replay_serve(recs, params=spill_serve["params"])
        assert res.identical, (res.divergence, res.error)

    def test_pool_drains_clean_after_cycles(self, spill_serve):
        """Leak audit after spill/restore cycles: host pages are not
        pool pages; clearing the cache returns everything."""
        pc, eng = spill_serve["pc"], spill_serve["eng"]
        pc.clear()
        assert eng.pager.leak_report() == []
        assert pc.pages_held == 0


# ---------------------------------------------------------------------------
# the audited sync contract over the tiered loop
# ---------------------------------------------------------------------------


class TestTieredSyncAudit:
    def test_tiered_serve_one_fetch_per_segment(self, tiny):
        """flagged == [], allowed == segment fetches EXACTLY: the D2H
        staging rides the per-segment event fetch (no extra allowed
        label, no extra count) and restores are dispatches."""
        from paddle_tpu.analysis import SyncAudit

        cfg, params = tiny
        arr = _tenant_trace(cfg, seed=13)
        eng, pc = _mk(cfg, params)
        sch = OnlineScheduler(eng, seg_steps=12, prefix_cache=pc)
        sch.serve(arr)                   # warm (compiles outside audit)
        sch.results()
        eng.reset_slots()
        pc.reset()
        sch._reqs.clear()
        with SyncAudit() as audit:
            audit.phase = "serve"
            rep = sch.serve(arr)
        assert audit.flagged("serve") == [], audit.flagged("serve")
        assert audit.allowed("serve") == {
            "serving.segment_event_fetch": rep.segments}
        assert pc.spills > 0 and pc.restores > 0  # the loop WAS tiered


# ---------------------------------------------------------------------------
# capacity plane: the tier dimension
# ---------------------------------------------------------------------------


class TestTierCapacity:
    def test_reclaimable_tier_dimension(self, tiny):
        cfg, params = tiny
        eng, pc = _mk(cfg, params, num_pages=21)
        rng = np.random.RandomState(5)
        p = rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
        eng.add_request(p, 4)
        while eng._queue or eng.free_slot_count() < eng.slots:
            eng.run_segment(16, prefix_cache=pc)
        eng.collect_finished()
        held = pc.pages_held
        assert held > 0
        assert pc.reclaimable_pages() == held
        assert pc.reclaimable_pages(tier="host") == 0   # not yet staged
        # one more segment boundary materialises the stage; spill all
        eng.add_request(p[:8], 2)
        while eng._queue or eng.free_slot_count() < eng.slots:
            eng.run_segment(16, prefix_cache=pc)
        eng.collect_finished()
        assert pc.spillable_pages() > 0                 # clean now
        pc.evict_until(eng.pager.num_pages)             # spill everything
        assert pc.pages_held < held or pc.spills > 0
        assert pc.reclaimable_pages(tier="host") == pc.host_pages > 0
        assert pc.reclaimable_pages(tier="all") == \
            pc.reclaimable_pages() + pc.host_pages
        pc.clear()
        assert eng.pager.leak_report() == []

    def test_capacity_monitor_avail_by_tier(self):
        from paddle_tpu.observability import CapacityMonitor

        cap = CapacityMonitor()
        cap.begin_segment(10, 4, host_pages=20)
        rec = cap.report()
        assert rec["avail_pages"] == 14                 # hbm term unchanged
        assert rec["avail_by_tier"] == {"hbm": 14, "host": 20}
        cap.begin_segment(8, 2)                         # host term sticky
        assert cap.report()["avail_by_tier"]["host"] == 20
        cap.reset()
        assert cap.report()["avail_by_tier"]["host"] is None

    def test_scheduler_feeds_host_dimension(self, spill_serve):
        """The monitored tiered serve reports the host axis (wired in
        OnlineScheduler.begin_segment)."""
        from paddle_tpu.observability import CapacityMonitor

        cfg_rep = spill_serve["rep"]
        assert cfg_rep.tiers["pages_host"] >= 0
        # direct wiring check on a short serve
        eng, pc = _mk(spill_serve["eng"].cfg, spill_serve["params"])
        cap = CapacityMonitor()
        sch = OnlineScheduler(eng, seg_steps=12, prefix_cache=pc,
                              capacity_monitor=cap)
        sch.serve(_tenant_trace(eng.cfg, seed=23, tenants=2, rounds=2))
        assert cap.report()["avail_by_tier"]["host"] is not None


# ---------------------------------------------------------------------------
# fleet directory: steering + migration-on-miss
# ---------------------------------------------------------------------------


def _fleet(cfg, params, n=2):
    from paddle_tpu.inference.fleet import FleetRouter, build_fleet

    engines = build_fleet(cfg, params, n, slots=2, max_len=96,
                          prompt_buckets=(8, 16, 32), paged=True,
                          page_size=16)
    pcs = [PagedPrefixCache(e.pager, capacity_pages=16,
                            host_tier=HostTier(e.pager,
                                               capacity_pages=64))
           for e in engines]
    return FleetRouter(engines, seg_steps=16, prefix_caches=pcs,
                       directory=True)


def _hot_trace(cfg, pref, n, seed=3, gen=6):
    rng = np.random.RandomState(seed)
    return [Arrival(0.0, np.concatenate(
        [pref, rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)]),
        gen) for _ in range(n)]


class TestCacheDirectory:
    def test_steering_routes_to_owner(self, tiny, tmp_path):
        """A hot prefix's owner takes the repeat wave as 'directory'
        dispatches — never a silent least-loaded miss to the other
        replica — and the journaled candidate ranking carries each
        replica's directory-hit rows + tier."""
        cfg, params = tiny
        router = _fleet(cfg, params)
        rng = np.random.RandomState(11)
        pref = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        router.serve(_hot_trace(cfg, pref, 4))     # wave 1: populate
        owner = next(r for r in router._replicas
                     if r.prefix_cache.stats()["entries"] > 0)
        j = journal.Journal(str(tmp_path))
        with journal.attach(j):
            rep = router.serve(_hot_trace(cfg, pref, 4, seed=5))
        j.close()
        assert rep.dispatches_directory > 0
        assert rep.directory["hits"] > 0
        # every steered request landed on the factual owner
        for r in router._replicas:
            if r.idx != owner.idx:
                assert r.dispatches["directory"] == 0
        recs = journal.read_journal(str(tmp_path))["records"]
        cands = [r["candidates"] for r in recs if r["kind"] == "dispatch"
                 and r.get("candidates")]
        assert cands
        steered = [c for cl in cands for c in cl if c["dir_hit"] > 0]
        assert steered and all(c["dir_tier"] in ("hbm", "clean", "host")
                               for c in steered)
        assert router.leak_report() == []

    def test_migration_on_miss_imports(self, tiny):
        """Owner unhealthy -> the fallback replica IMPORTS the host
        bytes and serves the prefix from its own restored pages instead
        of recomputing the prefill."""
        cfg, params = tiny
        router = _fleet(cfg, params)
        rng = np.random.RandomState(17)
        pref = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        router.serve(_hot_trace(cfg, pref, 4, seed=19))
        owner = next(r for r in router._replicas
                     if r.prefix_cache.stats()["entries"] > 0)
        assert owner.prefix_cache.host_tier.stages > 0  # staged = portable
        owner.set_health("suspect")
        rep = router.serve(_hot_trace(cfg, pref, 3, seed=29))
        other = router._replicas[1 - owner.idx]
        assert rep.tier_migrations > 0
        assert other.prefix_cache.host_tier.imports > 0
        assert other.prefix_cache.restores > 0          # import then restore
        assert other.prefix_cache.hits > 0              # NOT recomputed
        owner.set_health("healthy")
        assert router.leak_report() == []

    def test_fleet_loop_sync_audit_with_tiers(self, tiny):
        """The tiered FLEET loop: flagged == [], allowed == primary
        segment fetches exactly (stage gathers ride them)."""
        from paddle_tpu.analysis import SyncAudit

        cfg, params = tiny
        router = _fleet(cfg, params)
        rng = np.random.RandomState(31)
        pref = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        arr = _hot_trace(cfg, pref, 6, seed=37)
        router.serve(arr)                     # warm
        router.reset()
        with SyncAudit() as audit:
            audit.phase = "serve"
            rep = router.serve(arr)
        assert audit.flagged("serve") == [], audit.flagged("serve")
        assert audit.allowed("serve") == {
            "serving.segment_event_fetch": rep.segments}

    def test_healthz_and_capacity_tiers_breakdown(self, tiny):
        """The operator satellite: /healthz pages gain the tier split
        and /capacity per-replica sections carry tier stats + the
        directory's state."""
        import json as _json
        import urllib.request

        from paddle_tpu.observability import OpsServer

        cfg, params = tiny
        router = _fleet(cfg, params)
        rng = np.random.RandomState(41)
        pref = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        router.serve(_hot_trace(cfg, pref, 4, seed=43))
        with OpsServer(port=0, fleet=router) as srv:
            with urllib.request.urlopen(srv.url + "/healthz",
                                        timeout=5) as r:
                health = _json.loads(r.read())
            with urllib.request.urlopen(srv.url + "/capacity",
                                        timeout=5) as r:
                capacity = _json.loads(r.read())
        for idx in ("0", "1"):
            t = health["pages"][idx]["tiers"]
            assert set(t) >= {"host_pages", "spills", "restores",
                              "imports", "bytes_staged", "bytes_restored"}
            assert "tiers" in capacity["replicas"][idx]
        assert capacity["directory"]["entries"] >= 1


# ---------------------------------------------------------------------------
# unit mechanics: HostTier + the ambient TierMeter
# ---------------------------------------------------------------------------


class TestHostTierUnit:
    def test_stage_flush_restore_mechanics(self, tiny):
        cfg, params = tiny
        eng, pc = _mk(cfg, params, num_pages=21)
        tier = pc.host_tier
        pgr = eng.pager
        rng = np.random.RandomState(47)
        toks = rng.randint(0, cfg.vocab_size, (32,)).astype(np.int32)
        pages, _ = pgr.reserve(32)            # a fake live span
        pc.insert(toks, pages)                # queues the stage
        assert tier.stats()["pending_stages"] == 1
        tier.flush()                          # out-of-loop materialise
        assert tier.has(toks.tobytes()) and tier.pages_host == 2
        assert tier.bytes_to_host == 2 * page_bytes(pgr)
        pgr.release_pages(pages)              # span retires
        pc.evict_until(pgr.num_pages)         # -> spill (clean)
        assert pc.spills == 1 and pc.pages_held == 0
        m = pc.match(np.concatenate(
            [toks, rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)]))
        assert m is not None and m.tier == "host" and m.pages == []
        restored = pc.restore(m.key, m.length)
        assert restored and len(restored) == m.length // 16
        assert pc.restores == 1 and tier.bytes_to_hbm > 0
        pc.clear()
        assert pgr.leak_report() == []

    def test_host_capacity_bounds_and_validation(self, tiny):
        cfg, params = tiny
        eng, _ = _mk(cfg, params, tiered=False, num_pages=21)
        with pytest.raises(ValueError, match="capacity_pages"):
            HostTier(eng.pager, capacity_pages=0)
        tier = HostTier(eng.pager, capacity_pages=3)
        rng = np.random.RandomState(53)
        for i in range(3):
            k = np.zeros((cfg.num_layers, 2, 16, cfg.num_kv_heads,
                          cfg.head_dim), np.float32)
            tier.note_import(f"k{i}".encode(), {"k": k, "v": k}, 2)
        assert tier.pages_host <= 3 + 2       # LRU dropped the oldest
        assert tier.host_evictions >= 1

    def test_tier_meter_ambient_install(self, tiny):
        """--tiers on|off substrate: the meter observes segments + tier
        pool events ambiently and detaches clean."""
        from paddle_tpu.inference import kv_tiers, paged_kv, serving

        cfg, params = tiny
        meter = TierMeter()
        kv_tiers.install(meter)
        kv_tiers.install(meter)               # idempotent
        try:
            eng, pc = _mk(cfg, params)
            sch = OnlineScheduler(eng, seg_steps=12, prefix_cache=pc)
            sch.serve(_tenant_trace(cfg, seed=59, tenants=2, rounds=2))
        finally:
            kv_tiers.uninstall(meter)
        assert meter.segments >= 1
        assert meter.events.get("tier_stage", 0) >= 1
        assert meter.on_pool not in paged_kv.POOL_HOOKS
        assert meter.on_segment not in serving.SEGMENT_HOOKS

    def test_gate_bit_identity_tiers_on_off(self):
        """Budgets bit-identical with the tier meter ambient-attached
        (--tiers on|off), pinned on the paged canonical program."""
        from paddle_tpu.analysis import auditor, budgets, programs
        from paddle_tpu.inference import kv_tiers

        handle = programs.build("paged_serving_segment")

        def audit(attach):
            meter = TierMeter() if attach else None
            if meter is not None:
                kv_tiers.install(meter)
            try:
                return auditor.audit_replay("paged_serving_segment",
                                            handle.replay, replays=2)
            finally:
                if meter is not None:
                    kv_tiers.uninstall(meter)

        rep_on = audit(True)
        rep_off = audit(False)
        rep_on.merge(auditor.audit_static(
            "paged_serving_segment", handle.hlo(),
            donation_threshold=handle.donation_threshold,
            expected_undonated=handle.expected_undonated))
        assert budgets.check(rep_on) == [], rep_on.format()
        for key in ("host_syncs_flagged", "host_syncs_allowed",
                    "warm_compiles"):
            assert rep_on.metrics[key] == rep_off.metrics[key], (
                key, rep_on.metrics[key], rep_off.metrics[key])

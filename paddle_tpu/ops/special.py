"""Special-function / statistics ops rounding out the corpus.

Reference counterparts: ``paddle.bincount``/``histogram`` (phi kernels
``paddle/phi/kernels/cpu|gpu/bincount_kernel.*``, ``histogram_kernel.*``),
``paddle.cross``, ``paddle.cdist``/``dist``, ``paddle.renorm``,
``paddle.i0/i0e/i1/i1e``, ``paddle.polygamma``, ``paddle.poisson``
(SURVEY.md §2.1 PHI kernel corpus).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..framework.random import next_key
from .dispatch import run_op
from .registry import register_op

__all__ = [
    "bincount", "histogram", "histogramdd", "cross", "cdist", "dist",
    "pdist", "renorm", "i0", "i0e", "i1", "i1e", "polygamma", "poisson",
]


@register_op(differentiable=False)
def bincount(x, weights=None, minlength=0, name=None) -> Tensor:
    xv = x._value
    # jnp.bincount needs a static length: use minlength or the data max
    # (concrete here — eager op, not traced).
    length = max(int(minlength), int(jnp.max(xv)) + 1 if xv.size else 0)
    w = weights._value if isinstance(weights, Tensor) else weights
    return to_tensor(jnp.bincount(xv.reshape(-1), weights=None if w is None
                                  else w.reshape(-1), length=length))


@register_op(differentiable=False)
def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None) -> Tensor:
    xv = input._value.reshape(-1).astype(jnp.float32)
    if min == 0 and max == 0:
        lo, hi = jnp.min(xv), jnp.max(xv)
        lo, hi = jnp.where(lo == hi, lo - 0.5, lo), jnp.where(lo == hi, hi + 0.5, hi)
    else:
        lo, hi = jnp.float32(min), jnp.float32(max)
    w = weight._value.reshape(-1) if isinstance(weight, Tensor) else weight
    hist, _ = jnp.histogram(xv, bins=bins, range=(lo, hi), weights=w,
                            density=density)
    return to_tensor(hist)


@register_op(differentiable=False)
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xv = x._value.astype(jnp.float32)
    w = weights._value if isinstance(weights, Tensor) else weights
    if isinstance(bins, (list, tuple)) and len(bins) and isinstance(
            bins[0], Tensor):
        bins = [b._value for b in bins]
    hist, edges = jnp.histogramdd(xv, bins=bins, range=ranges, weights=w,
                                  density=density)
    return to_tensor(hist), [to_tensor(e) for e in edges]


@register_op()
def cross(x, y, axis=9, name=None) -> Tensor:
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis of size 3
            ax = next(i for i, d in enumerate(a.shape) if d == 3)
        return jnp.cross(a, b, axis=ax)
    return run_op("cross", f, x, y)


@register_op()
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None) -> Tensor:
    """Pairwise p-norm distance [..., P, M] x [..., R, M] -> [..., P, R].
    Euclidean case uses the matmul expansion (MXU-friendly) like the
    reference's use_mm_for_euclid_dist mode."""
    def f(a, b):
        if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
            a2 = jnp.sum(a * a, -1)[..., :, None]
            b2 = jnp.sum(b * b, -1)[..., None, :]
            sq = a2 + b2 - 2.0 * (a @ jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(sq, 0.0))
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return jnp.sum(diff != 0, -1).astype(a.dtype)
        if jnp.isinf(p):
            return jnp.max(diff, -1)
        return jnp.sum(diff ** p, -1) ** (1.0 / p)
    return run_op("cdist", f, x, y)


@register_op()
def pdist(x, p=2.0, name=None) -> Tensor:
    """Condensed pairwise distance of the rows of a [N, M] matrix: the
    N*(N-1)/2 upper-triangle entries of ``cdist(x, x, p)`` in row-major
    (i < j) order (reference: ``paddle.pdist``). Pair indices are static
    (N is a trace-time shape), so the gather lowers to one XLA take."""
    import numpy as np

    def f(a):
        n = a.shape[0]
        iu, ju = np.triu_indices(n, k=1)
        diff = jnp.abs(a[iu] - a[ju])           # [n(n-1)/2, M]
        pf = float(p)
        if pf == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, -1))
        if pf == 0:
            return jnp.sum(diff != 0, -1).astype(a.dtype)
        if np.isinf(pf):
            return jnp.max(diff, -1)
        return jnp.sum(diff ** pf, -1) ** (1.0 / pf)

    return run_op("pdist", f, x)


@register_op()
def dist(x, y, p=2, name=None) -> Tensor:
    def f(a, b):
        d = jnp.abs(a - b).reshape(-1)
        pf = float(p)
        if pf == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if jnp.isinf(pf):
            return jnp.max(d)
        return jnp.sum(d ** pf) ** (1.0 / pf)
    return run_op("dist", f, x, y)


@register_op()
def renorm(x, p, axis, max_norm, name=None) -> Tensor:
    """Renormalise sub-tensors along ``axis`` whose p-norm exceeds
    ``max_norm`` (reference ``paddle.renorm``)."""
    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return run_op("renorm", f, x)


@register_op()
def i0(x, name=None) -> Tensor:
    return run_op("i0", lambda a: jax.scipy.special.i0(a), x)


@register_op()
def i0e(x, name=None) -> Tensor:
    return run_op("i0e", lambda a: jax.scipy.special.i0e(a), x)


@register_op()
def i1(x, name=None) -> Tensor:
    return run_op("i1", lambda a: jax.scipy.special.i1(a), x)


@register_op()
def i1e(x, name=None) -> Tensor:
    return run_op("i1e", lambda a: jax.scipy.special.i1e(a), x)


@register_op()
def polygamma(x, n, name=None) -> Tensor:
    if n == 0:
        return run_op("polygamma", lambda a: jax.scipy.special.digamma(a), x)
    return run_op("polygamma",
                  lambda a: jax.scipy.special.polygamma(n, a), x)


@register_op(differentiable=False)
def poisson(x, name=None) -> Tensor:
    return to_tensor(
        jax.random.poisson(next_key(), x._value).astype(x._value.dtype))

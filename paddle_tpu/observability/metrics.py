"""Process-wide metrics registry — counters, gauges, fixed-bucket
histograms — with lock-cheap hot-path recording and a zero-EXTRA-sync
contract.

Design center (ISSUE 5): every number an operator could scrape already
exists on the host — the serving scheduler's event-log replay, AMP's
fused finite check, the DataLoader's queue bookkeeping all work on host
mirrors fetched at the two sanctioned ``allowed_sync`` points. The
metrics layer therefore accepts **host scalars only**: handing it a
device value (a ``jax.Array`` or framework ``Tensor``) raises instead of
silently forcing a device→host sync that the program auditor would then
flag. ``python -m paddle_tpu.analysis --gate`` runs with telemetry
enabled and the per-program sync/compile/relayout budgets must be
bit-identical to the uninstrumented programs — recording is pure python
arithmetic on values a sanctioned sync already delivered.

Hot-path cost: one module-flag branch + one float add (counters) or one
``bisect`` (histograms). No locks on the record path — metric CREATION
takes the registry lock once; recording relies on the GIL the same way
``profiler._hooks`` does (single-writer per metric in practice; a lost
update under true free-threading costs one sample, never a crash).

Multi-process runs merge **snapshots**, not live objects: each rank
writes ``write_snapshot(log_dir)`` (rank-tagged JSON, the launcher
log-dir aggregation path) and ``merge_log_dir``/``merge_snapshots``
reduce them — counters and histogram buckets sum, gauges keep a
per-rank map plus min/max/sum aggregates. Export is Prometheus text
(``render_prometheus``) or JSON (``snapshot``).
"""

from __future__ import annotations

import bisect
import contextlib
import json
import math
import os
import threading
from typing import Dict, List, Optional, Sequence

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "registry",
    "counter", "gauge", "histogram", "percentile", "snapshot",
    "render_prometheus", "merge_snapshots", "write_snapshot",
    "merge_log_dir", "set_enabled", "enabled", "reset",
    "scoped_registry", "LATENCY_BUCKETS_S",
]


class _State:
    enabled = True


_STATE = _State()


def set_enabled(on: bool) -> bool:
    """Toggle all recording (counters/gauges/histograms become no-ops).
    Returns the previous state so callers can restore it."""
    prev = _STATE.enabled
    _STATE.enabled = bool(on)
    return prev


def enabled() -> bool:
    return _STATE.enabled


# Default latency bucket ladder: ~1 ms .. 64 s in powers of two — wide
# enough for TTFT on a tunneled dispatch path AND e2e on long batches.
LATENCY_BUCKETS_S = tuple(0.001 * 2 ** i for i in range(17))


def _host_scalar(v) -> float:
    """Coerce a HOST value to float; refuse device values.

    The zero-extra-sync contract: ``float()`` on a ``jax.Array`` or a
    framework ``Tensor`` is a blocking device→host sync — exactly the
    hazard class ``analysis.syncs`` exists to catch. Telemetry must
    consume values an existing sanctioned sync already delivered, so
    anything device-resident is a caller bug, reported eagerly."""
    t = type(v)
    if t is float or t is int or t is bool:
        return float(v)
    # framework Tensor (has _value) or jax array (has addressable_shards):
    # both would sync on coercion — refuse instead of flagging later
    if hasattr(v, "addressable_shards") or hasattr(v, "_value"):
        raise TypeError(
            f"telemetry records host scalars only, got {t.__name__}: "
            f"fetch the value at an allowed_sync point first "
            f"(zero-extra-sync contract, see paddle_tpu/observability)")
    return float(v)  # numpy scalars and other host number types


class Counter:
    """Monotonic count (admissions, backpressure drops, cache hits)."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if _STATE.enabled:
            self.value += _host_scalar(n)

    def _reset(self) -> None:
        self.value = 0.0

    def _snap(self) -> dict:
        return {"value": self.value}


class Gauge:
    """Last-observed level (queue depth, slot occupancy, MFU)."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v: float) -> None:
        if _STATE.enabled:
            self.value = _host_scalar(v)

    def _reset(self) -> None:
        self.value = 0.0

    def _snap(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Fixed-bucket histogram (TTFT, e2e latency, step time).

    ``buckets`` are ascending upper bounds; an implicit +inf bucket
    catches the tail. ``observe`` is one bisect + two adds. ``quantile``
    estimates by linear interpolation inside the covering bucket —
    resolution is the bucket width (tests pin it against numpy); use
    ``percentile`` for exact small-population percentiles."""

    __slots__ = ("name", "help", "buckets", "counts", "sum", "count",
                 "min", "max")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        self.name = name
        self.help = help
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be strictly ascending: "
                             f"{buckets}")
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +inf tail
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        if not _STATE.enabled:
            return
        v = _host_scalar(v)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1) by in-bucket linear
        interpolation, clamped to the observed [min, max]."""
        if not self.count:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= rank and c:
                lo = self.buckets[i - 1] if i > 0 else min(self.min, 0.0)
                hi = (self.buckets[i] if i < len(self.buckets)
                      else max(self.max, lo))
                frac = (rank - seen) / c
                est = lo + frac * (hi - lo)
                return min(max(est, self.min), self.max)
            seen += c
        return self.max

    def _reset(self) -> None:
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def _snap(self) -> dict:
        return {"buckets": list(self.buckets), "counts": list(self.counts),
                "sum": self.sum, "count": self.count,
                "min": (None if self.count == 0 else self.min),
                "max": (None if self.count == 0 else self.max)}


def percentile(xs: Sequence[float], q: float) -> float:
    """Exact nearest-rank percentile over a full sample list — THE rule
    ``OnlineReport`` has always used (r7), now the single shared copy:
    sorted ``xs``, index ``min(len-1, int(len*q))``, 0.0 when empty.
    Kept bit-identical to the scheduler's historical ``_pctl`` so every
    published SERVING artifact percentile stays reproducible."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * q))]


class Registry:
    """Name → metric map. One process-wide default (``registry()``);
    tests build private instances to simulate ranks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, cls, name: str, help: str, **kw):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, not {cls.__name__}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def reset(self) -> None:
        """Zero every metric IN PLACE — handles cached by hot paths stay
        registered (clearing the dict would orphan them)."""
        for m in self._metrics.values():
            m._reset()

    # -- export -------------------------------------------------------------
    def snapshot(self, rank: Optional[int] = None) -> dict:
        if rank is None:
            rank = _default_rank()
        snap = {"rank": rank, "counters": {}, "gauges": {},
                "histograms": {}}
        for name, m in sorted(self._metrics.items()):
            kind = ("counters" if isinstance(m, Counter) else
                    "gauges" if isinstance(m, Gauge) else "histograms")
            snap[kind][name] = m._snap()
        return snap

    def render_prometheus(self) -> str:
        """Prometheus text exposition (the scrape format).

        r17 conformance (ISSUE 12 satellite): metric names carrying a
        bracket tag — the ``request.ttft[class0]`` / ``[req12]`` /
        ``slo.burn_rate[class1]`` per-entity convention — used to leak
        the brackets into the exposition name, which real collectors
        REJECT (``[`` is not a legal name character). The tag now
        renders as a proper label (``request_ttft_bucket{class="0",
        le="0.001"}``), label VALUES are escaped per the spec
        (backslash, double-quote, newline), remaining illegal name
        characters sanitise to ``_``, series sharing a family emit ONE
        ``# TYPE`` line, and histogram ``_bucket`` counts stay
        cumulative with the ``+Inf`` terminator. A parity test against
        a hand-written exposition sample pins the format
        (tests/test_observability.py)."""
        families: Dict[str, dict] = {}
        order: List[str] = []
        for name, m in sorted(self._metrics.items()):
            pname, labels = _prom_name(name)
            kind = ("counter" if isinstance(m, Counter) else
                    "gauge" if isinstance(m, Gauge) else "histogram")
            fam = families.get(pname)
            if fam is None:
                fam = families[pname] = {"kind": kind, "help": m.help,
                                         "series": []}
                order.append(pname)
            fam["help"] = fam["help"] or m.help
            fam["series"].append((labels, m))
        lines: List[str] = []
        for pname in order:
            fam = families[pname]
            if fam["help"]:
                lines.append(f"# HELP {pname} {fam['help']}")
            lines.append(f"# TYPE {pname} {fam['kind']}")
            for labels, m in fam["series"]:
                lab = _prom_labels(labels)
                if isinstance(m, Counter):
                    lines.append(f"{pname}_total{lab} {_fmt(m.value)}")
                elif isinstance(m, Gauge):
                    lines.append(f"{pname}{lab} {_fmt(m.value)}")
                else:
                    cum = 0
                    for b, c in zip(m.buckets, m.counts):
                        cum += c
                        lines.append(f"{pname}_bucket" + _prom_labels(
                            labels + [("le", _fmt(b))]) + f" {cum}")
                    cum += m.counts[-1]
                    lines.append(f"{pname}_bucket" + _prom_labels(
                        labels + [("le", "+Inf")]) + f" {cum}")
                    lines.append(f"{pname}_sum{lab} {_fmt(m.sum)}")
                    lines.append(f"{pname}_count{lab} {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


# --- Prometheus name/label conformance (r17, ISSUE 12 satellite) ----------

import re as _re

_PROM_BAD = _re.compile(r"[^a-zA-Z0-9_:]")
_PROM_TAG = _re.compile(r"^(.*)\[([^\[\]]+)\]$")
# `class0` / `req12` / `cls3` — an alpha key fused to a numeric value
_PROM_KEYVAL = _re.compile(r"^([A-Za-z_]+?)(\d+)$")


def _prom_name(name: str):
    """Split a registry metric name into (exposition_name, labels).

    The registry convention suffixes per-entity series with a bracket
    tag (``request.ttft[class0]``, ``perf.mfu[decode_tick]``). The tag
    becomes a label: alpha+digits tags split into key/value
    (``class0`` → ``class="0"``), anything else lands under the
    generic ``tag`` key. Dots map to underscores and any remaining
    illegal character sanitises to ``_``."""
    labels: List[tuple] = []
    m = _PROM_TAG.match(name)
    if m:
        name, tag = m.group(1), m.group(2)
        kv = _PROM_KEYVAL.match(tag)
        if kv:
            labels.append((kv.group(1), kv.group(2)))
        else:
            labels.append(("tag", tag))
    return _PROM_BAD.sub("_", name.replace(".", "_")), labels


def _prom_escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _default_rank() -> int:
    try:
        from ..distributed import env as _env

        return _env.get_rank() if _env.is_initialized() else 0
    except Exception:
        return 0


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


@contextlib.contextmanager
def scoped_registry(reg: Registry):
    """Route module-level recording (``counter``/``gauge``/``histogram``)
    into ``reg`` for the duration of the block.

    The fleet router's replica-isolation hook (r12): N engine replicas
    share one process, but their telemetry must stay per-replica so the
    rank-tagged snapshot/merge machinery (``write_snapshot`` with
    ``rank=replica``, ``merge_log_dir``) reduces them exactly like a
    multi-process launcher run. The router wraps each replica's segment
    dispatch/finish in its registry; record paths resolve metrics at
    call time, so hot-path cost is unchanged (one dict lookup). NOT
    thread-safe across concurrent scopes — the serve loop is single-
    threaded by design (device overlap comes from async dispatch, not
    host threads)."""
    global _REGISTRY
    prev = _REGISTRY
    _REGISTRY = reg
    try:
        yield reg
    finally:
        _REGISTRY = prev


def counter(name: str, help: str = "") -> Counter:
    return _REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = LATENCY_BUCKETS_S) -> Histogram:
    return _REGISTRY.histogram(name, help, buckets=buckets)


def snapshot(rank: Optional[int] = None,
             registry: Optional[Registry] = None) -> dict:
    return (registry or _REGISTRY).snapshot(rank=rank)


def render_prometheus() -> str:
    return _REGISTRY.render_prometheus()


def reset() -> None:
    _REGISTRY.reset()


# ---------------------------------------------------------------------------
# Rank merge: snapshots are plain dicts so the reduction is pure host
# data-plumbing — over the launcher's shared log dir (each rank writes its
# own file; any reader merges) or over snapshots gathered by the existing
# gloo/object-collective path.
# ---------------------------------------------------------------------------


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Reduce rank-tagged snapshots: counters and histogram bucket counts
    SUM (they are extensive quantities), gauges keep the per-rank levels
    plus min/max/sum (a level does not sum meaningfully across ranks)."""
    merged = {"ranks": sorted(int(s.get("rank", 0)) for s in snaps),
              "counters": {}, "gauges": {}, "histograms": {}}
    for s in snaps:
        rank = int(s.get("rank", 0))
        for name, c in s.get("counters", {}).items():
            e = merged["counters"].setdefault(name, {"value": 0.0})
            e["value"] += c["value"]
        for name, g in s.get("gauges", {}).items():
            e = merged["gauges"].setdefault(
                name, {"by_rank": {}, "min": math.inf, "max": -math.inf,
                       "sum": 0.0})
            v = g["value"]
            e["by_rank"][str(rank)] = v
            e["min"] = min(e["min"], v)
            e["max"] = max(e["max"], v)
            e["sum"] += v
        for name, h in s.get("histograms", {}).items():
            e = merged["histograms"].get(name)
            if e is None:
                merged["histograms"][name] = {
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]), "sum": h["sum"],
                    "count": h["count"], "min": h["min"], "max": h["max"]}
                continue
            if e["buckets"] != list(h["buckets"]):
                raise ValueError(
                    f"histogram {name!r}: rank bucket ladders differ — "
                    f"ranks must share one metric definition")
            e["counts"] = [a + b for a, b in zip(e["counts"], h["counts"])]
            e["sum"] += h["sum"]
            e["count"] += h["count"]
            for k, pick in (("min", min), ("max", max)):
                if h[k] is not None:
                    e[k] = h[k] if e[k] is None else pick(e[k], h[k])
    return merged


def write_snapshot(log_dir: str, rank: Optional[int] = None,
                   registry: Optional[Registry] = None) -> str:
    """Write a rank-tagged snapshot into the launcher's shared log dir
    (``telemetry_rank<r>.json``); returns the path. ``registry`` lets a
    single-process fleet write one file per replica registry (rank =
    replica index) so ``merge_log_dir`` reduces replicas exactly like
    launcher ranks."""
    if rank is None:
        rank = _default_rank()
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, f"telemetry_rank{rank}.json")
    with open(path, "w") as f:
        json.dump(snapshot(rank=rank, registry=registry), f, indent=1)
    return path


def merge_log_dir(log_dir: str) -> dict:
    """Merge every ``telemetry_rank*.json`` under ``log_dir`` — the
    multi-process reduction for launcher runs (no collective needed).

    Robustness (r14, ISSUE 9 satellite): a replica killed mid-snapshot
    — reachable since the r13 failover path writes snapshots around
    replica deaths — leaves a truncated/empty rank file. The merge used
    to raise on it, taking down the SURVIVORS' report exactly when an
    operator needs it most; now a malformed file is skipped and
    flagged: counted in ``telemetry.merge_skipped_files``, recorded as
    a ``merge_skipped`` flight event, and listed under the merged
    dict's ``"skipped_files"`` key so the gap is visible, not silent.
    Only a dir with NO readable snapshot still raises."""
    import glob

    from . import flight as _flight

    snaps = []
    skipped: List[str] = []
    for p in sorted(glob.glob(os.path.join(log_dir,
                                           "telemetry_rank*.json"))):
        try:
            with open(p) as f:
                snap = json.load(f)
            if not isinstance(snap, dict):
                raise ValueError(f"snapshot is {type(snap).__name__}, "
                                 f"not an object")
            snaps.append(snap)
        except (json.JSONDecodeError, ValueError, OSError) as e:
            skipped.append(os.path.basename(p))
            counter("telemetry.merge_skipped_files",
                    "rank snapshots skipped as truncated/corrupt").inc()
            _flight.record("merge_skipped", file=os.path.basename(p),
                           error=f"{type(e).__name__}: {e}")
    if not snaps:
        raise FileNotFoundError(
            f"no readable telemetry_rank*.json under {log_dir}"
            + (f" ({len(skipped)} skipped as corrupt)" if skipped else ""))
    merged = merge_snapshots(snaps)
    if skipped:
        merged["skipped_files"] = skipped
    return merged
